// Host-side fused Adam/AdamW for offloaded optimizer states.
//
// TPU-native equivalent of the reference's csrc/adam/cpu_adam.cpp +
// cpu_adam_impl.cpp + includes/simd.h (SURVEY.md §2.2 "CPU Adam/AdamW"):
// the ZeRO-Offload optimizer step runs on the TPU-VM host over fp32 master
// params + moments while the chips hold bf16 working copies.  The reference
// hand-writes AVX256/AVX512 intrinsics; here the inner loops are written so
// the compiler's autovectorizer emits the same code (-O3 -march=native,
// verified contiguous, no aliasing), with OpenMP-style threading replaced by
// caller-side sharding (the Python wrapper splits work across a thread pool).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

// One Adam step over a contiguous fp32 span.
// mode: 0 = Adam (L2 as grad decay), 1 = AdamW (decoupled decay).
void ds_adam_step(int64_t n,
                  float* __restrict__ param,
                  const float* __restrict__ grad,
                  float* __restrict__ exp_avg,
                  float* __restrict__ exp_avg_sq,
                  int64_t step,
                  float lr, float beta1, float beta2, float eps,
                  float weight_decay, int adamw_mode) {
    const float bc1 = 1.0f - std::pow(beta1, (float)step);
    const float bc2 = 1.0f - std::pow(beta2, (float)step);
    const float step_size = lr / bc1;
    const float bc2_sqrt = std::sqrt(bc2);
    const float decay = weight_decay;
    if (adamw_mode) {
        const float w_scale = 1.0f - lr * decay;
        for (int64_t i = 0; i < n; ++i) {
            const float g = grad[i];
            const float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
            const float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
            exp_avg[i] = m;
            exp_avg_sq[i] = v;
            const float denom = std::sqrt(v) / bc2_sqrt + eps;
            param[i] = param[i] * w_scale - step_size * (m / denom);
        }
    } else {
        for (int64_t i = 0; i < n; ++i) {
            float g = grad[i];
            if (decay != 0.0f) g += decay * param[i];
            const float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
            const float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
            exp_avg[i] = m;
            exp_avg_sq[i] = v;
            const float denom = std::sqrt(v) / bc2_sqrt + eps;
            param[i] -= step_size * (m / denom);
        }
    }
}

// Same step, but gradients arrive in bf16 (as uint16 view) and a bf16 working
// copy of the params is produced alongside the fp32 master update — the
// layout the offload engine uses (bf16 on-chip copy, fp32 master on host).
void ds_adam_step_bf16g(int64_t n,
                        float* __restrict__ param,
                        const uint16_t* __restrict__ grad_bf16,
                        uint16_t* __restrict__ param_bf16_out,
                        float* __restrict__ exp_avg,
                        float* __restrict__ exp_avg_sq,
                        int64_t step,
                        float lr, float beta1, float beta2, float eps,
                        float weight_decay, int adamw_mode) {
    const float bc1 = 1.0f - std::pow(beta1, (float)step);
    const float bc2 = 1.0f - std::pow(beta2, (float)step);
    const float step_size = lr / bc1;
    const float bc2_sqrt = std::sqrt(bc2);
    for (int64_t i = 0; i < n; ++i) {
        uint32_t gbits = ((uint32_t)grad_bf16[i]) << 16;
        float g;
        std::memcpy(&g, &gbits, 4);
        float p = param[i];
        if (adamw_mode) {
            p *= (1.0f - lr * weight_decay);
        } else if (weight_decay != 0.0f) {
            g += weight_decay * p;
        }
        const float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
        const float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        const float denom = std::sqrt(v) / bc2_sqrt + eps;
        p -= step_size * (m / denom);
        param[i] = p;
        // round-to-nearest-even bf16
        uint32_t pbits;
        std::memcpy(&pbits, &p, 4);
        uint32_t rounding = 0x7FFF + ((pbits >> 16) & 1);
        param_bf16_out[i] = (uint16_t)((pbits + rounding) >> 16);
    }
}

// Adagrad (reference csrc/adagrad/cpu_adagrad.cpp).
void ds_adagrad_step(int64_t n, float* __restrict__ param,
                     const float* __restrict__ grad,
                     float* __restrict__ exp_avg_sq,
                     float lr, float eps, float weight_decay) {
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i];
        if (weight_decay != 0.0f) g += weight_decay * param[i];
        const float v = exp_avg_sq[i] + g * g;
        exp_avg_sq[i] = v;
        param[i] -= lr * g / (std::sqrt(v) + eps);
    }
}

// Lion (reference csrc/lion/cpu_lion.cpp).
void ds_lion_step(int64_t n, float* __restrict__ param,
                  const float* __restrict__ grad,
                  float* __restrict__ exp_avg,
                  float lr, float beta1, float beta2, float weight_decay) {
    for (int64_t i = 0; i < n; ++i) {
        const float g = grad[i];
        const float m = exp_avg[i];
        const float c = beta1 * m + (1.0f - beta1) * g;
        const float sign = (c > 0.0f) ? 1.0f : ((c < 0.0f) ? -1.0f : 0.0f);
        param[i] = param[i] * (1.0f - lr * weight_decay) - lr * sign;
        exp_avg[i] = beta2 * m + (1.0f - beta2) * g;
    }
}

}  // extern "C"
