// Async file I/O library for NVMe tiering (ZeRO-Offload / ZeRO-Infinity).
//
// TPU-native equivalent of the reference's csrc/aio/ (deepspeed_py_aio_handle,
// deepspeed_aio_common; SURVEY.md §2.2 "Async I/O (NVMe)"): an aio_handle
// with submit/wait semantics backed by a worker thread pool doing
// pread/pwrite — optionally O_DIRECT with aligned buffers, like the
// reference's libaio path.  Thread-pool blocking I/O is the portable
// equivalent of libaio/io_uring and saturates NVMe at queue_depth × threads
// for the large sequential blocks the swapper issues.
//
// Plain C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Request {
    int op;  // 0 = read, 1 = write
    std::string path;
    void* buffer;
    int64_t nbytes;
    int64_t offset;
};

struct Handle {
    int block_size;
    int queue_depth;
    bool single_submit;
    bool overlap_events;
    int num_threads;
    bool use_direct;

    std::vector<std::thread> workers;
    std::deque<Request> queue;
    std::mutex mu;
    std::condition_variable cv;
    std::condition_variable done_cv;
    std::atomic<int64_t> inflight{0};
    std::atomic<int64_t> errors{0};
    bool stop = false;

    void worker() {
        for (;;) {
            Request req;
            {
                std::unique_lock<std::mutex> lk(mu);
                cv.wait(lk, [&] { return stop || !queue.empty(); });
                if (stop && queue.empty()) return;
                req = std::move(queue.front());
                queue.pop_front();
            }
            int flags = (req.op == 0) ? O_RDONLY : (O_WRONLY | O_CREAT);
#ifdef O_DIRECT
            if (use_direct) flags |= O_DIRECT;
#endif
            int fd = ::open(req.path.c_str(), flags, 0644);
            bool failed = fd < 0;
            if (!failed) {
                char* p = (char*)req.buffer;
                int64_t left = req.nbytes, off = req.offset;
                while (left > 0) {
                    ssize_t r = (req.op == 0) ? ::pread(fd, p, left, off)
                                              : ::pwrite(fd, p, left, off);
                    if (r <= 0) { failed = true; break; }
                    p += r; off += r; left -= r;
                }
                ::close(fd);
            }
            if (failed) errors.fetch_add(1);
            {
                // The lock orders this decrement with ds_aio_wait's
                // inflight==0 predicate check: without it the waiter can see
                // inflight!=0, the worker then decrements to 0 and notifies
                // before the waiter blocks, and the waiter sleeps forever
                // (lost wakeup).
                std::lock_guard<std::mutex> lk(mu);
                if (inflight.fetch_sub(1) == 1) done_cv.notify_all();
            }
        }
    }
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int block_size, int queue_depth, int single_submit,
                        int overlap_events, int num_threads, int use_direct) {
    auto* h = new Handle();
    h->block_size = block_size > 0 ? block_size : (1 << 20);
    h->queue_depth = queue_depth > 0 ? queue_depth : 8;
    h->single_submit = single_submit != 0;
    h->overlap_events = overlap_events != 0;
    h->num_threads = num_threads > 0 ? num_threads : 1;
    h->use_direct = use_direct != 0;
    for (int i = 0; i < h->num_threads; ++i)
        h->workers.emplace_back([h] { h->worker(); });
    return h;
}

void ds_aio_handle_free(void* vh) {
    auto* h = (Handle*)vh;
    {
        std::lock_guard<std::mutex> lk(h->mu);
        h->stop = true;
    }
    h->cv.notify_all();
    for (auto& t : h->workers) t.join();
    delete h;
}

// Split [buffer, nbytes) into block_size chunks and enqueue them (async).
static void submit(Handle* h, int op, const char* path, void* buffer,
                   int64_t nbytes, int64_t file_offset) {
    int64_t chunk = h->block_size;
    {
        std::lock_guard<std::mutex> lk(h->mu);
        for (int64_t off = 0; off < nbytes; off += chunk) {
            int64_t len = (off + chunk <= nbytes) ? chunk : (nbytes - off);
            h->inflight.fetch_add(1);
            h->queue.push_back(Request{op, path, (char*)buffer + off, len,
                                       file_offset + off});
        }
    }
    h->cv.notify_all();
}

void ds_aio_pread_async(void* vh, const char* path, void* buffer,
                        int64_t nbytes, int64_t offset) {
    submit((Handle*)vh, 0, path, buffer, nbytes, offset);
}

void ds_aio_pwrite_async(void* vh, const char* path, void* buffer,
                         int64_t nbytes, int64_t offset) {
    submit((Handle*)vh, 1, path, buffer, nbytes, offset);
}

// Block until all submitted requests complete; returns error count since
// the last wait (0 == success).
int64_t ds_aio_wait(void* vh) {
    auto* h = (Handle*)vh;
    std::unique_lock<std::mutex> lk(h->mu);
    h->done_cv.wait(lk, [&] { return h->inflight.load() == 0; });
    return h->errors.exchange(0);
}

// Synchronous convenience (reference: deepspeed_py_aio sync entry points).
int64_t ds_aio_read(void* vh, const char* path, void* buffer, int64_t nbytes,
                    int64_t offset) {
    ds_aio_pread_async(vh, path, buffer, nbytes, offset);
    return ds_aio_wait(vh);
}

int64_t ds_aio_write(void* vh, const char* path, void* buffer, int64_t nbytes,
                     int64_t offset) {
    ds_aio_pwrite_async(vh, path, buffer, nbytes, offset);
    return ds_aio_wait(vh);
}

}  // extern "C"
