"""Fault-injection harness: the crash vocabulary behind the resilience
suite (docs/RESILIENCE.md).

The checkpoint atomicity contract is only worth anything if it is proved
against actual mid-write deaths, and a unit test cannot SIGKILL itself at
byte 1337 of a shard file.  This module supplies the equivalent faults as
injectable, deterministic primitives:

- :func:`crash_on_write` — "process dies at byte offset N of the save":
  patches ``builtins.open`` so matched files' writes cut off after a
  cumulative byte budget and raise :class:`InjectedFault`.  The partial
  prefix IS flushed to disk first, so the on-disk state equals what a
  kill at that offset leaves behind (no cleanup code runs — the save
  aborts mid-flight exactly like a death would, modulo OS page-cache
  durability, which the atomicity contract does not depend on).
- :func:`crash_before` — "process dies right before method M": the
  between-the-barriers probe (e.g. after all shards are written but
  before ``checkpoint_engine.commit``).
- :func:`truncate_file` / :func:`flip_bit` — post-save storage faults
  (torn tail, silent media corruption) that manifest verification must
  catch.
- :func:`fail_after_calls` — an exception out of the Nth call of any
  method ("exception mid-step").

Serving-fleet faults (the chaos-harness vocabulary behind
docs/RESILIENCE.md "Serving fleet"):

- :func:`crash_on_call` — raise out of exactly the Nth call, pass
  through before AND after: "the serving loop dies mid-trace, then the
  supervisor restarts it" needs the method working again post-kill,
  which :func:`fail_after_calls` (fails forever after N) cannot model.
- :func:`wedge_method` — the Nth call BLOCKS until released: a hung
  serving loop / wedged replica (alive, answering nothing) rather than
  a dead one.
- :func:`http_error_burst` — wrap a ``(payload) -> (status, body)``
  HTTP handler to answer 500 for its next N calls (inject 500s at the
  replica's ``/generate`` seam without touching the engine).
- :class:`ChaosProxy` — a runtime-switchable TCP proxy for the network
  fault vocabulary between a router and a replica: ``pass`` /
  ``refuse`` (connection dies at accept) / ``blackhole`` (accepts and
  never answers — the ambiguous client-side timeout) /
  ``deliver_then_reset`` (forwards the request, lets the replica DO the
  work, then tears the client connection down before the response — the
  ambiguous socket death that makes non-idempotent retries
  double-generate) / ``slow`` (drips bytes).

Process-level faults (SIGKILL between incarnations, SIGTERM grace
windows) are exercised by the supervisor tests via real subprocesses;
this module covers the intra-process byte-level vocabulary those cannot
aim precisely.

Stdlib-only; nothing here imports jax.
"""

from __future__ import annotations

import builtins
import os
import socket
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional

__all__ = ["InjectedFault", "crash_on_write", "crash_before",
           "fail_after_calls", "truncate_file", "flip_bit",
           "crash_on_call", "wedge_method", "http_error_burst",
           "gradient_bomb", "ChaosProxy"]


class InjectedFault(RuntimeError):
    """A deliberately injected failure (never raised by real code paths,
    so tests can assert on the type)."""


class _CrashingWriter:
    """File proxy that writes through until the shared byte budget is
    exhausted, flushes the partial prefix, and dies."""

    def __init__(self, fh, state: Dict[str, Any]):
        self._fh = fh
        self._state = state

    def write(self, data):
        n = len(data)
        room = self._state["budget"] - self._state["written"]
        if room <= 0:
            raise InjectedFault(
                f"injected crash at byte {self._state['budget']} of save")
        if n > room:
            self._fh.write(data[:room])
            self._fh.flush()
            self._state["written"] += room
            raise InjectedFault(
                f"injected crash at byte {self._state['budget']} of save "
                f"(mid-write of {getattr(self._fh, 'name', '?')})")
        self._state["written"] += n
        return self._fh.write(data)

    def __getattr__(self, name):
        return getattr(self._fh, name)

    def __enter__(self):
        self._fh.__enter__()
        return self

    def __exit__(self, *exc):
        return self._fh.__exit__(*exc)

    def __iter__(self):  # pragma: no cover - completeness
        return iter(self._fh)


@contextmanager
def crash_on_write(after_bytes: int, path_substr: str = "",
                   ) -> Iterator[Dict[str, Any]]:
    """Kill the next save at a chosen byte offset.

    Every file opened for writing whose path contains ``path_substr``
    shares one ``after_bytes`` budget; the write that crosses it flushes
    the in-budget prefix and raises :class:`InjectedFault`.  Yields the
    shared state dict (``written`` tells how far the "crash" got).

    ``after_bytes=0`` dies on the very first write — the earliest
    possible mid-save death."""
    state = {"budget": int(after_bytes), "written": 0}
    real_open = builtins.open

    def fake_open(file, mode="r", *args, **kwargs):
        fh = real_open(file, mode, *args, **kwargs)
        if any(m in mode for m in ("w", "x", "a", "+")) \
                and path_substr in str(file):
            return _CrashingWriter(fh, state)
        return fh

    builtins.open = fake_open
    try:
        yield state
    finally:
        builtins.open = real_open


@contextmanager
def crash_before(obj: Any, method: str) -> Iterator[None]:
    """Die immediately before ``obj.method`` runs — the probe for
    ordering bugs between two barriers (e.g. everything written, commit
    never reached: ``latest`` must not have moved)."""
    real = getattr(obj, method)

    def bomb(*_a, **_k):
        raise InjectedFault(f"injected crash before {method}")

    setattr(obj, method, bomb)
    try:
        yield
    finally:
        setattr(obj, method, real)


@contextmanager
def fail_after_calls(obj: Any, method: str, n: int) -> Iterator[Dict[str, int]]:
    """Let ``obj.method`` succeed ``n`` times, then raise
    :class:`InjectedFault` from every later call ("exception
    mid-step")."""
    real = getattr(obj, method)
    state = {"calls": 0}

    def wrapped(*args, **kwargs):
        state["calls"] += 1
        if state["calls"] > n:
            raise InjectedFault(
                f"injected failure on call {state['calls']} of {method}")
        return real(*args, **kwargs)

    setattr(obj, method, wrapped)
    try:
        yield state
    finally:
        setattr(obj, method, real)


def truncate_file(path: str, drop_bytes: int = 1) -> int:
    """Torn-tail storage fault: cut ``drop_bytes`` off the end of a file
    (post-save truncation).  Returns the new size."""
    size = os.path.getsize(path)
    new = max(0, size - int(drop_bytes))
    with open(path, "rb+") as fh:
        fh.truncate(new)
    return new


@contextmanager
def crash_on_call(obj: Any, method: str, n: int) -> Iterator[Dict[str, int]]:
    """Raise :class:`InjectedFault` out of exactly the ``n``-th call of
    ``obj.method`` (1-based); calls before AND after pass through.  The
    kill-then-restart probe: a serving loop crashed by call ``n`` can be
    revived inside the same context and step again — which
    :func:`fail_after_calls` (fails forever past N) cannot model."""
    real = getattr(obj, method)
    state = {"calls": 0}

    def wrapped(*args, **kwargs):
        state["calls"] += 1
        if state["calls"] == n:
            raise InjectedFault(
                f"injected crash on call {n} of {method}")
        return real(*args, **kwargs)

    setattr(obj, method, wrapped)
    try:
        yield state
    finally:
        setattr(obj, method, real)


@contextmanager
def wedge_method(obj: Any, method: str,
                 on_call: int = 1) -> Iterator[Dict[str, Any]]:
    """From its ``on_call``-th call (1-based), ``obj.method`` BLOCKS until
    the yielded handle's ``release`` event is set — a hung (wedged)
    component rather than a dead one.  The handle: ``{"release": Event,
    "wedged": Event, "calls": int}``; exiting the context releases and
    restores.  Earlier calls pass through."""
    real = getattr(obj, method)
    handle: Dict[str, Any] = {"release": threading.Event(),
                              "wedged": threading.Event(), "calls": 0}

    def wrapped(*args, **kwargs):
        handle["calls"] += 1
        if handle["calls"] >= on_call:
            handle["wedged"].set()
            handle["release"].wait()
        return real(*args, **kwargs)

    setattr(obj, method, wrapped)
    try:
        yield handle
    finally:
        handle["release"].set()
        setattr(obj, method, real)


@contextmanager
def gradient_bomb(engine: Any, scale: float = 1e20, on_call: int = 1,
                  n: int = 1) -> Iterator[Dict[str, int]]:
    """Training fault: multiply the float leaves of the batches fed to
    calls ``[on_call, on_call + n)`` (1-based) of ``engine.forward`` by
    ``scale`` — the corrupt-batch / garbage-host event that sends a bf16
    run non-finite or spikes the gradient norm by orders of magnitude.
    The anomaly ladder (``anomaly_detection``) must contain it: skip the
    step, then roll back after ``patience`` consecutive trips.  Yields
    ``{"calls", "bombed"}``.  Works on float batches (int token batches
    cannot be scaled into a bomb — poison the labels/model instead)."""
    real = engine.forward
    state = {"calls": 0, "bombed": 0}

    def _scale(b):
        if isinstance(b, (tuple, list)):
            return type(b)(_scale(v) for v in b)
        if isinstance(b, dict):
            return {k: _scale(v) for k, v in b.items()}
        kind = getattr(getattr(b, "dtype", None), "kind", None)
        if kind == "f" or (kind is None and isinstance(b, float)):
            return b * scale
        return b

    def wrapped(batch):
        state["calls"] += 1
        if on_call <= state["calls"] < on_call + n:
            state["bombed"] += 1
            batch = _scale(batch)
        return real(batch)

    engine.forward = wrapped
    try:
        yield state
    finally:
        engine.forward = real


def http_error_burst(handler, n: int, code: int = 500):
    """Wrap a ``(payload) -> (status, body)`` HTTP handler (the replica's
    ``/generate`` seam) so its next ``n`` calls answer ``code`` with an
    injected-error body, then pass through.  Returns ``(wrapped,
    state)``; ``state["errors"]`` counts the faults served."""
    state = {"left": int(n), "errors": 0}

    def wrapped(payload):
        if state["left"] > 0:
            state["left"] -= 1
            state["errors"] += 1
            return code, {"error": f"injected {code} "
                                   f"({state['errors']}/{n})"}
        return handler(payload)

    return wrapped, state


class ChaosProxy:
    """Runtime-switchable TCP fault proxy (router <-> replica seam).

    ``ChaosProxy(upstream_port).start()`` listens on an ephemeral
    ``proxy.port``; each ACCEPTED connection obeys the mode at accept
    time (flip ``proxy.mode`` between requests):

    - ``"pass"`` — transparent byte pump both ways;
    - ``"refuse"`` — the connection dies immediately (unambiguous
      failure: nothing was delivered);
    - ``"blackhole"`` — accepted and held silent, never answered (the
      client times out; ambiguous, nothing delivered);
    - ``"deliver_then_reset"`` — the request is forwarded and the
      upstream DOES the work, but the client connection is torn down the
      moment the response starts back: the ambiguous socket death after
      delivery — the retry that double-generates unless dispatch is
      idempotent;
    - ``"slow"`` — both directions drip in small chunks with a delay
      per chunk (``slow_delay``).

    ``counts`` tallies connections per mode.  ``stop()`` closes the
    listener and every held/open connection."""

    def __init__(self, upstream_port: int, upstream_host: str = "127.0.0.1",
                 mode: str = "pass", slow_delay: float = 0.05,
                 slow_chunk: int = 256):
        self.upstream = (upstream_host, int(upstream_port))
        self.mode = mode
        self.slow_delay = float(slow_delay)
        self.slow_chunk = int(slow_chunk)
        self.counts: Dict[str, int] = {}
        self._listener: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._conns = []
        self._once = []          # one-shot modes consumed before self.mode
        self._lock = threading.Lock()
        self.port = 0

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def inject(self, mode: str, n: int = 1) -> None:
        """Queue ``n`` one-shot faults: the next ``n`` accepted
        connections get ``mode``, later ones fall back to ``self.mode``
        — a single ambiguous socket death in an otherwise-clean stream,
        without racing a mode flip against the victim's connect."""
        with self._lock:
            self._once.extend([mode] * int(n))

    def start(self) -> "ChaosProxy":
        if self._listener is not None:
            return self
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="chaos-proxy", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._listener = None

    def _track(self, *socks) -> None:
        with self._lock:
            self._conns.extend(socks)

    def _accept_loop(self) -> None:
        # local ref: stop() nulls the attribute concurrently — the
        # closed socket raises OSError below, an attribute read of None
        # would raise AttributeError out of the daemon thread instead
        listener = self._listener
        while not self._stopping:
            try:
                client, _addr = listener.accept()
            except OSError:
                return
            with self._lock:
                mode = self._once.pop(0) if self._once else self.mode
                self.counts[mode] = self.counts.get(mode, 0) + 1
            if mode == "refuse":
                client.close()
                continue
            if mode == "blackhole":
                self._track(client)      # held open, never answered
                continue
            try:
                up = socket.create_connection(self.upstream, timeout=10)
            except OSError:
                client.close()
                continue
            # (pass-mode sockets are closed by the pump countdown below;
            # only held blackhole sockets need stop()-time tracking)
            # both pumps share one countdown: sockets are CLOSED exactly
            # once, by whichever pump finishes LAST.  A pump closing both
            # sockets on its own EOF (the obvious implementation) races
            # its twin's blocked recv across threads — and once the freed
            # fd is reused by a new connection, a stale recv can STEAL
            # that connection's bytes (observed: a replica's 200 response
            # vanished mid-proxy and the router hung to its socket
            # deadline).  Mid-stream teardown uses shutdown(), which
            # never frees the fd out from under the twin.
            pair = {"left": 2, "lock": threading.Lock(),
                    "socks": (client, up)}
            threading.Thread(target=self._pump,
                             args=(pair, client, up, mode, True),
                             daemon=True).start()
            threading.Thread(target=self._pump,
                             args=(pair, up, client, mode, False),
                             daemon=True).start()

    @staticmethod
    def _pair_done(pair) -> None:
        with pair["lock"]:
            pair["left"] -= 1
            last = pair["left"] == 0
        if last:
            for s in pair["socks"]:
                try:
                    s.close()
                except OSError:
                    pass

    def _pump(self, pair, src: socket.socket, dst: socket.socket, mode: str,
              client_to_up: bool) -> None:
        chunk = self.slow_chunk if mode == "slow" else 65536
        try:
            while True:
                data = src.recv(chunk)
                if not data:
                    break
                if mode == "deliver_then_reset" and not client_to_up:
                    # the upstream answered: the work is DONE there —
                    # kill the client connection without delivering a
                    # byte (shutdown, not close: the fd must stay owned
                    # until both pumps retire)
                    try:
                        dst.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    break
                dst.sendall(data)
                if mode == "slow":
                    time.sleep(self.slow_delay)
        except OSError:
            pass
        finally:
            # propagate EOF to the write side only; the twin pump keeps
            # the reverse direction alive (an HTTP client half-closing
            # after its request must still receive the response)
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            self._pair_done(pair)


def flip_bit(path: str, byte_offset: Optional[int] = None,
             bit: int = 0) -> int:
    """Silent media corruption: flip one bit in place (default: the
    middle byte).  Returns the byte offset flipped."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot flip a bit in empty file {path}")
    off = size // 2 if byte_offset is None else int(byte_offset)
    with open(path, "rb+") as fh:
        fh.seek(off)
        b = fh.read(1)
        fh.seek(off)
        fh.write(bytes([b[0] ^ (1 << bit)]))
    return off
