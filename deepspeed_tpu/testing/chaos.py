"""Fault-injection harness: the crash vocabulary behind the resilience
suite (docs/RESILIENCE.md).

The checkpoint atomicity contract is only worth anything if it is proved
against actual mid-write deaths, and a unit test cannot SIGKILL itself at
byte 1337 of a shard file.  This module supplies the equivalent faults as
injectable, deterministic primitives:

- :func:`crash_on_write` — "process dies at byte offset N of the save":
  patches ``builtins.open`` so matched files' writes cut off after a
  cumulative byte budget and raise :class:`InjectedFault`.  The partial
  prefix IS flushed to disk first, so the on-disk state equals what a
  kill at that offset leaves behind (no cleanup code runs — the save
  aborts mid-flight exactly like a death would, modulo OS page-cache
  durability, which the atomicity contract does not depend on).
- :func:`crash_before` — "process dies right before method M": the
  between-the-barriers probe (e.g. after all shards are written but
  before ``checkpoint_engine.commit``).
- :func:`truncate_file` / :func:`flip_bit` — post-save storage faults
  (torn tail, silent media corruption) that manifest verification must
  catch.
- :func:`fail_after_calls` — an exception out of the Nth call of any
  method ("exception mid-step").

Process-level faults (SIGKILL between incarnations, SIGTERM grace
windows) are exercised by the supervisor tests via real subprocesses;
this module covers the intra-process byte-level vocabulary those cannot
aim precisely.

Stdlib-only; nothing here imports jax.
"""

from __future__ import annotations

import builtins
import os
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional

__all__ = ["InjectedFault", "crash_on_write", "crash_before",
           "fail_after_calls", "truncate_file", "flip_bit"]


class InjectedFault(RuntimeError):
    """A deliberately injected failure (never raised by real code paths,
    so tests can assert on the type)."""


class _CrashingWriter:
    """File proxy that writes through until the shared byte budget is
    exhausted, flushes the partial prefix, and dies."""

    def __init__(self, fh, state: Dict[str, Any]):
        self._fh = fh
        self._state = state

    def write(self, data):
        n = len(data)
        room = self._state["budget"] - self._state["written"]
        if room <= 0:
            raise InjectedFault(
                f"injected crash at byte {self._state['budget']} of save")
        if n > room:
            self._fh.write(data[:room])
            self._fh.flush()
            self._state["written"] += room
            raise InjectedFault(
                f"injected crash at byte {self._state['budget']} of save "
                f"(mid-write of {getattr(self._fh, 'name', '?')})")
        self._state["written"] += n
        return self._fh.write(data)

    def __getattr__(self, name):
        return getattr(self._fh, name)

    def __enter__(self):
        self._fh.__enter__()
        return self

    def __exit__(self, *exc):
        return self._fh.__exit__(*exc)

    def __iter__(self):  # pragma: no cover - completeness
        return iter(self._fh)


@contextmanager
def crash_on_write(after_bytes: int, path_substr: str = "",
                   ) -> Iterator[Dict[str, Any]]:
    """Kill the next save at a chosen byte offset.

    Every file opened for writing whose path contains ``path_substr``
    shares one ``after_bytes`` budget; the write that crosses it flushes
    the in-budget prefix and raises :class:`InjectedFault`.  Yields the
    shared state dict (``written`` tells how far the "crash" got).

    ``after_bytes=0`` dies on the very first write — the earliest
    possible mid-save death."""
    state = {"budget": int(after_bytes), "written": 0}
    real_open = builtins.open

    def fake_open(file, mode="r", *args, **kwargs):
        fh = real_open(file, mode, *args, **kwargs)
        if any(m in mode for m in ("w", "x", "a", "+")) \
                and path_substr in str(file):
            return _CrashingWriter(fh, state)
        return fh

    builtins.open = fake_open
    try:
        yield state
    finally:
        builtins.open = real_open


@contextmanager
def crash_before(obj: Any, method: str) -> Iterator[None]:
    """Die immediately before ``obj.method`` runs — the probe for
    ordering bugs between two barriers (e.g. everything written, commit
    never reached: ``latest`` must not have moved)."""
    real = getattr(obj, method)

    def bomb(*_a, **_k):
        raise InjectedFault(f"injected crash before {method}")

    setattr(obj, method, bomb)
    try:
        yield
    finally:
        setattr(obj, method, real)


@contextmanager
def fail_after_calls(obj: Any, method: str, n: int) -> Iterator[Dict[str, int]]:
    """Let ``obj.method`` succeed ``n`` times, then raise
    :class:`InjectedFault` from every later call ("exception
    mid-step")."""
    real = getattr(obj, method)
    state = {"calls": 0}

    def wrapped(*args, **kwargs):
        state["calls"] += 1
        if state["calls"] > n:
            raise InjectedFault(
                f"injected failure on call {state['calls']} of {method}")
        return real(*args, **kwargs)

    setattr(obj, method, wrapped)
    try:
        yield state
    finally:
        setattr(obj, method, real)


def truncate_file(path: str, drop_bytes: int = 1) -> int:
    """Torn-tail storage fault: cut ``drop_bytes`` off the end of a file
    (post-save truncation).  Returns the new size."""
    size = os.path.getsize(path)
    new = max(0, size - int(drop_bytes))
    with open(path, "rb+") as fh:
        fh.truncate(new)
    return new


def flip_bit(path: str, byte_offset: Optional[int] = None,
             bit: int = 0) -> int:
    """Silent media corruption: flip one bit in place (default: the
    middle byte).  Returns the byte offset flipped."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot flip a bit in empty file {path}")
    off = size // 2 if byte_offset is None else int(byte_offset)
    with open(path, "rb+") as fh:
        fh.seek(off)
        b = fh.read(1)
        fh.seek(off)
        fh.write(bytes([b[0] ^ (1 << bit)]))
    return off
