"""Testing utilities: the fault-injection harness (``chaos.py``) behind
the resilience suite (docs/RESILIENCE.md)."""

from deepspeed_tpu.testing.chaos import (InjectedFault, crash_before,
                                         crash_on_write, fail_after_calls,
                                         flip_bit, truncate_file)

__all__ = ["InjectedFault", "crash_on_write", "crash_before",
           "fail_after_calls", "truncate_file", "flip_bit"]
