"""Mixture-of-Experts / expert parallelism (reference: ``deepspeed/moe/``)."""

from deepspeed_tpu.moe.layer import MoE, split_params_into_moe_groups
from deepspeed_tpu.moe.sharded_moe import (compute_capacity, moe_mlp,
                                           topk_gating)

__all__ = ["MoE", "split_params_into_moe_groups", "compute_capacity",
           "moe_mlp", "topk_gating"]
