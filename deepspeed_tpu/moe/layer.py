"""Reference-parity MoE layer API.

The reference's ``deepspeed.moe.layer.MoE`` wraps a user torch expert module
with a ``TopKGate`` + ``MOELayer`` (SURVEY.md §2.1).  The functional analog is
a standalone block with ``init``/``apply`` usable inside any jax model, plus
the expert/non-expert param split helper (reference ``moe/utils.py``)
reworked as a pytree mask for optax (partition-by-mask replaces torch param
groups).
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Any, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.moe.sharded_moe import moe_mlp
from deepspeed_tpu.utils.logging import logger


class MoE:
    """Standalone top-k MoE feed-forward block.

    Mirrors the reference constructor surface.  ``ep_size`` is informational
    on TPU: expert placement is governed by the mesh's ``ep`` axis (a mismatch
    logs a warning rather than resizing process groups).
    """

    def __init__(self, hidden_size: int, num_experts: int = 1, k: int = 1,
                 intermediate_size: Optional[int] = None, ep_size: int = 1,
                 capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4, activation: str = "silu", glu: bool = True,
                 use_residual: bool = False, drop_tokens: bool = True,
                 use_rts: bool = False, mesh=None):
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.mesh = mesh
        if mesh is not None and ep_size > 1 and mesh.shape.get("ep", 1) != ep_size:
            logger.warning("MoE ep_size=%d ignored: mesh ep axis is %d (TPU expert "
                           "placement follows the mesh)", ep_size, mesh.shape.get("ep", 1))
        self.cfg = SimpleNamespace(
            num_experts=num_experts, num_experts_per_tok=k,
            moe_capacity_factor=capacity_factor,
            moe_eval_capacity_factor=eval_capacity_factor,
            moe_min_capacity=min_capacity, activation=activation, glu=glu,
            moe_drop_tokens=drop_tokens, moe_use_rts=use_rts)
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.use_residual = use_residual

    def init(self, rng, x=None) -> Any:
        D, F, E = self.hidden_size, self.intermediate_size, self.num_experts
        k1, k2, k3, k4, k5, k6, k7 = jax.random.split(rng, 7)
        s_in, s_ff = D ** -0.5, F ** -0.5
        params = {
            "gate_w": jax.random.uniform(k1, (D, E), jnp.float32, -s_in, s_in),
            "w_up": jax.random.uniform(k2, (E, D, F), jnp.float32, -s_in, s_in),
            "w_down": jax.random.uniform(k3, (E, F, D), jnp.float32, -s_ff, s_ff),
        }
        if self.cfg.glu:
            params["w_gate"] = jax.random.uniform(k4, (E, D, F), jnp.float32, -s_in, s_in)
        if self.use_residual:
            params["res_up"] = jax.random.uniform(k5, (D, F), jnp.float32, -s_in, s_in)
            params["res_down"] = jax.random.uniform(k6, (F, D), jnp.float32, -s_ff, s_ff)
            params["res_coef"] = jnp.zeros((D, 2), jnp.float32)
        return params

    def apply(self, params, x, training: bool = True, rng=None):
        """x: [B, S, D] -> (y, aux_loss).  ``training`` selects
        capacity_factor vs eval_capacity_factor (reference TopKGate arg);
        ``rng`` feeds random token selection when ``use_rts``.
        (Reference MoE.forward also returns exp_counts, a profiling detail.)"""
        cfg = self.cfg
        factor = cfg.moe_capacity_factor if training else cfg.moe_eval_capacity_factor
        eff = SimpleNamespace(**{**vars(cfg), "moe_capacity_factor": factor})
        y, aux = moe_mlp(params, x, eff, self.mesh, rng=rng)
        if self.use_residual:
            from deepspeed_tpu.models.layers import activation_fn
            act = activation_fn(cfg.activation)
            res = act(x @ params["res_up"]) @ params["res_down"]
            coef = jax.nn.softmax(x @ params["res_coef"], axis=-1)
            y = y * coef[..., 0:1] + res * coef[..., 1:2]
        return y, aux


def split_params_into_moe_groups(params) -> Any:
    """Boolean mask pytree: True where a leaf is an expert-parallel weight.

    Expert weights are identified *structurally*: any dict that contains a
    ``gate_w`` router alongside ``w_up``/``w_down`` is an MoE block (the
    built-in models' dense MLPs use the same leaf names but have no router).
    The router itself is dense/replicated, like the reference's gate (it sits
    in the non-expert group).  Use with ``optax.masked`` to give expert params
    their own schedule/decay — the functional replacement for the reference's
    optimizer param groups (``moe/utils.py``).
    """
    expert_keys = {"w_up", "w_down", "w_gate"}

    def walk(node, in_moe):
        if isinstance(node, dict):
            is_moe_block = "gate_w" in node and expert_keys & set(node)
            return {k: walk(v, in_moe or (is_moe_block and k in expert_keys))
                    for k, v in node.items()}
        return jax.tree.map(lambda _: in_moe, node)

    return walk(params, False)


def is_moe_param(params, path_or_mask=None) -> Any:
    """Convenience: the mask tree itself (see split_params_into_moe_groups)."""
    return split_params_into_moe_groups(params)
