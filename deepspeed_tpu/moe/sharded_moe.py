"""Expert-parallel MoE: top-k gating + all-to-all dispatch/combine.

TPU-native rebuild of the reference's ``deepspeed/moe/sharded_moe.py``
(GShard-style ``top1gating``/``top2gating`` + ``MOELayer`` with ``_AllToAll``
over the expert-parallel process group; SURVEY.md §2.1 "MoE / expert
parallelism").  Differences forced by XLA's static shapes — and they are the
same choices GShard itself made:

- **Fixed expert capacity + masking** instead of dynamic token lists: every
  expert processes exactly ``C = ceil(k·N/E · capacity_factor)`` token slots;
  overflow tokens are dropped (their combine weight is zero, so they pass
  through the residual connection untouched).
- **Dispatch/combine as einsums** with a [N, E, C] one-hot tensor; the
  reference's explicit ``all_to_all_single`` calls become GSPMD-inserted
  all-to-alls when the [E, C, D] expert tensor is sharding-constrained onto
  the ``ep`` mesh axis while tokens are sharded over the data axes.
- Load-balancing aux loss (the reference's ``l_aux``): ``E · Σ_e mean_prob_e
  · frac_tokens_e`` over the top-1 assignment.

Expert weights are sharded over ``ep`` (expert parallelism) and optionally
``tp`` (intra-expert tensor parallelism) via the model's logical specs; the
expert-data-parallel hybrid (reference ``ep_size`` < world) falls out of the
mesh factorization (ep axis size < dp·fsdp·ep extent).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.layers import activation_fn, constrain


def compute_capacity(num_tokens: int, num_experts: int, k: int,
                     capacity_factor: float, min_capacity: int = 4) -> int:
    return max(min_capacity,
               int(math.ceil(k * num_tokens / num_experts * capacity_factor)))


def topk_assignments(gates, k: int, capacity: int, rng=None,
                     use_rts: bool = False):
    """Compact top-k assignment: (expert_idx [N,k], pos [N,k], weight [N,k],
    aux scalar).  Same gating math as :func:`topk_gating` but without the
    [N, E, C] one-hot tensors — feeds the O(N·k·D) scatter/gather dispatch
    (VERDICT r2 weak #9: the one-hot dispatch einsum is O(N²·k/E)).

    ``use_rts`` (reference ``top1gating(use_rts=True)`` Random Token
    Selection): capacity slots are granted in a RANDOM token order instead
    of sequence order, so truncation under overflow doesn't systematically
    drop late-sequence tokens.  A no-op when nothing overflows."""
    if use_rts and rng is not None:
        N = gates.shape[0]
        perm = jax.random.permutation(rng, N)
        inv = jnp.argsort(perm)
        e_idx, pos, w, aux = topk_assignments(gates[perm], k, capacity)
        return e_idx[inv], pos[inv], w[inv], aux
    N, E = gates.shape
    C = capacity
    remaining = gates
    location_base = jnp.zeros((E,), jnp.int32)
    aux = jnp.zeros((), jnp.float32)
    idxs, poss, ws = [], [], []
    kept_gate_sum = jnp.zeros((N,), jnp.float32)
    for slot in range(k):
        idx = jnp.argmax(remaining, axis=-1)                      # [N]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)        # [N, E]
        if slot == 0:
            me = jnp.mean(gates, axis=0)
            ce = jnp.mean(onehot, axis=0)
            aux = E * jnp.sum(me * ce)
        pos_in_e = jnp.cumsum(onehot, axis=0) - onehot + location_base[None]
        pos = jnp.sum(pos_in_e * onehot, axis=-1).astype(jnp.int32)
        keep = (pos < C).astype(jnp.float32)
        gate_val = jnp.sum(gates * onehot, axis=-1)
        idxs.append(idx)
        poss.append(pos)
        ws.append(gate_val * keep)
        kept_gate_sum = kept_gate_sum + gate_val * keep
        location_base = location_base + jnp.sum(onehot, axis=0).astype(jnp.int32)
        remaining = jnp.where(onehot > 0, -jnp.inf, remaining)
    weight = jnp.stack(ws, axis=1)                                # [N, k]
    if k > 1:
        weight = weight / jnp.maximum(kept_gate_sum, 1e-9)[:, None]
    return (jnp.stack(idxs, axis=1), jnp.stack(poss, axis=1), weight, aux)


def topk_gating(gates, k: int, capacity: int, rng=None,
                use_rts: bool = False):
    """GShard top-k gating with fixed capacity.

    gates: [N, E] softmax router probabilities (fp32).
    Returns (combine [N, E, C], dispatch [N, E, C] bool, aux_loss scalar).
    Reference: ``top1gating``/``top2gating`` in deepspeed/moe/sharded_moe.py;
    ``use_rts`` = the reference's Random Token Selection (see
    :func:`topk_assignments`).
    """
    if use_rts and rng is not None:
        N = gates.shape[0]
        perm = jax.random.permutation(rng, N)
        inv = jnp.argsort(perm)
        combine, dispatch, aux = topk_gating(gates[perm], k, capacity)
        return combine[inv], dispatch[inv], aux
    N, E = gates.shape
    C = capacity
    remaining = gates
    location_base = jnp.zeros((E,), jnp.int32)
    combine = jnp.zeros((N, E, C), jnp.float32)
    kept_gate_sum = jnp.zeros((N,), jnp.float32)
    aux = jnp.zeros((), jnp.float32)

    for slot in range(k):
        idx = jnp.argmax(remaining, axis=-1)                      # [N]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)        # [N, E]
        if slot == 0:
            me = jnp.mean(gates, axis=0)                          # mean router prob
            ce = jnp.mean(onehot, axis=0)                         # token fraction
            aux = E * jnp.sum(me * ce)
        # position of each token within its chosen expert's capacity buffer
        pos_in_e = jnp.cumsum(onehot, axis=0) - onehot + location_base[None]
        pos = jnp.sum(pos_in_e * onehot, axis=-1).astype(jnp.int32)  # [N]
        keep = (pos < C).astype(jnp.float32)
        gate_val = jnp.sum(gates * onehot, axis=-1)               # [N]
        pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, C - 1), C, dtype=jnp.float32)
        combine = combine + ((gate_val * keep)[:, None, None]
                             * onehot[:, :, None] * pos_oh[:, None, :])
        kept_gate_sum = kept_gate_sum + gate_val * keep
        location_base = location_base + jnp.sum(onehot, axis=0).astype(jnp.int32)
        remaining = jnp.where(onehot > 0, -jnp.inf, remaining)

    if k > 1:
        # normalize combine weights over the kept top-k experts per token
        # (Mixtral/top2gating convention); k=1 keeps the raw gate probability
        # so the router still gets gradient from the task loss (top1gating).
        combine = combine / jnp.maximum(kept_gate_sum, 1e-9)[:, None, None]
    dispatch = combine > 0
    return combine, dispatch, aux


def moe_mlp(params, x, cfg, mesh=None, rng=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One MoE feed-forward block on [B, S, D] hidden states.

    ``params``: {"gate_w" [D, E], "w_up" [E, D, F], ("w_gate" [E, D, F]),
    "w_down" [E, F, D]} — the per-layer slice of the model's stacked MoE
    weights.  Returns (output [B, S, D], aux_loss scalar).

    ``cfg.moe_drop_tokens=False`` (reference ``drop_tokens=False``): the
    capacity covers the worst-case expert load (C = N — XLA's static shapes
    forbid the reference's runtime max-load capacity), so no token is ever
    dropped.  ``cfg.moe_use_rts``: Random Token Selection for capacity
    slots; the permutation key is ``rng`` (the layer's dropout key when the
    model has one) or, failing that, derived from the batch content so it
    still varies across batches inside one compiled step.
    """
    B, S, D = x.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    N = B * S
    xt = x.reshape(N, D)

    logits = xt.astype(jnp.float32) @ params["gate_w"].astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    drop = getattr(cfg, "moe_drop_tokens", True)
    use_rts = bool(getattr(cfg, "moe_use_rts", False))
    if use_rts and rng is None:
        seed = jax.lax.bitcast_convert_type(
            xt.astype(jnp.float32).sum(), jnp.int32)
        rng = jax.random.fold_in(jax.random.PRNGKey(17), seed)
    if drop:
        C = compute_capacity(N, E, k, cfg.moe_capacity_factor,
                             getattr(cfg, "moe_min_capacity", 4))
    else:
        C = N  # worst case: every token routed to the same expert
    use_scatter = getattr(cfg, "moe_dispatch", "scatter") == "scatter"
    if use_scatter:
        # O(N·k·D) scatter dispatch / gather combine (VERDICT r2 weak #9):
        # the [N, E, C] one-hot einsum is O(N²·k/E) because C ~ k·N/E.
        e_idx, pos, weight, aux = topk_assignments(gates, k, C, rng,
                                                   use_rts)     # [N, k]
        keep = pos < C
        safe_pos = jnp.clip(pos, 0, C - 1)
        contrib = jnp.where(keep.reshape(-1)[:, None],
                            jnp.repeat(xt, k, axis=0), 0)         # [N·k, D]
        expert_in = jnp.zeros((E, C, D), x.dtype).at[
            e_idx.reshape(-1), safe_pos.reshape(-1)].add(contrib)
    else:
        combine, dispatch, aux = topk_gating(gates, k, C, rng, use_rts)
        # dispatch: tokens (sharded over data axes) -> expert buffers
        # (sharded over ep) — GSPMD inserts the all-to-all here
        # (reference: _AllToAll).
        expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), xt)
    # comm_quantization.all_to_all (engine sets cfg.moe_q_dispatch): the
    # DISPATCH boundary collective moves blockwise-int8 codes + fp32
    # scales instead of dense activations (comm/collectives_q.py
    # q_reshard — the GSPMD form; its custom VJP transports the
    # cotangent quantized too, so training dispatch stays honest)
    q_disp = (getattr(cfg, "moe_q_dispatch", False) and mesh is not None
              and not getattr(mesh, "empty", False)
              and dict(mesh.shape).get("ep", 1) > 1)
    if q_disp:
        from jax.sharding import PartitionSpec as _P

        from deepspeed_tpu.comm.collectives_q import q_reshard
        from deepspeed_tpu.comm.mesh import data_axes

        qblock = int(getattr(cfg, "comm_quant_block", 256))
        # src pinned to the token side (codes' block dim over the data
        # axes), dst to ep: BOTH boundaries constrained so GSPMD cannot
        # hoist the reshard before the quantize and move dense bytes
        # (q_reshard's contract — the exchange happens between the two
        # code constraints)
        daxes = data_axes(mesh)
        expert_in = q_reshard(expert_in, mesh, _P("ep"),
                              src_spec=_P(None, daxes), block=qblock)
    else:
        expert_in = constrain(expert_in, mesh, "ep", None, None)

    act = activation_fn(cfg.activation)
    up = jnp.einsum("ecd,edf->ecf", expert_in, params["w_up"].astype(x.dtype))
    if cfg.glu:
        gate = jnp.einsum("ecd,edf->ecf", expert_in, params["w_gate"].astype(x.dtype))
        hidden = act(gate) * up
    else:
        hidden = act(up)
    out = jnp.einsum("ecf,efd->ecd", hidden, params["w_down"].astype(x.dtype))
    # the combine return path stays DENSE on purpose: redistributing
    # expert outputs to tokens via replicated int8 codes would move
    # ~size*(1+4/block) bytes per device where the dense ep-sharded
    # exchange moves ~size*itemsize/ep — for ep>=4 the "quantized" form
    # is MORE wire bytes, not fewer (and materializes the full [E,C,D]
    # tensor per device).  The dispatch direction above is where the
    # int8 win is; its custom VJP already quantizes the combine-shaped
    # cotangent on the honest per-destination reshard.
    out = constrain(out, mesh, "ep", None, None)

    # combine: expert buffers -> tokens (the return all-to-all)
    if use_scatter:
        gathered = out[e_idx, safe_pos]                           # [N, k, D]
        y = jnp.sum(gathered * (weight * keep).astype(x.dtype)[..., None],
                    axis=1)
    else:
        y = jnp.einsum("ecd,nec->nd", out, combine.astype(x.dtype))
    return y.reshape(B, S, D), aux
