"""Autotuning: search ZeRO stage / micro-batch / remat space by short
measured trials.

Reference: ``deepspeed/autotuning/autotuner.py`` (SURVEY.md §2.1
"Autotuning") — the reference launches short experiment jobs through the
launcher and fits a cost model.  TPU-native shape: trials run in-process
(one jit compile + a few timed steps each; no subprocess churn needed
because jax programs are isolated by construction), OOM prunes the branch,
and the best config is returned as a ds_config patch.

``Autotuner(model_fn, base_config).tune()`` returns (best_config, report).
``model_fn() -> (model, sample_batch)`` builds a fresh model per trial.
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import log_dist, logger

DEFAULT_TUNING_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8, 16],
    "activation_checkpointing.policy": ["none", "full", "dots", "mlp_dots"],
}


def _set_path(cfg: Dict[str, Any], dotted: str, value: Any) -> None:
    parts = dotted.split(".")
    cur = cfg
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = value


def patched_config(base: Dict[str, Any], overrides: Dict[str, Any]) -> Dict[str, Any]:
    """Apply dotted-path overrides to a ds_config (shared by the in-process
    and experiment tuners; the activation-checkpointing policy pseudo-key
    and the batch-triad re-derivation live HERE only)."""
    import copy

    cfg = copy.deepcopy(base)
    for k, v in overrides.items():
        if k == "activation_checkpointing.policy":
            if v == "none":
                _set_path(cfg, "activation_checkpointing.enabled", False)
                continue
            _set_path(cfg, "activation_checkpointing.enabled", True)
        _set_path(cfg, k, v)
    cfg.pop("train_batch_size", None)  # re-derived from micro x gas x world
    return cfg


def pruned_grid(space: Dict[str, List[Any]], max_trials: int,
                micro_key: str = "train_micro_batch_size_per_gpu"):
    """Generator over the grid with per-branch OOM pruning (shared by the
    in-process and experiment tuners).  Protocol: ``next()`` the first
    overrides dict, then ``send(trial_oomed: bool)`` for each subsequent
    one; micro-batches at or above an OOM point on the same branch are
    skipped."""
    keys = list(space)
    combos = itertools.product(*(space[k] for k in keys))
    oom_points: List[tuple] = []
    tried = 0
    for combo in combos:
        if tried >= max_trials:
            return
        overrides = dict(zip(keys, combo))
        branch = tuple(v for k, v in overrides.items() if k != micro_key)
        micro = overrides.get(micro_key, 0)
        if any(b == branch and m <= micro for b, m in oom_points):
            continue
        oomed = yield overrides
        tried += 1
        if oomed:
            oom_points.append((branch, micro))


class Autotuner:
    def __init__(self, model_fn: Callable[[], Tuple[Any, Any]],
                 base_config: Dict[str, Any],
                 tuning_space: Optional[Dict[str, List[Any]]] = None,
                 max_trials: int = 12, steps_per_trial: int = 3,
                 mesh=None):
        self.model_fn = model_fn
        self.base = dict(base_config)
        self.space = tuning_space or dict(DEFAULT_TUNING_SPACE)
        self.max_trials = max_trials
        self.steps_per_trial = steps_per_trial
        self.mesh = mesh
        self.results: List[Dict[str, Any]] = []

    # -- one measured trial ---------------------------------------------
    def _trial(self, overrides: Dict[str, Any]) -> Dict[str, Any]:
        import jax

        import deepspeed_tpu

        cfg = patched_config(self.base, overrides)
        micro = cfg.get("train_micro_batch_size_per_gpu", 1)
        gas = cfg.get("gradient_accumulation_steps", 1)
        rec: Dict[str, Any] = {"overrides": dict(overrides)}
        try:
            model, batch = self.model_fn()
            engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg,
                                                       mesh=self.mesh)
            from deepspeed_tpu import comm

            rows = micro * comm.get_data_parallel_world_size(engine.mesh)
            b = jax.tree.map(lambda x: x[:rows], batch)
            for _ in range(gas):
                engine.forward(b)
            engine.step()  # compile + warmup
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                for _ in range(gas):
                    engine.forward(b)
                engine.step()
            jax.block_until_ready(jax.tree.leaves(engine.state.params)[0])
            dt = (time.perf_counter() - t0) / self.steps_per_trial
            tokens = micro * gas
            for leaf in jax.tree.leaves(b):
                if getattr(leaf, "ndim", 0) >= 2:
                    tokens = micro * gas * leaf.shape[1]
                    break
            rec.update(status="ok", step_s=dt, throughput=tokens / dt)
        except Exception as exc:
            msg = str(exc)
            oom = "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
            rec.update(status="oom" if oom else "error", error=msg[:160])
        return rec

    # -- search ----------------------------------------------------------
    def tune(self) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
        grid = pruned_grid(self.space, self.max_trials)
        overrides = next(grid, None)
        while overrides is not None:
            rec = self._trial(overrides)
            self.results.append(rec)
            log_dist(f"autotune trial {overrides}: {rec['status']} "
                     f"{rec.get('throughput', 0):.0f} tok/s", ranks=[0])
            try:
                overrides = grid.send(rec["status"] == "oom")
            except StopIteration:
                break
        ok = [r for r in self.results if r["status"] == "ok"]
        if not ok:
            logger.warning("autotuning: no successful trial; returning base config")
            return self.base, self.results
        best = max(ok, key=lambda r: r["throughput"])
        log_dist(f"autotuning: best {best['overrides']} "
                 f"({best['throughput']:.0f} tok/s over {len(ok)} ok trials)",
                 ranks=[0])
        return patched_config(self.base, best["overrides"]), self.results
