"""Launcher-driven autotuning experiments + a model-based tuner.

Reference: ``deepspeed/autotuning/`` (SURVEY.md §2.1 row 44) — beyond the
in-process grid search (``autotuner.py``), the reference runs each trial as
a fresh launcher JOB (ResourceManager + scheduler) and prunes the space
with a fitted cost model (``tuner/model_based.py``).  TPU-native shape:

- **ExperimentRunner**: each trial spawns the user script as a fresh
  process (group) with the trial's patched ds_config delivered via
  ``DS_AUTOTUNE_CONFIG``; the script trains a few steps and reports by
  writing ``DS_AUTOTUNE_RESULT``.  Fresh processes give every trial clean
  device memory (an OOM cannot poison the next trial) and let multi-process
  worlds be tuned — the two things the in-process search cannot do.
- **CostModelTuner**: step time is affine in the micro-batch on a fixed
  branch (t = a + b*micro: constant dispatch/update cost + per-token
  compute), so two measured points per branch predict every other
  micro-batch.  The tuner measures the two smallest micros per branch,
  extrapolates, and only spends real trials on each branch's predicted
  best — the reference's XGBoost role with a closed-form model that
  matches how the space actually behaves.

User-script contract (mirrors the reference's ``--autotuning run`` hook):

    cfg_path = os.environ["DS_AUTOTUNE_CONFIG"]     # patched ds_config.json
    ... build engine with json.load(open(cfg_path)), time a few steps ...
    json.dump({"throughput": tokens_per_sec},
              open(os.environ["DS_AUTOTUNE_RESULT"], "w"))
"""

from __future__ import annotations

import copy
import itertools
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.autotuning.autotuner import (DEFAULT_TUNING_SPACE,
                                                patched_config, pruned_grid)
from deepspeed_tpu.utils.logging import log_dist, logger


class ExperimentRunner:
    """One fresh process (group) per trial — see module docstring."""

    def __init__(self, user_script: str, base_config: Dict[str, Any],
                 tuning_space: Optional[Dict[str, List[Any]]] = None,
                 user_args: Optional[List[str]] = None, num_procs: int = 1,
                 max_trials: int = 12, trial_timeout_s: float = 600.0,
                 results_dir: str = "autotuning_results",
                 env: Optional[Dict[str, str]] = None):
        self.user_script = user_script
        self.user_args = list(user_args or [])
        self.base = dict(base_config)
        self.space = tuning_space or dict(DEFAULT_TUNING_SPACE)
        self.num_procs = num_procs
        self.max_trials = max_trials
        self.trial_timeout_s = trial_timeout_s
        self.results_dir = results_dir
        self.env = dict(env if env is not None else os.environ)
        self.results: List[Dict[str, Any]] = []

    # -- one experiment --------------------------------------------------
    def _experiment(self, overrides: Dict[str, Any], idx: int) -> Dict[str, Any]:
        os.makedirs(self.results_dir, exist_ok=True)
        cfg_path = os.path.join(self.results_dir, f"exp{idx}_config.json")
        res_path = os.path.join(self.results_dir, f"exp{idx}_result.json")
        with open(cfg_path, "w") as fh:
            json.dump(patched_config(self.base, overrides), fh)
        if os.path.exists(res_path):
            os.unlink(res_path)
        env = dict(self.env, DS_AUTOTUNE_CONFIG=cfg_path,
                   DS_AUTOTUNE_RESULT=res_path)
        rec: Dict[str, Any] = {"overrides": dict(overrides), "exp": idx}
        if self.num_procs > 1:
            cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
                   "--num_procs", str(self.num_procs), "--no_local_rank",
                   self.user_script] + self.user_args
        else:
            cmd = [sys.executable, self.user_script] + self.user_args
        t0 = time.perf_counter()
        # own session: a timeout must kill the WHOLE process group (the
        # launcher's grandchild workers would otherwise survive the direct
        # child's SIGKILL and keep holding the device)
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                start_new_session=True)
        try:
            _out, err = proc.communicate(timeout=self.trial_timeout_s)
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            proc.communicate()
            rec.update(status="timeout", elapsed_s=self.trial_timeout_s)
            return rec
        rec["elapsed_s"] = round(time.perf_counter() - t0, 1)
        if os.path.exists(res_path):
            try:
                with open(res_path) as fh:
                    rec.update(json.load(fh))
                rec.setdefault("status", "ok")
                return rec
            except json.JSONDecodeError:
                pass
        err = err or ""
        oom = ("RESOURCE_EXHAUSTED" in err or "Out of memory" in err
               or "out of memory" in err)
        rec.update(status="oom" if oom else f"failed: exit {proc.returncode}",
                   stderr_tail=err[-300:])
        return rec

    # -- search ----------------------------------------------------------
    def run(self) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
        grid = pruned_grid(self.space, self.max_trials)
        overrides = next(grid, None)
        while overrides is not None:
            rec = self._experiment(overrides, len(self.results))
            self.results.append(rec)
            log_dist(f"autotune experiment {overrides}: {rec['status']} "
                     f"{rec.get('throughput', 0):.0f} tok/s", ranks=[0])
            try:
                overrides = grid.send(rec["status"] == "oom")
            except StopIteration:
                break
        ok = [r for r in self.results if r.get("status") == "ok"
              and "throughput" in r]
        summary_path = os.path.join(self.results_dir, "summary.json")
        os.makedirs(self.results_dir, exist_ok=True)
        with open(summary_path, "w") as fh:
            json.dump(self.results, fh, indent=1)
        if not ok:
            logger.warning("autotuning experiments: no successful trial; "
                           "returning base config (see %s)", summary_path)
            return self.base, self.results
        best = max(ok, key=lambda r: r["throughput"])
        log_dist(f"autotuning experiments: best {best['overrides']} "
                 f"({best['throughput']:.0f} tok/s; report {summary_path})",
                 ranks=[0])
        return patched_config(self.base, best["overrides"]), self.results


class CostModelTuner:
    """Affine-step-time model over micro-batch (see module docstring).

    ``measure(overrides) -> dict`` is any callable with the Autotuner/
    ExperimentRunner trial contract (returns ``status`` + ``step_s``).
    """

    def __init__(self, measure, tuning_space: Optional[Dict[str, List[Any]]] = None,
                 micro_key: str = "train_micro_batch_size_per_gpu"):
        self.measure = measure
        self.space = tuning_space or dict(DEFAULT_TUNING_SPACE)
        self.micro_key = micro_key
        self.results: List[Dict[str, Any]] = []

    def _measured(self, overrides):
        rec = self.measure(dict(overrides))
        rec = dict(rec, overrides=dict(overrides))
        self.results.append(rec)
        return rec

    def tune(self) -> Tuple[Optional[Dict[str, Any]], List[Dict[str, Any]]]:
        micros = sorted(self.space.get(self.micro_key, [1]))
        branch_keys = [k for k in self.space if k != self.micro_key]
        branches = list(itertools.product(*(self.space[k] for k in branch_keys)))
        best = None
        for combo in branches:
            base_over = dict(zip(branch_keys, combo))
            # fit t = a + b*micro from the two smallest micros
            pts = []
            for m in micros[:2]:
                rec = self._measured({**base_over, self.micro_key: m})
                if rec.get("status") != "ok":
                    break
                pts.append((m, rec["step_s"]))
            if len(pts) == 2:
                (m0, t0), (m1, t1) = pts
                b = (t1 - t0) / (m1 - m0) if m1 != m0 else 0.0
                a = t0 - b * m0
                # predicted throughput = micro / (a + b*micro): increasing
                # in micro while a > 0, so the model proposes the LARGEST
                # micro; walk down from it on OOM
                candidates = list(micros[2:])
                candidates.sort(key=lambda m: -(m / max(a + b * m, 1e-9)))
                for m in candidates:
                    rec = self._measured({**base_over, self.micro_key: m})
                    if rec.get("status") == "ok":
                        break
            # branch best over EVERYTHING measured ok on this branch — a
            # single-fit-point branch (or a one-micro space) still counts
            pool = [r for r in self.results
                    if r.get("status") == "ok"
                    and all(r["overrides"].get(k) == v
                            for k, v in base_over.items())]
            if not pool:
                continue
            tput = lambda r: r["overrides"][self.micro_key] / r["step_s"]
            branch_best = max(pool, key=tput)
            if best is None or tput(branch_best) > tput(best):
                best = branch_best
        if best is None:
            logger.warning("cost-model tuner: no successful measurement")
            return None, self.results
        log_dist(f"cost-model tuner: best {best['overrides']} "
                 f"({len(self.results)} measurements)", ranks=[0])
        return dict(best["overrides"]), self.results
