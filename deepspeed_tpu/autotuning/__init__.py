"""Autotuning (reference: ``deepspeed/autotuning/``, SURVEY.md §2.1):
in-process measured trials, launcher-driven experiments (one fresh process
group per trial), and the affine cost-model tuner."""

from deepspeed_tpu.autotuning.autotuner import Autotuner, DEFAULT_TUNING_SPACE  # noqa: F401
from deepspeed_tpu.autotuning.experiment import (  # noqa: F401
    CostModelTuner, ExperimentRunner)
