"""Autotuning (reference: ``deepspeed/autotuning/``, SURVEY.md §2.1):
in-process measured trials over the ZeRO/micro-batch/remat space."""

from deepspeed_tpu.autotuning.autotuner import Autotuner, DEFAULT_TUNING_SPACE  # noqa: F401
