"""Elastic training config math.

Reference: ``deepspeed/elasticity/elasticity.py`` (SURVEY.md §2.1
"Elasticity", §5.3): *schedule-time* elasticity — given an acceptable maximum
global batch size and a set of candidate micro-batch sizes, compute a final
global batch size and the set of device counts at which training can resume
with that batch size kept invariant (so a restart at a different scale is
numerically consistent).  Recovery itself is restart-from-checkpoint at the
new mesh shape (universal checkpoint, SURVEY.md §5.4); this module only does
the host-side math.

On TPU the "gpu count" is the device count of the mesh's data-parallel
extent (dp × fsdp × ep).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Typed view of the ``elasticity`` config section (reference schema)."""

    def __init__(self, param_dict: Dict):
        self.enabled = param_dict.get("enabled", False)
        if "max_train_batch_size" not in param_dict and self.enabled:
            raise ElasticityConfigError("elasticity requires max_train_batch_size")
        self.max_acceptable_batch_size = param_dict.get("max_train_batch_size", 0)
        self.micro_batches = param_dict.get("micro_batch_sizes", [2, 4, 6])
        if not isinstance(self.micro_batches, list) or not all(
                isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be positive ints, got {self.micro_batches}")
        self.min_gpus = param_dict.get("min_gpus", 1)
        self.max_gpus = param_dict.get("max_gpus", 10_000)
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"invalid gpu range [{self.min_gpus}, {self.max_gpus}]")
        self.min_time = param_dict.get("min_time", 0)
        self.version = param_dict.get("version", LATEST_ELASTICITY_VERSION)
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch", True)
        self.ignore_non_elastic_batch_info = param_dict.get(
            "ignore_non_elastic_batch_info", False)


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """Device counts g such that batch_size = micro * accum * g exactly for
    some micro in ``micro_batches`` (accum any positive int)."""
    valid = set()
    for micro in micro_batches:
        if batch_size % micro:
            continue
        max_gpus = batch_size // micro
        for g in range(min_valid_gpus, min(max_valid_gpus, max_gpus) + 1):
            if (batch_size // micro) % g == 0:
                valid.add(g)
    return sorted(valid)


def get_best_candidates(max_acceptable_batch_size: int, micro_batches: List[int],
                        min_gpus: int, max_gpus: int, prefer_larger: bool
                        ) -> Tuple[int, List[int]]:
    """Pick the batch size <= max that admits the most device counts
    (tie-break: larger/smaller batch per ``prefer_larger``)."""
    base = _lcm_list(micro_batches)
    candidates = list(range(base, max_acceptable_batch_size + 1, base))
    if not candidates:
        raise ElasticityConfigError(
            f"max_train_batch_size {max_acceptable_batch_size} is smaller than "
            f"the lcm of micro_batch_sizes {micro_batches} ({base})")
    best_batch, best_gpus = 0, []
    for b in candidates:
        gpus = get_valid_gpus(b, micro_batches, min_gpus, max_gpus)
        better = (len(gpus) > len(best_gpus)
                  or (len(gpus) == len(best_gpus) and len(gpus) > 0 and prefer_larger))
        if better:
            best_batch, best_gpus = b, gpus
    if not best_gpus:
        raise ElasticityConfigError(
            f"no valid device counts in [{min_gpus}, {max_gpus}] for "
            f"micro_batch_sizes {micro_batches} and max batch "
            f"{max_acceptable_batch_size}")
    return best_batch, best_gpus


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """Main entry (reference signature): returns
    ``(final_batch_size, valid_gpus[, micro_batch])`` and — when
    ``world_size`` > 0 — validates that world_size is one of the valid counts
    and picks the micro-batch/grad-accum split for it."""
    if "elasticity" not in ds_config:
        raise ElasticityConfigError("no elasticity section in config")
    elastic = ElasticityConfig(ds_config["elasticity"])
    if float(elastic.version) > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"unsupported elasticity version {elastic.version} "
            f"(latest {LATEST_ELASTICITY_VERSION})")
    final_batch_size, valid_gpus = get_best_candidates(
        elastic.max_acceptable_batch_size, elastic.micro_batches,
        elastic.min_gpus, elastic.max_gpus, elastic.prefer_larger_batch_size)
    logger.info("elasticity: final global batch %d, valid device counts %s",
                final_batch_size, valid_gpus)
    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in the elastic set {valid_gpus} "
                f"for batch {final_batch_size}")
        micro = _best_micro_batch(final_batch_size, elastic.micro_batches,
                                  world_size, elastic.prefer_larger_batch_size)
        if return_microbatch:
            return final_batch_size, valid_gpus, micro
    if return_microbatch:
        micro = _best_micro_batch(final_batch_size, elastic.micro_batches,
                                  valid_gpus[-1], elastic.prefer_larger_batch_size)
        return final_batch_size, valid_gpus, micro
    return final_batch_size, valid_gpus


def _best_micro_batch(batch: int, micro_batches: List[int], world_size: int,
                      prefer_larger: bool) -> int:
    fitting = [m for m in micro_batches
               if batch % m == 0 and (batch // m) % world_size == 0]
    if not fitting:
        raise ElasticityIncompatibleWorldSize(
            f"no micro batch in {micro_batches} divides batch {batch} at "
            f"world size {world_size}")
    return max(fitting) if prefer_larger else min(fitting)


def _lcm_list(xs: List[int]) -> int:
    from math import gcd

    out = 1
    for x in xs:
        out = out * x // gcd(out, x)
    return out
