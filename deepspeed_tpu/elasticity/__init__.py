"""Elastic training (reference: ``deepspeed/elasticity/``, SURVEY.md §5.3)."""

from deepspeed_tpu.elasticity.elasticity import (  # noqa: F401
    ElasticityConfig, ElasticityConfigError, ElasticityError,
    ElasticityIncompatibleWorldSize, compute_elastic_config, get_best_candidates,
    get_valid_gpus)
from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent  # noqa: F401
