"""Elastic agent v2: supervise, shrink, restart from checkpoint.

Reference: ``deepspeed/elasticity/elastic_agent.py`` (SURVEY.md §2.1 row 45,
§5.3) — the reference extends torch-elastic's agent: when a member dies, the
rendezvous re-forms with the survivors and training restarts from the latest
checkpoint at the new world size (which elasticity v1 guarantees keeps the
global batch invariant).

TPU-native shape: there is no torch-elastic; the agent owns the process
group directly.  It spawns the ranks with the same env contract as
``launcher/launch.py``, and on a member failure — instead of the launcher's
fail-fast exit — it

1. tears the remaining ranks down,
2. validates the surviving count against the elastic config
   (``compute_elastic_config(world_size=survivors)``, which also yields the
   micro-batch for the invariant global batch),
3. relaunches on the survivors with a fresh coordinator port and
   ``DS_ELASTIC_RESTART`` bumped; the training script resumes from the
   latest checkpoint tag (``engine.load_checkpoint`` with no tag).

Give-up conditions: ``max_restarts`` exhausted, or the surviving count is
not in the elastic set.

Preemption-aware (docs/RESILIENCE.md): ranks exiting with
``PREEMPTED_EXIT_CODE`` (``runtime/preemption.py`` — SIGTERM emergency
save taken, left on purpose) trigger a relaunch at the SAME world size
instead of a shrink; the checkpoint they just wrote is the resume point.

World-set detection (docs/RESILIENCE.md "Elastic training"): before every
relaunch the agent re-probes the AVAILABLE world via ``world_size_fn`` /
``--world-size-file`` (a file the scheduler or operator keeps current with
the allocatable worker count).  A probe larger than the surviving count
GROWS the next incarnation back — preempted capacity returning is as
routine as it leaving — and a probe smaller shrinks ahead of the failure
the doomed relaunch would hit.  The probe is validated against the
elastic set like any other world; training itself reshards on load (the
engine's ``_maybe_elastic_rescale`` keeps the global batch invariant).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from deepspeed_tpu.elasticity.elasticity import (
    ElasticityError, compute_elastic_config)
from deepspeed_tpu.utils.logging import logger

POLL_INTERVAL_S = 0.25


def _free_port(addr: str = "127.0.0.1") -> int:
    import socket

    with socket.socket() as s:
        s.bind((addr, 0))
        return s.getsockname()[1]


class DSElasticAgent:
    """Process-level elastic supervisor (see module docstring)."""

    def __init__(self, ds_config: Dict, user_script: str,
                 user_args: Optional[List[str]] = None, num_procs: int = 1,
                 master_addr: str = "127.0.0.1", master_port: int = 29600,
                 max_restarts: int = 3, env: Optional[Dict[str, str]] = None,
                 no_local_rank: bool = False, world_size_fn=None):
        self.ds_config = ds_config
        self.user_script = user_script
        self.user_args = list(user_args or [])
        self.num_procs = num_procs
        self.master_addr = master_addr
        self.master_port = master_port
        self.max_restarts = max_restarts
        self.base_env = dict(env if env is not None else os.environ)
        self.no_local_rank = no_local_rank
        # optional availability probe: () -> int | None, consulted before
        # every (re)launch; None/invalid readings fall back to the default
        self.world_size_fn = world_size_fn
        self.restart_count = 0

    @staticmethod
    def world_size_file_fn(path: str):
        """Probe reading the allocatable worker count from a file the
        scheduler/operator keeps current (``--world-size-file``).  A
        missing or unparseable file reads as None (keep the default)."""
        def probe() -> Optional[int]:
            try:
                with open(path) as fh:
                    return int(fh.read().strip())
            except (OSError, ValueError):
                return None
        return probe

    def _probe_world(self, default: int) -> int:
        """The available world for the next incarnation: the probe's
        answer when it gives a usable one, else ``default`` (bounded by
        the configured ceiling — a probe cannot grow past num_procs)."""
        if self.world_size_fn is None:
            return default
        try:
            avail = self.world_size_fn()
        except Exception as exc:  # a broken probe must not kill the agent
            logger.warning("elastic agent: world probe failed: %s", exc)
            return default
        if avail is None or int(avail) < 1:
            return default
        world = min(int(avail), self.num_procs)
        if world != default:
            logger.info("elastic agent: world probe reports %d available "
                        "(default was %d)", world, default)
        return world

    # -- membership validation ------------------------------------------
    def _validate_world(self, world_size: int) -> int:
        """Return the micro-batch for this world size, or raise if the
        elastic config rejects it."""
        _, _, micro = compute_elastic_config(
            self.ds_config, world_size=world_size, return_microbatch=True)
        return micro

    # -- one incarnation -------------------------------------------------
    def _spawn(self, world_size: int, port: int) -> List[subprocess.Popen]:
        procs = []
        for rank in range(world_size):
            env = dict(self.base_env)
            env["COORDINATOR_ADDRESS"] = f"{self.master_addr}:{port}"
            env["MASTER_ADDR"] = self.master_addr
            env["MASTER_PORT"] = str(port)
            env["RANK"] = str(rank)
            env["LOCAL_RANK"] = str(rank)
            env["WORLD_SIZE"] = str(world_size)
            env["DS_ELASTIC_RESTART"] = str(self.restart_count)
            env["DS_ELASTIC_WORLD_SIZE"] = str(world_size)
            cmd = [sys.executable, "-u", self.user_script]
            if not self.no_local_rank:
                cmd.append(f"--local_rank={rank}")
            cmd.extend(self.user_args)
            procs.append(subprocess.Popen(cmd, env=env))
        return procs

    @staticmethod
    def _terminate(procs: List[subprocess.Popen]) -> None:
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.time() + 10
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    p.kill()

    def run(self) -> int:
        world = self._probe_world(self.num_procs)
        port = self.master_port
        try:
            micro = self._validate_world(world)
            logger.info("elastic agent: starting world=%d (micro_batch=%d)",
                        world, micro)
        except Exception as exc:  # initial world must be valid
            logger.error("elastic agent: initial world invalid: %s", exc)
            return 1
        while True:
            procs = self._spawn(world, port)
            failed: List[tuple] = []
            alive = set(range(len(procs)))
            while alive and not failed:
                time.sleep(POLL_INTERVAL_S)
                for i in sorted(alive):
                    code = procs[i].poll()
                    if code is None:
                        continue
                    alive.discard(i)
                    if code != 0:
                        failed.append((i, code))
            if not failed:
                logger.info("elastic agent: job completed (restarts=%d)",
                            self.restart_count)
                return 0
            # drain the poll window: several ranks may have died together
            # (e.g. a host loss); shrinking by 1 per restart would burn one
            # max_restarts budget slot per doomed relaunch before converging.
            # Give co-failing ranks one grace interval to finish exiting
            # before the drain pass, or they'd be miscounted as survivors.
            time.sleep(POLL_INTERVAL_S)
            for i in sorted(alive):
                code = procs[i].poll()
                if code is not None:
                    alive.discard(i)
                    if code != 0:
                        failed.append((i, code))
            code = failed[0][1]
            logger.warning("elastic agent: rank(s) %s died (exit codes %s); "
                           "tearing down survivors",
                           [r for r, _ in failed], [c for _, c in failed])
            self._terminate(procs)
            if self.restart_count >= self.max_restarts:
                logger.error("elastic agent: max_restarts=%d exhausted",
                             self.max_restarts)
                return code
            # Preemption is not member loss: a rank exiting with the
            # preempted code (runtime/preemption.py) took its SIGTERM
            # emergency save and left ON PURPOSE — the host is coming
            # back, so relaunch at the SAME world size instead of
            # shrinking (still bounded by max_restarts).
            from deepspeed_tpu.runtime.preemption import PREEMPTED_EXIT_CODE

            if all(c == PREEMPTED_EXIT_CODE for _, c in failed):
                self.restart_count += 1
                port = _free_port(self.master_addr)
                # the probe may report the preempted capacity already back
                # (or more gone): relaunch at what is actually available
                new_world = self._probe_world(world)
                if new_world != world:
                    try:
                        self._validate_world(new_world)
                        world = new_world
                    except ElasticityError as exc:
                        logger.warning(
                            "elastic agent: probed world %d rejected by "
                            "elastic config (%s); keeping world=%d",
                            new_world, exc, world)
                logger.info(
                    "elastic agent: rank(s) %s preempted (clean emergency "
                    "save); restart #%d at world=%d — training resumes "
                    "from the latest checkpoint",
                    [r for r, _ in failed], self.restart_count, world)
                continue
            # changed-device-set detection: the probe's availability (hosts
            # may already be BACK — grow — or more may be gone) wins over
            # the naive survivors count when it validates
            new_world = self._probe_world(world - len(failed))
            if new_world < 1:
                logger.error("elastic agent: no survivors to restart with")
                return code
            try:
                micro = self._validate_world(new_world)
            except ElasticityError as exc:
                fallback = world - len(failed)
                if fallback != new_world and fallback >= 1:
                    logger.warning(
                        "elastic agent: probed world %d rejected by elastic "
                        "config (%s); trying the surviving count %d",
                        new_world, exc, fallback)
                    new_world = fallback
                    try:
                        micro = self._validate_world(new_world)
                    except ElasticityError as exc2:
                        logger.error("elastic agent: surviving world %d "
                                     "rejected by elastic config: %s",
                                     new_world, exc2)
                        return code
                else:
                    logger.error("elastic agent: surviving world %d rejected "
                                 "by elastic config: %s", new_world, exc)
                    return code
            self.restart_count += 1
            world = new_world
            # fresh coordinator port: the old one may sit in TIME_WAIT, and a
            # sequential guess could land on an occupied port (which would
            # masquerade as another member loss) — bind an ephemeral one
            port = _free_port(self.master_addr)
            logger.info("elastic agent: restart #%d at world=%d "
                        "(micro_batch=%d); training resumes from the latest "
                        "checkpoint", self.restart_count, world, micro)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="ds_elastic",
        description="Elastic training supervisor (restart-on-membership-change)")
    parser.add_argument("--ds_config", required=True,
                        help="path to a ds_config.json with an elasticity section")
    parser.add_argument("--num_procs", type=int, default=1)
    parser.add_argument("--master_addr", default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29600)
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--no_local_rank", action="store_true")
    parser.add_argument("--world_size_file", default=None,
                        help="file holding the currently-allocatable worker "
                             "count; re-read before every relaunch so the "
                             "next incarnation grows/shrinks to the actual "
                             "device set")
    parser.add_argument("user_script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    with open(args.ds_config) as fh:
        ds_config = json.load(fh)
    probe = (DSElasticAgent.world_size_file_fn(args.world_size_file)
             if args.world_size_file else None)
    agent = DSElasticAgent(ds_config, args.user_script, args.user_args,
                           num_procs=args.num_procs,
                           master_addr=args.master_addr,
                           master_port=args.master_port,
                           max_restarts=args.max_restarts,
                           no_local_rank=args.no_local_rank,
                           world_size_fn=probe)
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
