"""Shared restart/backoff state machine for process supervisors.

`tools/train_supervisor.py` (PR 8) and `tools/serve_supervisor.py` (the
serving-fleet replica supervisor) enforce the SAME exit-code contract —
clean exit, preempt-exit-after-emergency-save, crash-with-backoff,
budget exhaustion — and two hand-rolled copies of that ladder WILL
drift (different backoff caps, preemptions silently burning the crash
budget on one side).  This module is the single source of truth both
tools load (via the package when it is importable, else by file path —
the ``tools/router.py`` idiom), so the contract cannot fork.

Stdlib-only by design: supervisors run on operator boxes with no jax
install (dslint rule DSL003 pins the whole import closure).

The state machine (:class:`RestartPolicy`) is deliberately process-free:
``decide(exit_code)`` consumes one child exit and returns what to do
(``done`` / ``restart`` after ``delay`` / ``give_up``), mutating the
restart counters exactly once per exit.  The caller owns spawning,
waiting, and sleeping — which is what differs between a single training
job and an N-replica serving fleet.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, NamedTuple, Optional

# runtime/preemption.py carries the same default; every side reads the
# env override so the contract cannot drift silently in a deployment
PREEMPT_EXIT_CODE = int(os.environ.get("DS_PREEMPT_EXIT_CODE", "243"))

__all__ = ["PREEMPT_EXIT_CODE", "RestartDecision", "RestartPolicy",
           "write_status"]


def write_status(path: Optional[str], payload: Dict[str, Any]) -> None:
    """Atomically publish supervisor truth as a JSON file (``--status-file``
    on both supervisors): ladder counters, worker/replica states, restart
    timestamps — so operators and ``fleet_dump`` read state instead of
    scraping logs.  tmp + ``os.replace``: a reader never sees a torn
    write.  A ``None`` path no-ops; write failures are swallowed (a full
    disk must not take the supervisor down with it)."""
    if not path:
        return
    try:
        payload = dict(payload)
        payload["updated_unix"] = time.time()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True, default=str)
        os.replace(tmp, path)
    except OSError:
        pass


class RestartDecision(NamedTuple):
    """One consumed child exit: what the supervisor should do next."""

    action: str          # "done" | "restart" | "give_up"
    delay: float         # backoff seconds before the restart (0 = now)
    kind: str            # "completed" | "preempt" | "crash" | "exhausted"


class RestartPolicy:
    """Bounded-retry + exponential-backoff restart ladder.

    - exit ``0`` — done.
    - exit ``preempt_exit_code`` — the child took its SIGTERM emergency
      save and left ON PURPOSE: restart immediately, do NOT burn the
      crash budget (preemptions are routine scheduling events; N of
      them must never abandon a healthy job).
    - any other exit — a crash: restart after ``backoff_base * 2^n``
      seconds (capped at ``backoff_max``) until ``max_restarts`` crash
      restarts are exhausted, then give up.

    ``healthy_reset_s`` (optional): a child that ran at least this long
    before crashing resets the crash ladder first — a replica that
    crashes once a day must not exhaust a lifetime budget (the serving
    fleet's long-horizon mode; the train supervisor keeps the strict
    PR 8 ladder by leaving it ``None``).

    Counters (``restarts`` / ``crash_restarts`` / ``preempt_restarts`` /
    ``backoffs``) mutate exactly once per :meth:`decide` and carry the
    same meanings the PR 8 ``TrainSupervisor`` exposed.
    """

    def __init__(self, max_restarts: int = 3, backoff_base: float = 1.0,
                 backoff_max: float = 60.0,
                 preempt_exit_code: int = PREEMPT_EXIT_CODE,
                 healthy_reset_s: Optional[float] = None):
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.preempt_exit_code = int(preempt_exit_code)
        self.healthy_reset_s = healthy_reset_s
        self.restarts = 0            # restarts performed (any reason)
        self.crash_restarts = 0      # restarts that burned backoff budget
        self.preempt_restarts = 0
        self.backoffs: List[float] = []

    def counters(self) -> Dict[str, Any]:
        """Ladder truth for ``--status-file`` payloads (one schema for
        both supervisors)."""
        return {"max_restarts": self.max_restarts,
                "restarts": self.restarts,
                "crash_restarts": self.crash_restarts,
                "preempt_restarts": self.preempt_restarts,
                "backoffs": list(self.backoffs),
                "healthy_reset_s": self.healthy_reset_s}

    def decide(self, exit_code: int,
               ran_s: Optional[float] = None) -> RestartDecision:
        """Consume one child exit code; returns the action + backoff.

        ``ran_s`` (optional) is how long the incarnation ran — only used
        by the ``healthy_reset_s`` ladder reset."""
        if exit_code == 0:
            return RestartDecision("done", 0.0, "completed")
        if exit_code == self.preempt_exit_code:
            self.restarts += 1
            self.preempt_restarts += 1
            return RestartDecision("restart", 0.0, "preempt")
        if (self.healthy_reset_s is not None and ran_s is not None
                and ran_s >= self.healthy_reset_s):
            self.crash_restarts = 0
        if self.crash_restarts >= self.max_restarts:
            return RestartDecision("give_up", 0.0, "exhausted")
        self.restarts += 1
        self.crash_restarts += 1
        delay = min(self.backoff_max,
                    self.backoff_base * (2 ** (self.crash_restarts - 1)))
        self.backoffs.append(delay)
        return RestartDecision("restart", delay, "crash")
