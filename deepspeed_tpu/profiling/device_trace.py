"""Device-truth layer: perfetto trace post-processing for the named scopes
the engines already emit.

PR 3 deliberately shipped ``ds_comm_*_seconds`` as *host-window
attribution* (byte-weighted shares of the measured dispatch window —
monitor/comms.py) because a collective inside a compiled program cannot be
wall-clocked from the host.  The device truth was always in the trace:
every collective wrapper emits a ``ds_comm_<op>`` ``jax.named_scope``, the
train step carries ``ds_fwd_bwd`` / ``ds_optimizer_step``, and the serving
loop emits ``ds_serve_prefill`` / ``ds_serve_decode`` host ranges.  This
module closes the loop: jax 0.4.37's ``start_trace(...,
create_perfetto_trace=True)`` writes ``perfetto_trace.json.gz`` — plain
trace-event JSON, stdlib gzip+json parseable, no xplane proto dep — and
the post-processor here walks it, separates device tracks from host
threads via the trace's process/thread metadata, matches our named-scope
prefixes, and backfills the metrics registry with device-true series:

- ``ds_comm_<op>_device_seconds`` histograms (+ recomputed
  ``ds_comm_<op>_device_busbw_gbps`` when the caller knows the bytes) —
  kept DISTINCT from the PR 3 analytic ``ds_comm_<op>_seconds`` series,
  which stays the always-on cheap feed;
- a per-step phase breakdown ``ds_profile_{fwd_bwd,optimizer,comm,other,
  gap}_seconds`` where ``gap`` is device idle inside the captured window —
  the overlap-headroom number fine-grained-overlap work (T3,
  arXiv:2401.16677) optimizes against;
- serving-side device decode time vs host dispatch time
  (``ds_profile_serve_decode_{device,host}_seconds``), exposing the
  dispatch slack the sync-free decode path banks on.

Track classification, concretely:

- a *device process* is one whose ``process_name`` metadata starts with
  ``/device`` (TPU/GPU xplane exports one process per device plane);
  within it, *op rows* are threads whose name does not mark a summary lane
  (``Steps`` / ``XLA Modules`` / name-scope lines) — those lanes overlap
  op rows and would inflate the busy union;
- the CPU backend exports no device process; its XLA *runtime* threads
  carry op rows tagged ``args.hlo_op``, which this module accepts as
  device-proxy rows (CPU "device" time is host-thread time, but the
  busy/gap arithmetic still holds);
- when a trace holds NO device rows at all (pure host capture), the phase
  breakdown degrades to the host annotation ranges and says so
  (``"degraded": true``) — host attribution again, but labeled.

Scope matching scans event names AND string arg values (TPU op rows keep
the scope path in ``tf_op``-style args; dedicated name-scope lanes carry
it in the event name).  Per-scope time is an INTERVAL UNION per track
class, so nested/parent events never double-count.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

# RELATIVE imports, deliberately: tools/trace_report.py loads this module
# by file path under stub parent packages so an operator box never
# executes the jax-pulling ``deepspeed_tpu/__init__`` (dslint DSL003);
# monitor.comms / monitor.metrics are stdlib-only.
from ..monitor.comms import KNOWN_OPS, busbw_factor


def perfetto_supported() -> bool:
    """Whether this jax writes perfetto trace-event JSON (delegates to
    profiling/trace.py).  Lazy on purpose: only LIVE capture paths (the
    broker, TraceCapture) need jax — the offline parse half of this
    module stays importable with no jax installed."""
    from .trace import perfetto_supported as _probe  # dslint: disable=DSL003 -- live-capture path only; the offline parse (tools/trace_report.py) never calls it, and on an engine box jax is already present

    return _probe()

__all__ = ["find_perfetto_trace", "load_trace_events", "summarize_trace",
           "publish_summary", "analyze_capture", "ensure_registered",
           "ProfileBroker",
           "ProfileRequest", "get_profile_broker", "perfetto_supported",
           "TRAIN_SCOPES", "SERVE_SCOPES"]

# the named scopes the engines emit (see monitor/comms.py, runtime/engine.py,
# serving/engine.py); comm ops matched as ds_comm_<known op slug>
TRAIN_SCOPES = ("ds_fwd_bwd", "ds_optimizer_step")
SERVE_SCOPES = ("ds_serve_prefill", "ds_serve_decode")

_COMM_RE = re.compile(
    r"\bds_comm_(" + "|".join(sorted(KNOWN_OPS, key=len, reverse=True)) + r")\b")
_SCOPE_RE = re.compile(
    r"\b(" + "|".join(TRAIN_SCOPES + SERVE_SCOPES) + r")\b")

# summary lanes on a device process that overlap the op rows (step markers,
# whole-module spans, the name-scope band) — excluded from the busy union
_SUMMARY_LANE_RE = re.compile(r"steps|modules|scope|source", re.IGNORECASE)


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------


def find_perfetto_trace(path: str) -> Optional[str]:
    """Locate the perfetto JSON under a trace directory (jax writes it at
    ``<dir>/plugins/profile/<run>/perfetto_trace.json.gz``); accepts a
    direct file path too.  Newest wins when several runs exist."""
    if os.path.isfile(path):
        return path
    hits = glob.glob(os.path.join(path, "**", "perfetto_trace.json.gz"),
                     recursive=True)
    hits += glob.glob(os.path.join(path, "**", "*.perfetto-trace"),
                      recursive=True)
    return max(hits, key=os.path.getmtime) if hits else None


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """Read + normalize the trace-event JSON: returns complete-duration
    events as ``{"name", "ts", "dur", "args", "process", "thread"}`` with
    process/thread METADATA already resolved (``ts``/``dur`` stay in the
    file's microseconds)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        data = json.load(fh)
    raw = data["traceEvents"] if isinstance(data, dict) else data
    pnames: Dict[Any, str] = {}
    tnames: Dict[Tuple[Any, Any], str] = {}
    for e in raw:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pnames[e.get("pid")] = str(e.get("args", {}).get("name", ""))
        elif e.get("name") == "thread_name":
            tnames[(e.get("pid"), e.get("tid"))] = \
                str(e.get("args", {}).get("name", ""))
    out = []
    for e in raw:
        if e.get("ph") != "X":
            continue
        ts, dur = e.get("ts"), e.get("dur")
        if ts is None or dur is None:
            continue
        pid, tid = e.get("pid"), e.get("tid")
        out.append({"name": str(e.get("name", "")), "ts": float(ts),
                    "dur": float(dur), "args": e.get("args") or {},
                    "process": pnames.get(pid, ""),
                    "thread": tnames.get((pid, tid), "")})
    return out


def _is_device_op_row(ev: Dict[str, Any]) -> bool:
    """Op-granularity device work: rows on a ``/device`` process outside
    the summary lanes, or (CPU proxy) XLA-runtime rows tagged with the
    executed ``hlo_op``."""
    if ev["process"].startswith("/device"):
        return not _SUMMARY_LANE_RE.search(ev["thread"])
    return "hlo_op" in ev["args"]


def _is_device_row(ev: Dict[str, Any]) -> bool:
    """Any device-process row (op rows + name-scope/summary lanes) or CPU
    proxy op row — the pool scope matching draws from."""
    return ev["process"].startswith("/device") or "hlo_op" in ev["args"]


def _scope_matches(ev: Dict[str, Any]) -> List[str]:
    """Every ds_ scope this event belongs to, scanned from the event name
    and its string arg values (TPU op rows keep the scope path in args)."""
    hay = ev["name"]
    for v in ev["args"].values():
        if isinstance(v, str):
            hay += "\x00" + v
    out = [m.group(0) for m in _SCOPE_RE.finditer(hay)]
    out += ["ds_comm_" + m.group(1) for m in _COMM_RE.finditer(hay)]
    return sorted(set(out))


# -- interval arithmetic (all per-scope times are unions: nested or
# duplicated rows never double-count) ---------------------------------------


def _merge(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [intervals[0]]
    for s, e in intervals[1:]:
        ls, le = out[-1]
        if s <= le:
            out[-1] = (ls, max(le, e))
        else:
            out.append((s, e))
    return out


def _union_len(intervals: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in _merge(intervals))


def _subtract(a: List[Tuple[float, float]],
              b: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Interval set difference ``a - b`` (both get merged first)."""
    a, b = _merge(a), _merge(b)
    out: List[Tuple[float, float]] = []
    j = 0
    for s, e in a:
        cur = s
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < e:
            bs, be = b[k]
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if cur >= e:
                break
            k += 1
        if cur < e:
            out.append((cur, e))
    return out


def _clip(intervals: List[Tuple[float, float]], lo: float,
          hi: float) -> List[Tuple[float, float]]:
    return [(max(s, lo), min(e, hi)) for s, e in intervals
            if min(e, hi) > max(s, lo)]


def _intersect(a: List[Tuple[float, float]],
               b: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Interval set intersection ``a ∩ b`` (via ``a - (a - b)``)."""
    a = _merge(a)
    return _subtract(a, _subtract(a, b))


# ---------------------------------------------------------------------------
# summarization
# ---------------------------------------------------------------------------


def summarize_trace(trace_path: str,
                    steps: Optional[int] = None,
                    clock: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Walk one perfetto trace into the device-truth summary.

    Returns (durations in SECONDS)::

        {"source", "degraded", "steps", "window_s", "device_busy_s",
         "device_rows",
         "window_lo_us", "window_hi_us",             # raw trace-file ts
         "phases": {"fwd_bwd_s", "optimizer_s", "comm_s", "other_s",
                    "gap_s"},                       # sums to window_s
         "per_step": {... phases / steps ...},       # when steps known
         "comm_device": {op: {"seconds", "count", "max_s"}},
         "serve": {"decode_host_s", "decode_device_s",
                   "dispatch_slack_s", "decode_blocks",
                   "prefill_host_s", "prefill_device_s"} | None}

    ``window_lo_us``/``window_hi_us`` are in the FILE's clock domain —
    microseconds since the profiler-session start — the same domain
    ``/requestz?format=perfetto`` exports into, so a request span and the
    device phase tracks compare directly.  ``clock`` (the capturing
    ``TraceCapture.clock`` anchor) additionally translates the window
    onto the unix clock: ``summary["clock"] = {"anchor_unix",
    "window_unix_lo", "window_unix_hi", "source"}``.

    Phase accounting is exclusive by construction: ``comm`` is the union of
    device comm-scope time; ``fwd_bwd`` / ``optimizer`` are their scope
    unions minus comm; ``other`` is device-busy time in none of our
    scopes; ``gap`` is the device-idle remainder of the window — so the
    five phases partition the captured window exactly.  With no device
    rows at all the same arithmetic runs over the HOST annotation ranges
    and the result is flagged ``degraded`` (host attribution, the PR 3
    semantics, labeled as such).
    """
    path = find_perfetto_trace(trace_path)
    if path is None:
        raise FileNotFoundError(
            f"no perfetto_trace.json.gz under {trace_path!r} — was the "
            f"capture started with perfetto=True on a jax with "
            f"create_perfetto_trace support?")
    events = load_trace_events(path)

    dev_ops = [e for e in events if _is_device_op_row(e)]
    degraded = not dev_ops
    # scope pool: all device rows when we have them (op rows + dedicated
    # name-scope lanes), host rows otherwise
    pool = ([e for e in events if _is_device_row(e)] if not degraded
            else [e for e in events if not _is_device_row(e)])
    busy_rows = dev_ops if not degraded else []

    scope_iv: Dict[str, List[Tuple[float, float]]] = {}
    for e in pool:
        for scope in _scope_matches(e):
            scope_iv.setdefault(scope, []).append((e["ts"],
                                                   e["ts"] + e["dur"]))
    # host annotation ranges (always collected: the serving slack numbers
    # need them even on a device-true trace)
    host_iv: Dict[str, List[Tuple[float, float]]] = {}
    for e in events:
        if _is_device_row(e):
            continue
        for scope in _scope_matches(e):
            host_iv.setdefault(scope, []).append((e["ts"],
                                                  e["ts"] + e["dur"]))
    host_scoped: List[str] = []
    if degraded:
        scope_iv = host_iv
        busy_iv = [iv for ivs in host_iv.values() for iv in ivs]
    else:
        busy_iv = [(e["ts"], e["ts"] + e["dur"]) for e in busy_rows]
        merged_busy = _merge(busy_iv)
        # name-scope/summary lane rows can pad past the op rows or span
        # the idle between them: clamp every scope to the busy union so
        # the phase partition stays exact (phases + gap == window)
        scope_iv = {s: _clip_to(merged_busy, _merge(ivs))
                    for s, ivs in scope_iv.items()}
        # a scope with host ranges but NO device-row matches (CPU proxy
        # rows carry hlo_op tags, not scope paths) is attributed the
        # device-busy time INSIDE its host ranges — device-true durations,
        # host-bracketed assignment, reported in "host_scoped"
        for scope, hivs in host_iv.items():
            if scope_iv.get(scope):
                continue
            attributed = _clip_to(merged_busy, _merge(hivs))
            if attributed:
                scope_iv[scope] = attributed
                host_scoped.append(scope)

    def _clock_block(lo_us: float, hi_us: float) -> Dict[str, Any]:
        return {"anchor_unix": clock.get("unix"),
                "source": clock.get("source"),
                "window_unix_lo": clock.get("unix", 0.0) + lo_us * 1e-6,
                "window_unix_hi": clock.get("unix", 0.0) + hi_us * 1e-6}

    window_rows = busy_iv or [iv for ivs in scope_iv.values() for iv in ivs]
    if not window_rows:
        out = {"source": path, "degraded": True, "steps": steps,
               "window_s": 0.0, "device_busy_s": 0.0, "device_rows": 0,
               "window_lo_us": 0.0, "window_hi_us": 0.0,
               "overlapped_comm_s": 0.0,
               "phases": {"fwd_bwd_s": 0.0, "optimizer_s": 0.0,
                          "comm_s": 0.0, "other_s": 0.0, "gap_s": 0.0},
               "comm_device": {}, "serve": None}
        if clock is not None:
            # the documented clock contract holds on degraded summaries
            # too — those are exactly the captures someone is diagnosing
            out["clock"] = _clock_block(0.0, 0.0)
        return out
    lo = min(s for s, _ in window_rows)
    hi = max(e for _, e in window_rows)
    us = 1e-6  # file timestamps are microseconds

    comm_iv = _merge([iv for scope, ivs in scope_iv.items()
                      if scope.startswith("ds_comm_") for iv in ivs])
    fwd_iv = _merge(scope_iv.get("ds_fwd_bwd", []))
    opt_iv = _merge(scope_iv.get("ds_optimizer_step", []))
    serve_iv = _merge(scope_iv.get("ds_serve_prefill", [])
                      + scope_iv.get("ds_serve_decode", []))
    busy = _merge(_clip(busy_iv, lo, hi))
    comm_s = _union_len(comm_iv)
    fwd_s = _union_len(_subtract(fwd_iv, comm_iv))
    opt_s = _union_len(_subtract(opt_iv, comm_iv + fwd_iv))
    # comm concurrent with compute scopes — the comm the overlap schedule
    # HID.  The exclusive partition claims this time for ``comm`` and
    # subtracts it from fwd_bwd/optimizer exactly once (never from gap,
    # which is computed against the busy union), so overlapped comm is
    # not double-subtracted; this reports it explicitly so the hidden-
    # comm gauge and the bench ablation can read it.
    overlapped_s = _union_len(_intersect(comm_iv, fwd_iv + opt_iv))
    claimed = comm_iv + fwd_iv + opt_iv + (serve_iv if degraded else [])
    other_s = _union_len(_subtract(busy, claimed))
    gap_s = (hi - lo) - _union_len(busy)
    serve_claim = _union_len(_subtract(serve_iv, comm_iv + fwd_iv + opt_iv)) \
        if degraded else 0.0

    comm_device: Dict[str, Dict[str, float]] = {}
    if not degraded:
        for scope, ivs in scope_iv.items():
            if not scope.startswith("ds_comm_"):
                continue
            merged = _merge(ivs)
            if not merged:   # scope clipped to nothing against busy time
                continue
            comm_device[scope[len("ds_comm_"):]] = {
                "seconds": _union_len(merged) * us,
                "count": len(merged),
                "max_s": max(e - s for s, e in merged) * us,
            }

    serve = None
    dec_host = _merge(host_iv.get("ds_serve_decode", []))
    pre_host = _merge(host_iv.get("ds_serve_prefill", []))
    if dec_host or pre_host:
        dev_in_dec = _union_len(_clip_to(busy, dec_host))
        dev_in_pre = _union_len(_clip_to(busy, pre_host))
        serve = {
            "decode_blocks": len(dec_host),
            "decode_host_s": _union_len(dec_host) * us,
            "decode_device_s": dev_in_dec * us,
            "dispatch_slack_s": max(0.0, _union_len(dec_host) - dev_in_dec)
            * us,
            "prefill_host_s": _union_len(pre_host) * us,
            "prefill_device_s": dev_in_pre * us,
        }

    n_steps = steps
    if n_steps is None and opt_iv:
        n_steps = len(opt_iv)
    phases = {"fwd_bwd_s": fwd_s * us, "optimizer_s": opt_s * us,
              "comm_s": comm_s * us,
              "other_s": (other_s + serve_claim) * us, "gap_s": gap_s * us}
    out = {"source": path, "degraded": degraded, "steps": n_steps,
           "window_s": (hi - lo) * us, "device_busy_s": _union_len(busy) * us,
           "device_rows": len(dev_ops), "host_scoped": sorted(host_scoped),
           "window_lo_us": lo, "window_hi_us": hi,
           "overlapped_comm_s": overlapped_s * us,
           "phases": phases, "comm_device": comm_device, "serve": serve}
    if clock is not None:
        out["clock"] = _clock_block(lo, hi)
    if n_steps:
        out["per_step"] = {k: v / n_steps for k, v in phases.items()}
    return out


def _clip_to(intervals: List[Tuple[float, float]],
             windows: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Restrict an interval set to a union of windows."""
    out = []
    for lo, hi in windows:
        out.extend(_clip(intervals, lo, hi))
    return out


# ---------------------------------------------------------------------------
# registry backfill
# ---------------------------------------------------------------------------

_PROFILE_GAUGES = ("ds_profile_fwd_bwd_seconds", "ds_profile_optimizer_seconds",
                   "ds_profile_comm_seconds", "ds_profile_other_seconds",
                   "ds_profile_gap_seconds", "ds_profile_window_seconds",
                   "ds_profile_steps",
                   "ds_profile_serve_decode_host_seconds",
                   "ds_profile_serve_decode_device_seconds",
                   "ds_profile_serve_dispatch_slack_seconds")

# single source of truth for the overlap gauge help strings — registered
# here AND at engine init (docs/OBSERVABILITY.md "Overlap")
OVERLAP_GAUGES = {
    "ds_overlap_buckets":
        "layer-chunked overlap schedule bucket count "
        "(0 = overlap_comm off/ineligible)",
    "ds_overlap_hidden_comm_seconds_est":
        "per-step device comm time measured CONCURRENT with compute "
        "scopes in the last trace capture (the comm the overlap schedule "
        "hid; 0 until a capture runs)",
}


def ensure_registered(registry) -> None:
    """Register the device-truth instrument family up front (namespace
    guard + exporter warm-up; recording still gates on the registry)."""
    for name in _PROFILE_GAUGES:
        registry.gauge(name, "device-true profile (last capture; see "
                             "docs/OBSERVABILITY.md 'Device truth')")
    for name, help_ in OVERLAP_GAUGES.items():
        registry.gauge(name, help_)
    for op in KNOWN_OPS:
        registry.histogram(
            f"ds_comm_{op}_device_seconds",
            f"device-true {op} scope time per capture (perfetto "
            f"post-processor; distinct from the analytic ds_comm_{op}_"
            f"seconds host attribution)")
        registry.gauge(
            f"ds_comm_{op}_device_busbw_gbps",
            f"bus bandwidth recomputed from device-true {op} time")


def publish_summary(summary: Dict[str, Any], registry=None,
                    bytes_per_op: Optional[Dict[str, Tuple[int, int]]] = None
                    ) -> None:
    """Backfill the registry from a :func:`summarize_trace` result.

    ``bytes_per_op`` maps op slug -> (payload bytes moved inside the
    captured window, world size) — the engine knows both from its analytic
    comm plan — enabling the recomputed device busbw gauge.  The analytic
    ``ds_comm_<op>_seconds`` series is NEVER touched here: device truth
    lands only in ``*_device_*`` names.
    """
    if registry is None:
        from ..monitor.metrics import get_registry

        registry = get_registry()
    phases = summary["phases"]
    per = summary.get("per_step") or phases
    g = registry.gauge
    g("ds_profile_fwd_bwd_seconds").set(per["fwd_bwd_s"])
    g("ds_profile_optimizer_seconds").set(per["optimizer_s"])
    g("ds_profile_comm_seconds").set(per["comm_s"])
    g("ds_profile_other_seconds").set(per["other_s"])
    g("ds_profile_gap_seconds").set(per["gap_s"])
    g("ds_profile_window_seconds").set(summary["window_s"])
    g("ds_profile_steps").set(summary.get("steps") or 0)
    # measured comm∩compute per step — backfills the engine-registered
    # overlap gauge (docs/OBSERVABILITY.md "Overlap")
    g("ds_overlap_hidden_comm_seconds_est",
      OVERLAP_GAUGES["ds_overlap_hidden_comm_seconds_est"]).set(
        summary.get("overlapped_comm_s", 0.0)
        / max(1, summary.get("steps") or 1))
    for op, rec in summary.get("comm_device", {}).items():
        registry.histogram(f"ds_comm_{op}_device_seconds").record(
            rec["seconds"])
        if bytes_per_op and op in bytes_per_op and rec["seconds"] > 0:
            nbytes, world = bytes_per_op[op]
            alg = nbytes / rec["seconds"] / 1e9
            registry.gauge(f"ds_comm_{op}_device_busbw_gbps").set(
                alg * busbw_factor(op, world))
    serve = summary.get("serve")
    if serve:
        g("ds_profile_serve_decode_host_seconds").set(serve["decode_host_s"])
        g("ds_profile_serve_decode_device_seconds").set(
            serve["decode_device_s"])
        g("ds_profile_serve_dispatch_slack_seconds").set(
            serve["dispatch_slack_s"])


def analyze_capture(trace_dir: str, steps: int,
                    bytes_per_op: Optional[Dict[str, Tuple[int, int]]] = None,
                    clock: Optional[Dict[str, Any]] = None,
                    **tags: Any) -> Dict[str, Any]:
    """Summarize + tag + registry-backfill in one call — the shared tail
    of every capture lifecycle (training aux slot, serving ``/profilez``):
    ``tags`` (e.g. ``trigger=\"watchdog\"``, ``engine=\"serving\"``) land
    on the returned summary verbatim; ``clock`` (the capture's
    ``TraceCapture.clock`` anchor) translates the window onto the unix
    clock for cross-file correlation (``/requestz``)."""
    summary = summarize_trace(trace_dir, steps=steps, clock=clock)
    summary.update(tags)
    publish_summary(summary, bytes_per_op=bytes_per_op)
    return summary


# ---------------------------------------------------------------------------
# on-demand capture broker (/profilez)
# ---------------------------------------------------------------------------


class ProfileRequest:
    """One on-demand capture: created by the HTTP thread, claimed and
    fulfilled by whichever live engine hits its next step boundary."""

    def __init__(self, steps: int, trace_dir: Optional[str] = None):
        self.steps = max(1, int(steps))
        self.trace_dir = trace_dir
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None
        self._done = threading.Event()

    def finish(self, summary: Dict[str, Any]) -> None:
        self.result = summary
        self._done.set()

    def fail(self, message: str) -> None:
        self.error = message
        self._done.set()

    def wait(self, timeout: float) -> Dict[str, Any]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"profile capture did not complete within {timeout:.0f}s "
                f"(is an engine stepping?)")
        if self.error is not None:
            raise RuntimeError(self.error)
        return self.result


class ProfileBroker:
    """Single-slot handoff between the metrics HTTP server and the live
    engines.  ``submit`` parks one request; engines check :attr:`pending`
    (one attribute load per step — the hot-path cost) and ``claim`` it at
    a step boundary; the claimer runs the windowed capture, post-processes,
    and resolves the request.  One capture at a time: jax has a single
    global profiler session."""

    # dslint DSL006: the HTTP thread and N engine threads race on the
    # single slot — every transition holds the lock (``pending`` is READ
    # lock-free as the engines' one-attribute-load fast path; writes are
    # what must serialize)
    _dslint_shared = {"pending": "lock:_lock", "_claimed": "lock:_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self.pending: Optional[ProfileRequest] = None
        self._claimed: Optional[ProfileRequest] = None

    def submit(self, steps: int,
               trace_dir: Optional[str] = None) -> ProfileRequest:
        with self._lock:
            if self.pending is not None or self._claimed is not None:
                raise RuntimeError("a profile capture is already in flight")
            req = ProfileRequest(steps, trace_dir)
            self.pending = req
            return req

    def claim(self) -> Optional[ProfileRequest]:
        with self._lock:
            req = self.pending
            if req is not None:
                self.pending = None
                self._claimed = req
            return req

    def resolve(self, req: ProfileRequest, summary=None,
                error: Optional[str] = None) -> None:
        with self._lock:
            if self._claimed is req:
                self._claimed = None
        if error is not None:
            req.fail(error)
        else:
            req.finish(summary)

    def cancel(self, req: ProfileRequest) -> None:
        """Abandon a timed-out request so the slot frees: clears it from
        ``pending`` (nobody claimed it) AND from ``_claimed`` (an engine
        claimed it but stopped stepping before the window closed — leaving
        it there would 409 every later submit forever).  A late
        ``resolve`` from the original claimer is harmless: it only sets an
        event nobody waits on."""
        with self._lock:
            if self.pending is req:
                self.pending = None
            if self._claimed is req:
                self._claimed = None


_BROKER = ProfileBroker()


def get_profile_broker() -> ProfileBroker:
    """The process-global broker ``/profilez`` submits to and every live
    engine polls at its step boundary."""
    return _BROKER
