"""Always-on continuous profiler (ISSUE 20 tentpole).

Every device-truth number used to be operator-triggered: ``/profilez`` is
one-shot, so ``ds_comm_<op>_device_seconds`` only existed while someone
was watching.  This module turns the existing capture/decompose machinery
(``profiling/trace.py`` TraceCapture + ``profiling/device_trace.py``)
into a scheduled, low-duty-cycle attribution feed:

- the training engine's boundary tick and the serving loop drive a
  :class:`ContinuousProfiler`; every ``every_steps`` steps or
  ``every_seconds`` seconds (whichever comes FIRST), it opens a short
  TraceCapture window — unless the projected capture overhead would push
  the cumulative duty cycle past ``max_duty_cycle`` (default ≤1% of run
  wall clock), in which case the window is deferred;
- each closed window is decomposed offline via
  ``device_trace.analyze_capture`` (feeding the one registry the
  operator-triggered paths feed: ``ds_comm_<op>_device_seconds``,
  ``ds_profile_*``) and additionally committed as
  ``ds_prof_scope_device_seconds{scope=}`` + ``ds_prof_window_*``
  coverage/overhead gauges;
- window summaries persist to a bounded on-disk ring
  (``profile_history/ds_prof_window_<seq>.json``, retention by count AND
  bytes, atomic tmp+``os.replace``) that ``GET /profilez/history``,
  ``tools/trace_report.py --history``, ``tools/metrics_dump.py
  --profile`` and ``fleet_dump --profiles`` all read;
- a window-over-window differ names the regressing scope when the
  step-time decomposition drifts past tolerance (flight event
  ``prof_regression`` + ``ds_prof_regressions_total{scope=}``); the
  tolerance semantics — substring rules, first match wins — are the
  ``tools/perf_ledger.py`` contract, and perf_ledger's
  ``--profile-history`` mode runs this differ over a ring on disk.

Layout contract: everything above the ``live capture half`` marker is
stdlib-only with RELATIVE imports, so jax-less operator tools load this
file by path under stub packages (the fleet_dump/trace_report idiom;
dslint rule DSL003 pins the closure).  The live half lazily imports
TraceCapture (which pulls jax) only when a window actually opens.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .device_trace import analyze_capture, perfetto_supported

SCHEMA_VERSION = 1

# phase scopes every window carries (a partition of the window: the five
# per-step phase seconds sum to the per-step wall clock)
PHASE_SCOPES = ("fwd_bwd", "optimizer", "comm", "other", "gap")

# regression-tolerance semantics shared with tools/perf_ledger.py:
# (substring, tol) rules, FIRST match wins, default otherwise.  All
# window scopes are seconds — lower is better; a relative increase past
# tolerance is a regression.  gap/other are the noisy remainder lanes,
# so they get a looser default bar.
DEFAULT_TOLERANCE = 0.25
SCOPE_TOLERANCES: Tuple[Tuple[str, float], ...] = (
    ("gap", 0.50),
    ("other", 0.50),
)

_WINDOW_RE = re.compile(r"^ds_prof_window_(\d+)\.json$")


def tolerance_for(name: str,
                  tolerances: Optional[List[Tuple[str, float]]] = None,
                  default: float = DEFAULT_TOLERANCE) -> float:
    """First substring match wins (the perf_ledger ``_tolerance_for``
    contract), falling back to the built-in scope rules, then default."""
    for sub, tol in list(tolerances or []) + list(SCOPE_TOLERANCES):
        if sub in name:
            return float(tol)
    return float(default)


# ---------------------------------------------------------------------------
# history ring (offline half — jax-free)
# ---------------------------------------------------------------------------


class HistoryRing:
    """Bounded on-disk ring of window summaries.

    One JSON file per window (``ds_prof_window_<seq>.json``, monotonic
    sequence numbers), written atomically (tmp + ``os.replace``, the
    checkpoint latest-pointer idiom) so a reader — the HTTP handler, a
    fleet scrape, an operator tool — never sees a torn file.  Retention
    prunes oldest-first by BOTH count (``max_windows``) and total bytes
    (``max_bytes``)."""

    def __init__(self, directory: str, max_windows: int = 64,
                 max_bytes: int = 4 << 20):
        self.directory = directory
        self.max_windows = max(1, int(max_windows))
        self.max_bytes = max(1, int(max_bytes))

    def paths(self) -> List[str]:
        """Window files oldest-first (by sequence number)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        out = []
        for n in names:
            m = _WINDOW_RE.match(n)
            if m:
                out.append((int(m.group(1)), os.path.join(self.directory, n)))
        return [p for _, p in sorted(out)]

    def next_seq(self) -> int:
        paths = self.paths()
        if not paths:
            return 1
        m = _WINDOW_RE.match(os.path.basename(paths[-1]))
        return int(m.group(1)) + 1 if m else 1

    def append(self, window: Dict[str, Any]) -> str:
        """Atomically persist one window summary; prune; return its path."""
        os.makedirs(self.directory, exist_ok=True)
        seq = int(window.get("seq") or self.next_seq())
        window["seq"] = seq
        path = os.path.join(self.directory, f"ds_prof_window_{seq:08d}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(window, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
        self.prune()
        return path

    def prune(self) -> None:
        paths = self.paths()
        sizes = {}
        for p in paths:
            try:
                sizes[p] = os.path.getsize(p)
            except OSError:
                sizes[p] = 0
        total = sum(sizes.values())
        while paths and (len(paths) > self.max_windows
                         or total > self.max_bytes):
            victim = paths.pop(0)
            total -= sizes.get(victim, 0)
            try:
                os.unlink(victim)
            except OSError:
                pass

    @staticmethod
    def load(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None   # pruned underneath us, or torn by a crash

    def latest(self, n: int = 1) -> List[Dict[str, Any]]:
        """Newest ``n`` windows, oldest-first."""
        out = []
        for p in self.paths()[-max(0, int(n)):]:
            w = self.load(p)
            if w is not None:
                out.append(w)
        return out


# ---------------------------------------------------------------------------
# window schema + differ (offline half — jax-free)
# ---------------------------------------------------------------------------


def build_window(summary: Dict[str, Any], *, engine: str, step: int,
                 capture_wall_s: float, coverage_ratio: float,
                 overhead_ratio: float,
                 trigger: str = "continuous") -> Dict[str, Any]:
    """Compact one ``summarize_trace`` result into the persisted window
    record.  ``scopes`` holds PER-STEP device-seconds and is an exact
    partition of the per-step wall clock (the five phases), plus one
    ``comm_<op>`` entry per device-true collective; the raw
    ``comm_device`` table and the ``clock`` anchors ride along verbatim
    so fleet merges can place the window on the shared unix clock."""
    per = summary.get("per_step") or summary["phases"]
    steps = summary.get("steps") or 1
    scopes = {name: per[name + "_s"] for name in PHASE_SCOPES}
    for op, rec in (summary.get("comm_device") or {}).items():
        scopes["comm_" + op] = rec["seconds"] / max(1, steps)
    return {
        "schema_version": SCHEMA_VERSION,
        "engine": engine,
        "trigger": trigger,
        "step": int(step),
        "steps": steps,
        "degraded": bool(summary.get("degraded")),
        "source": summary.get("source"),
        "window_s": summary["window_s"],
        "device_busy_s": summary["device_busy_s"],
        "busy_ratio": (summary["device_busy_s"] / summary["window_s"]
                       if summary["window_s"] else 0.0),
        "capture_wall_s": capture_wall_s,
        "coverage_ratio": coverage_ratio,
        "overhead_ratio": overhead_ratio,
        "clock": summary.get("clock"),
        "scopes": scopes,
        "comm_device": summary.get("comm_device") or {},
    }


def diff_windows(prev: Dict[str, Any], cur: Dict[str, Any], *,
                 default_tol: float = DEFAULT_TOLERANCE,
                 tolerances: Optional[List[Tuple[str, float]]] = None,
                 min_seconds: float = 5e-5) -> List[Dict[str, Any]]:
    """Window-over-window regression triage: compare per-step scope
    device-seconds (plus the synthesized ``step_time`` = per-step wall
    clock) and name every scope whose time grew past tolerance.

    Same shape as ``perf_ledger.find_regressions``: relative drift
    ``(cur - prev) / prev`` against a substring-matched tolerance; scopes
    below the ``min_seconds`` noise floor in the BASELINE window are
    skipped (a 2us scope tripling is measurement noise, not a finding).
    Returns regressions sorted worst-first."""
    def scope_map(w: Dict[str, Any]) -> Dict[str, float]:
        out = dict(w.get("scopes") or {})
        steps = w.get("steps") or 1
        if w.get("window_s"):
            out["step_time"] = w["window_s"] / max(1, steps)
        return out

    base, now = scope_map(prev), scope_map(cur)
    out = []
    for scope, prev_s in base.items():
        if prev_s < min_seconds:
            continue
        cur_s = now.get(scope)
        if cur_s is None:
            continue
        tol = tolerance_for(scope, tolerances, default_tol)
        rel = (cur_s - prev_s) / prev_s
        if rel > tol:
            out.append({"scope": scope, "prev_s": prev_s, "cur_s": cur_s,
                        "rel": rel, "tol": tol})
    return sorted(out, key=lambda r: -r["rel"])


def render_window(window: Dict[str, Any]) -> str:
    """Terminal render of one window record (shared by ``trace_report
    --history`` and the fleet/metrics dump tools' profile views)."""
    def pct(v: float) -> str:
        return f"{100.0 * v:.2f}%"

    head = (f"window #{window.get('seq', '?')} engine={window.get('engine')}"
            f" step={window.get('step')}: {window.get('steps')} step(s), "
            f"{window.get('window_s', 0.0) * 1e3:.3f}ms wall, device busy "
            f"{pct(window.get('busy_ratio', 0.0))}")
    lines = [head]
    if window.get("degraded"):
        lines.append("NOTE: degraded (host-range attribution only)")
    lines.append(f"run coverage {pct(window.get('coverage_ratio', 0.0))}, "
                 f"capture overhead {pct(window.get('overhead_ratio', 0.0))}")
    scopes = sorted((window.get("scopes") or {}).items(),
                    key=lambda kv: -kv[1])
    steps = window.get("steps") or 1
    wall = window.get("window_s", 0.0) / max(1, steps)
    rows = []
    for name, sec in scopes:
        if sec <= 0.0:
            continue
        share = f"{100.0 * sec / wall:.1f}%" if wall else ""
        rows.append([name, f"{sec * 1e3:.4f}ms", share])
    if rows:
        widths = [max(len(r[i]) for r in [["scope", "per-step", "share"]]
                      + rows) for i in range(3)]
        lines.append("")
        lines.append("  ".join(c.ljust(w) for c, w in
                               zip(["scope", "per-step", "share"], widths)))
        lines.append("  ".join("-" * w for w in widths))
        for r in rows:
            lines.append("  ".join(c.ljust(w)
                                   for c, w in zip(r, widths)).rstrip())
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# live capture half (imports that pull jax stay lazy below this line)
# ---------------------------------------------------------------------------

# process-global directory of live profilers, keyed by engine kind —
# the /profilez/history handler reads it; latest registration wins.
# dslint DSL006: assignment under _ACTIVE_LOCK; the HTTP thread only
# reads (dict snapshot) — GIL-atomic.
_ACTIVE: Dict[str, "ContinuousProfiler"] = {}
_ACTIVE_LOCK = threading.Lock()


def history_snapshot(limit: int = 8) -> Dict[str, Any]:
    """Latest windows of every live profiler in this process — the
    ``GET /profilez/history`` payload (and the fleet scrape unit)."""
    with _ACTIVE_LOCK:
        active = sorted(_ACTIVE.items())
    windows: List[Dict[str, Any]] = []
    for _, prof in active:
        windows.extend(prof.ring.latest(limit))
    windows.sort(key=lambda w: (str(w.get("engine")), w.get("seq") or 0))
    return {"engines": [name for name, _ in active], "windows": windows}


class ContinuousProfiler:
    """Scheduled TraceCapture windows + offline decompose + history ring.

    The owning engine calls :meth:`maybe_begin` at a step boundary when no
    other capture slot owns the one global jax profiler session, and
    :meth:`after_step` after every completed step.  Disabled is not a
    state this class has — the engines keep ``self._cprof = None`` and
    one ``is not None`` branch per boundary (the PR 3 contract)."""

    def __init__(self, *, engine: str = "train",
                 every_steps: int = 200, every_seconds: float = 120.0,
                 capture_steps: int = 2, max_duty_cycle: float = 0.01,
                 history_dir: str = "profile_history",
                 max_windows: int = 64, max_bytes: int = 4 << 20,
                 regression_tolerance: float = DEFAULT_TOLERANCE,
                 tolerances: Optional[List[Tuple[str, float]]] = None,
                 min_scope_seconds: float = 5e-5,
                 bytes_per_op_fn: Optional[Callable[[int], dict]] = None,
                 registry=None, flight=None,
                 clock: Callable[[], float] = time.perf_counter):
        self.engine = engine
        self.every_steps = max(1, int(every_steps))
        self.every_seconds = float(every_seconds)
        self.capture_steps = max(1, int(capture_steps))
        self.max_duty_cycle = float(max_duty_cycle)
        self.regression_tolerance = float(regression_tolerance)
        self.tolerances = list(tolerances or [])
        self.min_scope_seconds = float(min_scope_seconds)
        self.ring = HistoryRing(history_dir, max_windows=max_windows,
                                max_bytes=max_bytes)
        self._bytes_per_op_fn = bytes_per_op_fn
        self._registry = registry
        self._flight = flight
        self._clock = clock
        self._t0 = clock()
        self._last_t = self._t0         # end of the previous window
        self._last_step = 0
        self._cap = None                # live TraceCapture, else None
        self._cap_t0 = 0.0
        self._captured_s = 0.0          # window wall covered so far
        self._overhead_s = 0.0          # capture + decompose wall so far
        self.windows = 0
        self.skipped_duty = 0           # deferrals by the duty-cycle cap
        # resume against an existing ring: the differ baselines on the
        # newest persisted window, so a restart keeps triaging
        prev = self.ring.latest(1)
        self._prev_window = prev[-1] if prev else None
        with _ACTIVE_LOCK:
            _ACTIVE[engine] = self

    # -- scheduling ------------------------------------------------------

    @property
    def active(self) -> bool:
        return self._cap is not None

    def due(self, upcoming_step: int) -> bool:
        """Every N steps or T seconds, whichever comes first."""
        if upcoming_step - self._last_step >= self.every_steps:
            return True
        return self._clock() - self._last_t >= self.every_seconds

    def _duty_ok(self) -> bool:
        """Projected duty cycle stays under the cap: the cost of the NEXT
        window is estimated from the measured per-window overhead so far
        (the first window is always admitted — nothing measured yet)."""
        if self.windows == 0:
            return True
        elapsed = max(self._clock() - self._t0, 1e-9)
        est = self._overhead_s / self.windows
        return (self._overhead_s + est) <= self.max_duty_cycle * elapsed

    def maybe_begin(self, upcoming_step: int) -> bool:
        """Open a capture window covering ``upcoming_step ..
        upcoming_step + capture_steps - 1``.  The CALLER guarantees no
        other capture slot (profile_trace, /profilez, watchdog) owns the
        global profiler session."""
        if self._cap is not None or not perfetto_supported():
            return False
        if not self.due(upcoming_step):
            return False
        if not self._duty_ok():
            self.skipped_duty += 1
            # push the timer cadence back so the deferral doesn't retry
            # every single boundary while the budget recovers
            self._last_t = self._clock()
            return False
        from .trace import TraceCapture  # dslint: disable=DSL003 -- live-capture path only; the offline half (tools/trace_report.py --history, perf_ledger --profile-history) never opens a window, and on an engine box jax is already present
        trace_dir = os.path.join(self.ring.directory, "_capture")
        cap = TraceCapture(trace_dir, start_step=upcoming_step,
                           num_steps=self.capture_steps, perfetto=True)
        try:
            cap.maybe_start(upcoming_step)
        except Exception as exc:  # profiler session contention, FS errors
            self._count_failure()
            self._record_flight("prof_capture_failed", error=str(exc))
            self._last_t = self._clock()
            return False
        if not cap.active:
            return False
        self._cap = cap
        self._cap_t0 = self._clock()
        return True

    def after_step(self, completed_step: int) -> Optional[Dict[str, Any]]:
        """Close + decompose + commit when the window just finished;
        returns the persisted window record then, else None."""
        if self._cap is None:
            return None
        try:
            trace_dir = self._cap.after_step(completed_step)
        except Exception as exc:
            self._cap = None
            self._count_failure()
            self._record_flight("prof_capture_failed", error=str(exc))
            return None
        if trace_dir is None:
            return None
        return self._finish(trace_dir, completed_step)

    def close(self) -> None:
        """Abandon a still-open window (engine shutdown mid-capture)."""
        cap, self._cap = self._cap, None
        if cap is not None:
            try:
                cap.close()
            except Exception:
                pass

    # -- decompose + commit ---------------------------------------------

    def _finish(self, trace_dir: str,
                completed_step: int) -> Optional[Dict[str, Any]]:
        cap, self._cap = self._cap, None
        now = self._clock()
        window_wall = now - self._cap_t0
        try:
            bytes_per_op = (self._bytes_per_op_fn(cap.num_steps)
                            if self._bytes_per_op_fn else None)
            summary = analyze_capture(
                trace_dir, cap.num_steps, bytes_per_op=bytes_per_op,
                clock=cap.clock, trigger="continuous", engine=self.engine)
        except Exception as exc:
            self._count_failure()
            self._record_flight("prof_decompose_failed", error=str(exc))
            return None
        finally:
            shutil.rmtree(trace_dir, ignore_errors=True)
            # book the whole capture+decompose cost before the next
            # scheduling decision reads the duty-cycle ledger
            decompose_done = self._clock()
            self._captured_s += window_wall
            self._overhead_s += decompose_done - self._cap_t0
            self._last_t = decompose_done
            self._last_step = completed_step
        elapsed = max(self._clock() - self._t0, 1e-9)
        window = build_window(
            summary, engine=self.engine, step=completed_step,
            capture_wall_s=window_wall,
            coverage_ratio=self._captured_s / elapsed,
            overhead_ratio=self._overhead_s / elapsed)
        self.ring.append(window)
        self.windows += 1
        regressions = []
        if self._prev_window is not None:
            regressions = diff_windows(
                self._prev_window, window,
                default_tol=self.regression_tolerance,
                tolerances=self.tolerances,
                min_seconds=self.min_scope_seconds)
        self._prev_window = window
        self._publish(window, regressions)
        return window

    # -- registry / flight commits --------------------------------------

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ..monitor.metrics import get_registry

        return get_registry()

    def _count_failure(self) -> None:
        try:
            self._reg().counter("ds_prof_capture_failures_total").inc()
        except Exception:
            pass

    def _record_flight(self, kind: str, **fields: Any) -> None:
        flight = self._flight
        if flight is None:
            from ..monitor.flight_recorder import get_flight_recorder

            flight = get_flight_recorder()
        try:
            flight.record(kind, engine=self.engine, **fields)
        except Exception:
            pass

    def _publish(self, window: Dict[str, Any],
                 regressions: List[Dict[str, Any]]) -> None:
        reg = self._reg()
        g = reg.gauge
        g("ds_prof_window_seconds").set(window["window_s"])
        g("ds_prof_window_steps").set(window["steps"])
        g("ds_prof_window_coverage_ratio").set(window["coverage_ratio"])
        g("ds_prof_window_overhead_ratio").set(window["overhead_ratio"])
        for scope, sec in window["scopes"].items():
            g("ds_prof_scope_device_seconds", labels={"scope": scope}).set(sec)
        reg.counter("ds_prof_windows_total").inc()
        for r in regressions:
            reg.counter("ds_prof_regressions_total",
                        "window-over-window scope regressions flagged by "
                        "the profile differ",
                        labels={"scope": r["scope"]}).inc()
            self._record_flight(
                "prof_regression", scope=r["scope"], step=window["step"],
                prev_s=round(r["prev_s"], 9), cur_s=round(r["cur_s"], 9),
                rel=round(r["rel"], 4), tol=r["tol"])


def ensure_registered(registry) -> None:
    """Pre-register the bare ``ds_prof_*`` series (namespace guard +
    exporter warm-up, like ``device_trace.ensure_registered``).  The
    labeled families — ``ds_prof_scope_device_seconds{scope=}`` and
    ``ds_prof_regressions_total{scope=}`` — register at first use with
    their labels (the ``ds_slo_burn_total{rule=}`` idiom): a name must be
    uniformly labeled or uniformly bare."""
    registry.gauge("ds_prof_window_seconds",
                   "wall length of the last continuous-profiler window")
    registry.gauge("ds_prof_window_steps",
                   "steps inside the last continuous-profiler window")
    registry.gauge("ds_prof_window_coverage_ratio",
                   "fraction of run wall clock covered by completed "
                   "continuous-profiler windows")
    registry.gauge("ds_prof_window_overhead_ratio",
                   "capture+decompose wall time as a fraction of run wall "
                   "clock (duty cycle actually paid; capped by config)")
    registry.counter("ds_prof_windows_total",
                     "completed continuous-profiler windows")
    registry.counter("ds_prof_capture_failures_total",
                     "continuous-profiler captures that failed to open, "
                     "close, or decompose")
