"""Profiling subsystem (reference: ``deepspeed/profiling/``, SURVEY.md §5.1):
the FLOPS profiler built on XLA cost analysis lives in ``flops_profiler``;
``trace`` adds xplane trace capture + host-side TraceAnnotation ranges."""

from deepspeed_tpu.profiling.flops import (TrainFlopsMeter, lm_flops_per_token,  # noqa: F401
                                           lm_layer_flops, peak_flops)
from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler, get_model_profile  # noqa: F401
from deepspeed_tpu.profiling.trace import TraceCapture, annotate, scope  # noqa: F401
