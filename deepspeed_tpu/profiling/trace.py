"""jax.profiler trace capture for a window of train steps.

Role of the reference's NVTX + nsys flow (``deepspeed/utils/nvtx.py``,
SURVEY.md §5.1): ``wall_clock_breakdown: true`` gives coarse host-side
fwd/bwd/step timers; this module additionally dumps an xplane trace
(viewable in XProf/Perfetto/TensorBoard) so collective latency, kernel
times, and host<->device gaps are attributable per step.  Host-side phases
appear as ``jax.profiler.TraceAnnotation`` ranges named after the engine
timers (``ds_forward`` / ``ds_step`` / ...) — the NVTX-range analog — and
device ops carry the ``ds_fwd_bwd`` / ``ds_optimizer_step``
``jax.named_scope`` prefixes from the compiled step functions.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax

from deepspeed_tpu.utils.logging import logger


def annotate(name: str):
    """Host-timeline named range in the xplane trace (the NVTX-range
    analog): ``with annotate("ds_serve_decode"): ...``.

    Used by the serving loop for its per-phase ranges (``ds_serve_admit`` /
    ``ds_serve_prefill`` / ``ds_serve_decode``) so the xplane device
    timeline lines up with the host-side ``ds_serve_*`` histograms
    (monitor/metrics.py) phase for phase.  Near-free when no trace is being
    captured; degrades to a no-op on jax builds without TraceAnnotation.
    """
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - jax without profiler support
        return contextlib.nullcontext()


def scope(name: str):
    """Device-timeline named range: ``with scope("ds_comm_all_gather"): ...``
    around ops *inside* jit, so the emitted HLO carries the name and the
    xplane device rows line up with the host-side ``ds_comm_*`` series.
    (``annotate`` is the host-timeline analog for eager regions; inside a
    trace it would time tracing, not execution.)  Trace-time metadata only —
    zero runtime cost, and applied unconditionally so toggling telemetry
    never changes the compiled program."""
    try:
        return jax.named_scope(name)
    except Exception:  # pragma: no cover - ancient jax
        return contextlib.nullcontext()


def perfetto_supported() -> bool:
    """Whether this jax's ``start_trace`` can write the perfetto
    trace-event JSON (``create_perfetto_trace=``, present in jax 0.4.37)
    — the input of the device-truth post-processor
    (profiling/device_trace.py).  Probed once, by signature."""
    global _PERFETTO_SUPPORTED
    if _PERFETTO_SUPPORTED is None:
        import inspect

        try:
            sig = inspect.signature(jax.profiler.start_trace)
            _PERFETTO_SUPPORTED = "create_perfetto_trace" in sig.parameters
        except (TypeError, ValueError):  # pragma: no cover - exotic builds
            _PERFETTO_SUPPORTED = False
    return _PERFETTO_SUPPORTED


_PERFETTO_SUPPORTED = None


class TraceCapture:
    """Start/stop a ``jax.profiler`` trace over steps
    ``[start_step, start_step + num_steps)``.  ``after_step(completed)`` is
    called by the engine after each optimizer step with the number of
    completed steps; the trace starts after step ``start_step - 1`` so the
    captured window contains whole steps (every micro-batch dispatch + the
    update).

    ``perfetto=True`` additionally asks jax for the perfetto trace-event
    JSON (``perfetto_trace.json.gz`` next to the xplane file — stdlib
    gzip+json parseable), which the device-truth post-processor
    (profiling/device_trace.py) consumes; silently ignored on jax builds
    without ``create_perfetto_trace`` (check :func:`perfetto_supported`).
    """

    def __init__(self, output_path: str, start_step: int = 2,
                 num_steps: int = 2, perfetto: bool = False):
        self.output_path = output_path
        self.start_step = max(1, int(start_step))
        self.num_steps = max(1, int(num_steps))
        self.perfetto = bool(perfetto)
        self.active = False
        self.done = False
        # clock anchor stamped when the window opens: the perfetto file's
        # timestamps are microseconds since the start_trace call, and this
        # records where that epoch sits on perf_counter/unix time — the
        # post-processor and /requestz correlate through it
        self.clock = None

    def _stamp_clock(self) -> None:
        from deepspeed_tpu.monitor.request_trace import \
            set_trace_clock_anchor

        self.clock = set_trace_clock_anchor()

    def maybe_start(self, upcoming_step: int) -> None:
        """Called before the first micro-batch of ``upcoming_step``: opens
        the window so the captured steps include their forward dispatches.
        ``>=`` (not ``==``): a checkpoint-resumed run starts past
        ``start_step`` and should still capture its first steps."""
        if self.done or self.active or upcoming_step < self.start_step:
            return
        import atexit

        os.makedirs(self.output_path, exist_ok=True)
        # anchor IMMEDIATELY before start_trace: the trace file's ts
        # epoch is the session start (measured within ~100us of the call)
        self._stamp_clock()
        if self.perfetto and perfetto_supported():
            jax.profiler.start_trace(self.output_path,
                                     create_perfetto_trace=True)
        else:
            jax.profiler.start_trace(self.output_path)
        self.active = True
        # training may end inside the window; close() is idempotent
        atexit.register(self.close)
        self.start_step = upcoming_step  # anchor the window where it opened
        logger.info("profile_trace: capturing steps %d..%d -> %s",
                    self.start_step, self.start_step + self.num_steps - 1,
                    self.output_path)

    def after_step(self, completed_steps: int) -> Optional[str]:
        """Returns the trace directory when the capture just finished."""
        if self.done or not self.active:
            return None
        if completed_steps >= self.start_step + self.num_steps - 1:
            jax.profiler.stop_trace()
            self.active = False
            self.done = True
            logger.info("profile_trace: wrote %s (xplane; open with XProf/"
                        "TensorBoard profile plugin)", self.output_path)
            return self.output_path
        return None

    def close(self) -> None:
        """Stop a still-open trace (training ended inside the window)."""
        if self.active:
            jax.profiler.stop_trace()
            self.active = False
            self.done = True
            logger.info("profile_trace: training ended inside the window; "
                        "wrote partial trace %s", self.output_path)
