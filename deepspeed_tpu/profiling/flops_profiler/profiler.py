"""FLOPS profiler — XLA cost-analysis based model profile.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py`` (SURVEY.md
§2.1 "FLOPS profiler", §5.1).  The reference counts MACs with per-module
torch forward hooks; the TPU-native source of truth is the compiled XLA
executable itself: ``jit(...).lower(...).compile().cost_analysis()`` gives
exact FLOPs/bytes for the program the hardware runs (fusion included) —
no hook bookkeeping, no per-op tables.

Two entry points, mirroring the reference API:

- ``FlopsProfiler(ds_engine)`` + config ``flops_profiler.enabled`` /
  ``profile_step``: the engine calls ``profile_step_hook`` each step and the
  profiler prints the model profile at the configured step, combining XLA
  cost analysis (per-program FLOPs) with the engine's wall-clock timers
  (achieved TFLOPS).
- ``get_model_profile(fn, args)``: standalone — profile any jittable
  callable (the reference's ``get_model_profile(model, input_shape)``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import jax

from deepspeed_tpu.utils.logging import log_dist, logger


def _cost_analysis(jitted, *args, **kwargs) -> Dict[str, float]:
    """FLOPs/bytes of the compiled executable for these args (retraces; call
    on profile steps only)."""
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax returns one per device
            ca = ca[0] if ca else {}
        return dict(ca or {})
    except Exception as exc:  # profiling must never break training
        logger.warning("flops profiler: cost analysis unavailable (%s)", exc)
        return {}


def get_model_profile(fn, args: Tuple = (), kwargs: Optional[dict] = None,
                      as_string: bool = False):
    """Profile a jittable callable: returns (flops, macs, params).

    ``params`` is counted from any pytree leaves in ``args`` (the reference
    counts module params; pass the param tree as an arg)."""
    kwargs = kwargs or {}
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    ca = _cost_analysis(jitted, *args, **kwargs)
    flops = float(ca.get("flops", 0.0))
    macs = flops / 2.0
    n_params = 0
    for a in args:
        try:
            n_params += sum(int(x.size) for x in jax.tree_util.tree_leaves(a)
                            if hasattr(x, "size"))
        except Exception:
            pass
    if as_string:
        return (f"{flops:.3e} FLOPs", f"{macs:.3e} MACs", f"{n_params:,} params")
    return flops, macs, n_params


def number_to_string(num: float, units: Optional[str] = None) -> str:
    for suffix, scale in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if units == suffix or (units is None and abs(num) >= scale):
            return f"{num / scale:.2f} {suffix}"
    return f"{num:.2f} "


class FlopsProfiler:
    """Engine-attached profiler (reference class name/API)."""

    def __init__(self, model: Any = None, ds_engine: Any = None):
        self.model = model
        self.ds_engine = ds_engine
        self.started = False
        self._t0 = 0.0
        self._cost: Dict[str, Dict[str, float]] = {}
        self._steps_profiled = 0

    # -- reference API --------------------------------------------------
    def start_profile(self, ignore_list=None) -> None:
        self.started = True
        self._t0 = time.perf_counter()
        self._steps_profiled = 0

    def stop_profile(self) -> None:
        self.started = False

    def end_profile(self) -> None:
        self.started = False
        self._cost.clear()

    def reset_profile(self) -> None:
        self._cost.clear()
        self._t0 = time.perf_counter()
        self._steps_profiled = 0

    # -- data collection -------------------------------------------------
    def collect(self, name: str, jitted, *args, **kwargs) -> None:
        """Record cost analysis for one compiled program under ``name``."""
        self._cost[name] = _cost_analysis(jitted, *args, **kwargs)

    def collect_scaled(self, name: str, parts) -> None:
        """Record one entry summing several programs, each weighted by its
        per-step call count (the streamed offload path dispatches per-layer
        programs L times per micro-batch instead of one whole program)."""
        total: Dict[str, float] = {}
        for jitted, args, mult in parts:
            ca = _cost_analysis(jitted, *args)
            for k, v in ca.items():
                if isinstance(v, (int, float)):
                    total[k] = total.get(k, 0.0) + float(v) * mult
        self._cost[name] = total

    def get_total_flops(self, as_string: bool = False):
        gas = 1
        if self.ds_engine is not None:
            gas = self.ds_engine.config.gradient_accumulation_steps
        if "train_step" in self._cost:
            # the fused single-dispatch program already spans all gas
            # microbatches + the update: it IS the train step
            total = self._cost["train_step"].get("flops", 0.0)
        else:
            total = (self._cost.get("accum", {}).get("flops", 0.0) * gas
                     + self._cost.get("apply", {}).get("flops", 0.0)
                     + self._cost.get("fwdbwd", {}).get("flops", 0.0) * gas)
        if not total and self._cost:
            total = sum(c.get("flops", 0.0) for c in self._cost.values())
        return number_to_string(total) + "FLOPs" if as_string else total

    def get_total_macs(self, as_string: bool = False):
        macs = self.get_total_flops() / 2.0
        return number_to_string(macs) + "MACs" if as_string else macs

    def get_total_params(self, as_string: bool = False):
        n = 0
        if self.ds_engine is not None and self.ds_engine.state is not None:
            params = (self.ds_engine.module_params()
                      if hasattr(self.ds_engine, "module_params")
                      else self.ds_engine.state.params)
            n = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
        return number_to_string(float(n)) + "params" if as_string else n

    def get_total_duration(self, as_string: bool = False):
        dt = time.perf_counter() - self._t0
        return f"{dt:.2f} s" if as_string else dt

    # -- output ----------------------------------------------------------
    def print_model_profile(self, profile_step: int = 1, module_depth: int = -1,
                            top_modules: int = 1, detailed: bool = True,
                            output_file: Optional[str] = None) -> str:
        lines = ["", "-" * 72,
                 f"DeepSpeed-TPU Flops Profiler (step {profile_step})",
                 "-" * 72]
        n_params = self.get_total_params()
        flops = self.get_total_flops()
        lines.append(f"params:                {number_to_string(float(n_params))}")
        lines.append(f"flops per train step:  {number_to_string(flops)}FLOPs "
                     f"(fwd+bwd+update, from XLA cost analysis)")
        lines.append(f"macs per train step:   {number_to_string(flops / 2)}MACs")
        if self.ds_engine is not None:
            eng = self.ds_engine
            step_t = eng.timers(eng.timers.STEP).mean() if hasattr(
                eng.timers, "STEP") else 0.0
            fwd_t = eng.timers(eng.timers.FORWARD).mean() if hasattr(
                eng.timers, "FORWARD") else 0.0
            if fwd_t or step_t:
                gas = eng.config.gradient_accumulation_steps
                wall = fwd_t * gas + step_t
                lines.append(f"fwd/micro-batch:       {fwd_t * 1e3:.2f} ms")
                lines.append(f"optimizer step:        {step_t * 1e3:.2f} ms")
                if wall > 0 and flops:
                    lines.append(f"achieved:              "
                                 f"{flops / wall / 1e12:.2f} TFLOPS")
        if detailed and self._cost:
            lines.append("per-program breakdown:")
            for name, ca in sorted(self._cost.items()):
                fl = ca.get("flops", 0.0)
                by = ca.get("bytes accessed", 0.0)
                lines.append(f"  {name:<18} flops={number_to_string(fl)} "
                             f"bytes={number_to_string(by)}B "
                             f"intensity={fl / by if by else 0:.1f} flop/B")
        lines.append("-" * 72)
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as fh:
                fh.write(text)
        log_dist(text, ranks=[0])
        return text
