from deepspeed_tpu.profiling.flops_profiler.profiler import (  # noqa: F401
    FlopsProfiler, get_model_profile)
