"""Static FLOP estimation + live TFLOPS/MFU gauges for the training loop.

The XLA-cost-analysis profiler (``profiling/flops_profiler``) answers "what
does the compiled program do" exactly, but costs a relower/compile per
probe — right for the one-shot model profile, wrong for a per-step gauge.
This module is the cheap static half: per-layer FLOP estimation from the
model config (the standard ``6N + 6·L·D·S`` per-token train cost — 2N fwd
+ 4N bwd matmul, plus causal attention), multiplied by the tokens the
engine actually stepped, divided by measured boundary-to-boundary wall
time, published as ``ds_train_tflops`` / ``ds_train_mfu`` gauges through
the metrics registry (and thus the ``_report`` MonitorMaster bridge and
``/statz``).

``peak_flops()`` (bf16 peak per chip, by device kind) lives here so
bench.py and the gauges share one table.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from deepspeed_tpu.monitor.metrics import MetricsRegistry, get_registry

__all__ = ["PEAK_FLOPS", "peak_flops", "lm_flops_per_token",
           "lm_layer_flops", "TrainFlopsMeter"]

PEAK_FLOPS = {  # bf16 peak per chip
    "tpu v5 lite": 197e12, "tpu v5e": 197e12, "tpu v5": 459e12,
    "tpu v4": 275e12, "tpu v6 lite": 918e12, "cpu": 1e12,
}


def peak_flops(device=None) -> float:
    """Peak bf16 FLOP/s of (the first) local device; 197 TF/s fallback."""
    import jax

    d = device if device is not None else jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 197e12


def lm_flops_per_token(n_params: int, num_layers: int, hidden_size: int,
                       seq: int) -> float:
    """Train (fwd+bwd) FLOPs per token for a dense causal LM: ``6N`` matmul
    (2N fwd + 4N bwd) + ``6·L·D·S`` causal attention (12·L·D·S for the
    full score/value matmuls, halved by causality) — the same accounting
    bench.py's MFU headline uses."""
    return 6.0 * n_params + 6.0 * num_layers * hidden_size * seq


def lm_layer_flops(cfg: Any, seq: int) -> Optional[Dict[str, float]]:
    """Static per-layer forward-FLOPs-per-token breakdown from a
    ``models.config.ModelConfig``-shaped object; None when the config does
    not carry the LM fields.  Keys: qkvo / attn_scores / mlp (per layer),
    embed_head (once)."""
    D = getattr(cfg, "hidden_size", None)
    L = getattr(cfg, "num_layers", None)
    if not D or not L:
        return None
    heads = getattr(cfg, "num_heads", 1) or 1
    kv = getattr(cfg, "num_kv_heads", None) or heads
    hd = getattr(cfg, "head_dim", None) or D // heads
    inter = getattr(cfg, "intermediate_size", 4 * D)
    V = getattr(cfg, "vocab_size", 0)
    q_out = heads * hd
    kv_out = kv * hd
    qkvo = 2.0 * D * (q_out + 2 * kv_out) + 2.0 * q_out * D
    attn_scores = 2.0 * 2.0 * q_out * seq / 2.0   # QK^T + AV, causal-halved
    mlp_mats = 3 if getattr(cfg, "glu", False) else 2
    mlp = 2.0 * mlp_mats * D * inter
    return {"qkvo": qkvo, "attn_scores": attn_scores, "mlp": mlp,
            "per_layer": qkvo + attn_scores + mlp,
            "embed_head": 2.0 * D * V, "layers": float(L)}


class TrainFlopsMeter:
    """Boundary-to-boundary TFLOPS/MFU gauges.

    ``observe_boundary(flops, anchor=...)`` is called once per optimizer
    step with the FLOPs that step performed; wall time is measured between
    consecutive calls.  Dispatch is async, so a bare host clock would time
    dispatch, not compute (a tight loop dispatches several steps before
    the first finishes) — the ``anchor`` (the step's loss output) is
    blocked on first, pinning each boundary to real device completion.
    The sync happens ONLY while the registry is enabled: telemetry users
    pay a boundary bubble (the ``wall_clock_breakdown`` trade, scoped the
    same way); disabled runs are untouched.  The first call only arms the
    clock.  One branch + no work while the registry is disabled.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        reg = registry if registry is not None else get_registry()
        self._registry = reg
        self._tflops = reg.gauge(
            "ds_train_tflops", "achieved train TFLOP/s (static FLOP "
            "estimate / boundary-to-boundary wall time)")
        self._mfu = reg.gauge(
            "ds_train_mfu", "model FLOPs utilization: ds_train_tflops / "
            "device peak")
        self._last_t: Optional[float] = None
        self._peak: Optional[float] = None

    def reset_clock(self) -> None:
        self._last_t = None

    def observe_boundary(self, flops_per_step: Optional[float],
                         anchor=None) -> None:
        if not self._registry._enabled:
            return
        if not flops_per_step:
            # no FLOP estimate (non-LM model config) -> no gauge possible;
            # in particular do NOT pay the anchor sync for nothing
            return
        if anchor is not None:
            try:
                import jax

                jax.block_until_ready(anchor)
            except Exception:
                pass
        now = time.perf_counter()
        last, self._last_t = self._last_t, now
        if last is None:
            return
        dt = now - last
        if dt <= 0:
            return
        if self._peak is None:
            try:
                self._peak = peak_flops()
            except Exception:
                self._peak = 197e12
        tflops = flops_per_step / dt / 1e12
        self._tflops.set(round(tflops, 4))
        self._mfu.set(round(tflops * 1e12 / self._peak, 6))
