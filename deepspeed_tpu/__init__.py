"""deepspeed_tpu — a TPU-native training/inference framework with the
capability surface of DeepSpeed (reference: kooyunmo/DeepSpeed; see SURVEY.md).

Public API parity (SURVEY.md §2.1 "Public API"): ``initialize()``,
``init_inference()``, ``init_distributed()``, ``add_config_arguments()``, the
``comm`` and ``zero`` submodules, and ``DeepSpeedConfig`` — reimplemented over
jax/XLA/pjit with a device mesh instead of torch/NCCL.
"""

from __future__ import annotations

__version__ = "0.1.0"
__git_branch__ = "main"

# before any submodule import: modules reference jax.shard_map at call time,
# and users' own code may too, as soon as deepspeed_tpu is imported
from deepspeed_tpu.utils.compat import install_jax_compat  # noqa: E402

install_jax_compat()

from deepspeed_tpu import comm  # noqa: F401,E402
from deepspeed_tpu.runtime import zero  # noqa: F401
from deepspeed_tpu.accelerator import get_accelerator  # noqa: F401
from deepspeed_tpu.runtime.config import DeepSpeedConfig  # noqa: F401
from deepspeed_tpu.utils.logging import log_dist, logger  # noqa: F401


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, distributed_port=29500,
               mpu=None, dist_init_required=None, collate_fn=None, config=None,
               config_params=None, mesh=None, rng=None, loss_fn=None):
    """Create a training engine (reference contract: SURVEY.md §3.2).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)``.

    ``model`` may be a flax ``nn.Module`` or any object exposing
    ``init(rng, *inputs)`` / ``apply(params, *inputs)``.  See
    ``deepspeed_tpu/runtime/engine.py`` for the engine design (functional
    jitted train step under an imperative forward/backward/step façade).
    """
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    cfg = config if config is not None else config_params
    if cfg is None and args is not None and hasattr(args, "deepspeed_config"):
        cfg = args.deepspeed_config
    engine = DeepSpeedEngine(args=args, model=model, optimizer=optimizer,
                             model_parameters=model_parameters, training_data=training_data,
                             lr_scheduler=lr_scheduler, mpu=mpu,
                             dist_init_required=dist_init_required, collate_fn=collate_fn,
                             config=cfg, mesh=mesh, rng=rng, loss_fn=loss_fn)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Create an inference engine (reference: SURVEY.md §3.5)."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    # engine-level kwargs (not config keys): jax models are functional, so
    # weights arrive separately from the module (torch bundles them)
    params = kwargs.pop("params", None)
    mesh = kwargs.pop("mesh", None)
    config = _merge_inference_config(config, kwargs, DeepSpeedInferenceConfig)
    return InferenceEngine(model, config, params=params, mesh=mesh)


def _merge_inference_config(config, kwargs, cls):
    """Overlay config-key kwargs on ``config`` (dict, model instance, or
    None) without dropping the instance's settings."""
    if config is None:
        config = kwargs
    elif kwargs:
        base = config.model_dump() if isinstance(config, cls) else dict(config)
        config = {**base, **kwargs}
    if not isinstance(config, cls):
        config = cls(**config)
    return config


def init_serving(model=None, config=None, **kwargs):
    """Create a continuous-batching :class:`~deepspeed_tpu.serving.engine.
    ServingEngine` (the MII / DeepSpeed-FastGen dynamic-batching role):
    paged KV cache (slots draw token pages from one shared pool;
    ``paged_kv_cache=False`` for the contiguous per-slot layout),
    iteration-level scheduling, chunked prefill interleaved with
    per-row-position decode, and sync-free (device-resident) EOS
    termination with deferred finish-event drains.

    ``metrics_port=`` (optional) enables the engine's metrics registry
    and serves it over HTTP for the engine's lifetime: ``GET /metrics``
    (Prometheus text) + ``GET /statz`` (JSON snapshot) + ``GET
    /requestz`` (per-request span timelines) + ``GET /healthz``
    (readiness) + ``POST /generate`` (the multi-replica router's dispatch
    target — ``serving/router.py``; requires a stepping loop, see
    ``serve_loop`` below).  Pass ``0`` for an ephemeral port — read it
    back from ``engine.metrics_server.port``.
    ``request_trace=True`` (optional) additionally enables the
    per-request span tracer (``monitor/request_trace.py``) feeding
    ``/requestz`` and the ``ds_serve_phase_*`` attribution histograms —
    off by default (one branch, zero allocation per lifecycle hook).
    ``serve_loop=True`` starts the background serving loop
    (``ServingEngine.start_loop``) so ``/generate`` requests progress
    without a caller-driven ``step()`` loop.
    ``registry=`` / ``private_health=True`` scope the metrics registry
    and the ``/healthz`` readiness flag to THIS engine instead of the
    process globals — how N replica engines in one process keep
    per-replica truths for the router (docs/OBSERVABILITY.md "Router").
    ``role=`` ("both" | "prefill" | "decode") enables disaggregated
    serving: a ``prefill`` replica answers ``{"phase": "prefill"}``
    requests and ships matched/computed KV pages to the ``handoff_to``
    decode replica over ``/kv_offer`` + ``/kv_adopt`` (int8 on the wire
    by default; ``handoff_wire="raw"`` for engine-dtype bytes) — see
    docs/RESILIENCE.md "Disaggregated serving".
    See docs/OBSERVABILITY.md.
    """
    from deepspeed_tpu.serving.engine import ServingEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    params = kwargs.pop("params", None)
    mesh = kwargs.pop("mesh", None)
    metrics_port = kwargs.pop("metrics_port", None)
    request_trace = kwargs.pop("request_trace", False)
    serve_loop = kwargs.pop("serve_loop", False)
    registry = kwargs.pop("registry", None)
    if kwargs.pop("private_health", False):
        from deepspeed_tpu.monitor.health import HealthState

        health = HealthState()
    else:
        health = None
    engine_kw = {k: kwargs.pop(k) for k in
                 ("engine", "num_slots", "prefill_chunk",
                  "decode_block_tokens", "do_sample", "temperature",
                  "top_k", "top_p", "role", "handoff_wire") if k in kwargs}
    if config is not None or kwargs:
        # only materialize a config when one was actually given —
        # ServingEngine rejects engine= combined with config/model args
        config = _merge_inference_config(config, kwargs,
                                         DeepSpeedInferenceConfig)
    serve = ServingEngine(model, config, params=params, mesh=mesh,
                          registry=registry, health=health, **engine_kw)
    if request_trace:
        from deepspeed_tpu.monitor.request_trace import get_request_tracer

        get_request_tracer().enable()
    if serve_loop:
        # before the HTTP server comes up: a /generate racing the loop
        # start must find a live stepper
        serve.start_loop()
    if metrics_port is not None:
        import weakref

        from deepspeed_tpu.monitor.metrics import get_registry
        from deepspeed_tpu.monitor.server import MetricsServer

        reg = registry if registry is not None else get_registry()
        reg.enable()
        server = MetricsServer(reg, port=int(metrics_port),
                               health=serve.health)
        server.set_generate_handler(serve._http_generate)
        server.set_kv_handoff_handlers(serve._http_kv_offer,
                                       serve._http_kv_adopt)
        server.start()
        serve.metrics_server = server
        # "for the engine's lifetime": a discarded engine must not leak its
        # bound port + exporter thread — engine.close() stops it
        # deterministically, this finalizer catches the GC path
        weakref.finalize(serve, server.stop)
    return serve


def init_telemetry(metrics_port=None, comms: bool = True,
                   flight_recorder: bool = False, flight_capacity: int = 512,
                   flight_dump_dir=None, on_signal: bool = False):
    """Turn on the training-side telemetry stack without a ds_config
    (the ``init_serving(metrics_port=...)`` analog for training loops):

    - enables the process-global metrics registry (``ds_*`` series record);
    - ``comms=True`` enables per-collective accounting (``ds_comm_*``);
    - ``metrics_port=`` additionally serves ``/metrics`` + ``/statz`` on an
      HTTP exporter (``0`` = ephemeral port; read ``server.port``);
    - ``flight_recorder=True`` arms the event ring
      (``monitor/flight_recorder.py``), with a SIGUSR2 dump handler only
      when ``on_signal=True``.

    Returns the started :class:`~deepspeed_tpu.monitor.server.MetricsServer`
    (or None when no port was requested).  Equivalent ds_config blocks:
    ``comms_logger`` and ``flight_recorder`` — see docs/OBSERVABILITY.md.
    """
    from deepspeed_tpu.monitor.comms import comm_metrics
    from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
    from deepspeed_tpu.monitor.metrics import get_registry

    get_registry().enable()
    if comms:
        comm_metrics.configure(enabled=True)
    if flight_recorder:
        rec = get_flight_recorder().enable(capacity=flight_capacity,
                                           dump_dir=flight_dump_dir)
        if on_signal:
            rec.install_signal_handler()
    if metrics_port is None:
        return None
    from deepspeed_tpu.monitor.server import MetricsServer

    return MetricsServer(get_registry(), port=int(metrics_port)).start()


def init_distributed(dist_backend: str = "xla", **kwargs):
    """Bootstrap multi-host + mesh (reference: ``deepspeed.init_distributed``)."""
    return comm.init_distributed(dist_backend=dist_backend, **kwargs)


def add_config_arguments(parser):
    """Add ``--deepspeed``/``--deepspeed_config`` CLI args (reference parity)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag, parity with reference)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the ds_config JSON file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse_suppress())
    group.add_argument("--local_rank", type=int, default=-1,
                       help="Local rank injected by the launcher")
    return parser


def argparse_suppress():
    import argparse

    return argparse.SUPPRESS
