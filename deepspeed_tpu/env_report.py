"""``ds_report`` — environment / op-build compatibility report.

Reference: ``deepspeed/env_report.py`` (SURVEY.md §2.1 "env report"): prints
framework versions, device inventory, and the native/Pallas op build matrix so
users can see at a glance what is installed, compatible, and built.

Run as ``python -m deepspeed_tpu.env_report``.
"""

from __future__ import annotations

import importlib
import os
import platform
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"
DOTS = "." * 2


def _try_version(mod_name: str):
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return None


def op_report():
    """Native (C++) + Pallas op availability matrix."""
    rows = []
    try:
        from deepspeed_tpu.ops.op_builder.native import available_ops
        rows.extend(available_ops())
    except Exception as exc:  # report must never crash
        rows.append(("op_builder", False, f"error: {exc}"))
    # Pallas kernels: importable == available (TPU lowering is checked at call
    # time; interpret mode covers CPU).
    for name, mod in (("pallas.flash_attention", "deepspeed_tpu.ops.pallas.flash_attention"),
                      ("pallas.layer_norm", "deepspeed_tpu.ops.pallas.layer_norm"),
                      ("pallas.fused_adam", "deepspeed_tpu.ops.pallas.fused_adam"),
                      ("pallas.softmax", "deepspeed_tpu.ops.pallas.softmax"),
                      ("pallas.rope", "deepspeed_tpu.ops.pallas.rope")):
        try:
            importlib.import_module(mod)
            rows.append((name, True, "importable"))
        except Exception as exc:
            rows.append((name, False, str(exc)))
    return rows


def main() -> int:
    print("-" * 70)
    print("deepspeed_tpu C++/Pallas op report")
    print("-" * 70)
    for name, ok, note in op_report():
        status = GREEN_OK if ok else RED_NO
        print(f"{name:<28} {DOTS} {status} {DOTS} {note}")

    print("-" * 70)
    print("General environment:")
    print(f"  python ................ {sys.version.split()[0]} ({platform.platform()})")
    import deepspeed_tpu

    print(f"  deepspeed_tpu ......... {deepspeed_tpu.__version__}")
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy"):
        v = _try_version(mod)
        print(f"  {mod:<21} {'.' * 1} {v if v else 'not installed'}")
    print(f"  DS_ACCELERATOR ........ {os.environ.get('DS_ACCELERATOR', '(auto)')}")
    print(f"  JAX_PLATFORMS ......... {os.environ.get('JAX_PLATFORMS', '(auto)')}")

    # Device inventory last: touching jax initializes the backend.
    try:
        import jax

        devs = jax.devices()
        print(f"  backend ............... {jax.default_backend()}")
        print(f"  devices ............... {len(devs)} x "
              f"{getattr(devs[0], 'device_kind', '?')}")
        print(f"  process count ......... {jax.process_count()}")
    except Exception as exc:
        print(f"  devices ............... unavailable ({exc})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
