"""Gradient-norm anomaly detector: the bf16 answer to fp16's overflow skip.

fp16 training gets loss-scale overflow detection for free — a non-finite
grad zeroes the step via the branchless ``has_overflow`` select in
``runtime/engine.py``.  bf16 has no loss scaler, so a run that goes
non-finite (or takes a gradient bomb from a corrupt batch / a straggler
host returning garbage) silently destroys the parameters and every
checkpoint saved after it.  This detector watches the one per-step scalar
training already computes — the global gradient norm — and classifies each
step against a rolling-median spike bound, the ``StepWatchdog`` cached-
bound idiom (``monitor/watchdog.py``):

- the trip *bound* (``factor`` x rolling median of ACCEPTED norms) is
  cached; healthy samples cost one deque append + one comparison, and the
  true median is recomputed only when a sample crosses the cached bound
  or once per ``window`` samples (the re-anchor that keeps a falling
  median honest);
- non-finite norms and norms above the bound are anomalies; anomalous
  samples never enter the window (a bomb must not drag its own bar up);
- unlike the watchdog this is MULTI-shot: every step is classified, and
  the engine escalates — skip the step in-program first (the fp16
  select, mirrored), then after ``patience`` CONSECUTIVE anomalies roll
  back to the last-good checkpoint (``runtime/engine._anomaly_tick``).

Host-side cost when enabled: the engine feeds realized norms with a lag-1
deferred fetch (the serving ``_fetch_block`` idiom), so no step ever
blocks on its own norm.  Disabled (default): the engine never constructs
a detector and the step program is byte-identical to before.

Stdlib-only; nothing here imports jax.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Dict, Optional

__all__ = ["GradAnomalyDetector"]


class GradAnomalyDetector:
    def __init__(self, factor: float = 10.0, window: int = 64,
                 warmup: int = 8, patience: int = 3):
        if factor <= 1.0:
            raise ValueError(f"anomaly factor must be > 1, got {factor}")
        self.factor = float(factor)
        self.window = max(2, int(window))
        # warmup > window could never arm (the deque holds `window` max)
        self.warmup = min(max(2, int(warmup)), self.window)
        self.patience = max(1, int(patience))
        self._dq: deque = deque(maxlen=self.window)
        self._bound = math.inf          # cached trip bound (inf = unarmed)
        self._refresh = self.window
        self.consecutive = 0            # current run of anomalous steps
        self.trips_total = 0
        self.rollbacks = 0              # lifetime rollback count
        self.rollback_streak = 0        # rollbacks with no accepted step between
        self.median_recomputes = 0
        self.last_trip: Optional[Dict[str, Any]] = None

    # -- the device-side select reads this each dispatch ----------------
    @property
    def bound(self) -> float:
        """Current trip bound for the in-program skip select (``+inf``
        until the warmup window fills: never skip on no evidence)."""
        return self._bound

    # -- classification --------------------------------------------------
    def observe(self, gnorm: float, skipped: Optional[bool] = None) -> bool:
        """Classify one realized grad norm; returns True when the step
        was anomalous/SKIPPED.  ``skipped`` is the device's own select
        decision for this step (made against the bound at dispatch) —
        passing it keeps the host ledger truthful even when the cached
        bound has drifted from the live median; None falls back to the
        host rule (host-stepped paths, where decision and ledger share
        one bound).  Healthy samples feed the window; anomalies never do.

        A step the device dropped whose norm is nevertheless WITHIN
        ``factor`` of the true median is a *drift* skip (the cached bound
        was stale-low): it is still reported True (the step really was
        lost — the caller must count it), the bound refreshes so the
        next dispatch stops skipping, and the sample enters the window
        WITHOUT escalating the rollback ladder."""
        if not math.isfinite(gnorm):
            return self._trip(gnorm, kind="non_finite")
        suspect = bool(skipped) if skipped is not None else gnorm > self._bound
        if suspect:
            # confirm against the true median EXCLUDING any influence of
            # the suspect (it was never appended)
            self.median_recomputes += 1
            med = self._median()
            if med > 0 and gnorm > self.factor * med:
                return self._trip(gnorm, kind="spike", median=med)
            # the median drifted up past the cached bound: refresh it so
            # the new normal stops tripping
            self._bound = self.factor * max(med, gnorm / self.factor)
            if skipped:
                # the device already dropped this step — report the skip
                # (kind "drift") but treat the run as healthy
                self.trips_total += 1
                self.last_trip = {"gnorm": gnorm, "kind": "drift",
                                  "median": med, "bound": self._bound,
                                  "consecutive": self.consecutive}
                self._accept(gnorm)
                return True
        self._accept(gnorm)
        return False

    def _accept(self, gnorm: float) -> None:
        self.consecutive = 0
        self.rollback_streak = 0        # a healthy step forgives the ladder
        self._dq.append(gnorm)
        n = len(self._dq)
        if self._bound is math.inf:
            if n >= self.warmup:
                self._bound = self.factor * self._median()
            return
        self._refresh -= 1
        if self._refresh <= 0:
            # once-per-window re-anchor: the median can FALL (early steps
            # are noisy, then training settles) and a stale-high bound
            # would let a real spike through
            self._refresh = self.window
            self._bound = self.factor * self._median()

    def _trip(self, gnorm: float, kind: str, median: float = 0.0) -> bool:
        self.consecutive += 1
        self.trips_total += 1
        self.last_trip = {"gnorm": gnorm, "kind": kind,
                          "median": median or self._median(),
                          "bound": self._bound,
                          "consecutive": self.consecutive}
        return True

    # -- escalation ------------------------------------------------------
    @property
    def should_rollback(self) -> bool:
        return self.consecutive >= self.patience

    def note_rollback(self) -> None:
        """Reset the escalation ladder after a rollback: the restored
        state starts a fresh consecutive count, and the window is kept —
        the healthy-median memory survives the rollback (a persisting
        bomb trips again immediately instead of slipping through a
        re-warmup blind spot)."""
        self.rollbacks += 1
        self.rollback_streak += 1
        self.consecutive = 0

    def _median(self) -> float:
        vals = sorted(self._dq)
        n = len(vals)
        if not n:
            return 0.0
        mid = n // 2
        return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])

    @property
    def median(self) -> float:
        """Current rolling median (reads sort; not the hot path)."""
        return self._median()
