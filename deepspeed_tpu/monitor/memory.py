"""HBM / device-memory telemetry.

Two feeds into the ``ds_mem_*`` gauge family (docs/OBSERVABILITY.md):

- :meth:`MemoryTelemetry.sample` — called by the engine at step
  boundaries: reads ``device.memory_stats()`` for every local device
  (TFRT exposes ``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``
  on TPU; CPU returns nothing and the sample is a no-op) and publishes the
  max across local devices — the binding constraint on an SPMD mesh is the
  fullest chip.
- :meth:`MemoryTelemetry.set_state_bytes` — set once at engine init from
  the *measured* placement of the training state: per-device resident
  bytes of params / grad accumulator / optimizer state (the ZeRO
  shard-group breakdown: what stage-N partitioning actually left on each
  chip).

One branch + no work per ``sample()`` while the registry is disabled.
"""

from __future__ import annotations

from typing import Any, Optional

from deepspeed_tpu.monitor.metrics import MetricsRegistry, get_registry
from deepspeed_tpu.utils.logging import logger

__all__ = ["MemoryTelemetry"]


class MemoryTelemetry:
    def __init__(self, registry: Optional[MetricsRegistry] = None):
        reg = registry if registry is not None else get_registry()
        self._registry = reg
        self._live = reg.gauge("ds_mem_live_bytes",
                               "device bytes in use (max over local devices)")
        self._peak = reg.gauge("ds_mem_peak_bytes",
                               "peak device bytes in use (max over local "
                               "devices)")
        self._limit = reg.gauge("ds_mem_limit_bytes",
                                "device memory capacity (max over local "
                                "devices)")
        self._p_bytes = reg.gauge("ds_mem_param_shard_bytes",
                                  "per-device resident parameter bytes "
                                  "(ZeRO shard view)")
        self._g_bytes = reg.gauge("ds_mem_grad_shard_bytes",
                                  "per-device resident grad-accumulator "
                                  "bytes (ZeRO shard view)")
        self._o_bytes = reg.gauge("ds_mem_optstate_shard_bytes",
                                  "per-device resident optimizer-state "
                                  "bytes (ZeRO shard view)")
        self._warned = False

    def sample(self) -> None:
        """Read live/peak/limit off every local device; max across devices."""
        if not self._registry._enabled:
            return
        try:
            import jax

            live = peak = limit = 0
            for d in jax.local_devices():
                ms = d.memory_stats()
                if not ms:
                    continue
                live = max(live, int(ms.get("bytes_in_use", 0)))
                peak = max(peak, int(ms.get("peak_bytes_in_use", 0)))
                limit = max(limit, int(ms.get("bytes_limit", 0)))
            if live or peak or limit:
                self._live.set(live)
                self._peak.set(peak)
                self._limit.set(limit)
        except Exception as exc:  # telemetry must never break training
            if not self._warned:
                self._warned = True
                logger.warning("memory telemetry: memory_stats unavailable "
                               "(%s)", exc)

    def set_state_bytes(self, param_bytes: int, grad_bytes: int,
                        opt_bytes: int) -> None:
        self._p_bytes.set(int(param_bytes))
        self._g_bytes.set(int(grad_bytes))
        self._o_bytes.set(int(opt_bytes))


def device_resident_bytes(tree: Any, device=None) -> int:
    """Measured bytes the leaves of ``tree`` keep on ``device`` (default:
    the first local device) — reads real shard shapes off each
    ``jax.Array``, so any ZeRO stage / spec layout is reported as placed,
    not as planned.  Non-array leaves (host numpy under offload) count 0."""
    import jax

    if device is None:
        device = jax.local_devices()[0]
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            continue
        for s in shards:
            if s.device == device:
                total += int(s.data.size) * leaf.dtype.itemsize
    return total
