"""Process-global readiness state backing the ``/healthz`` endpoint.

The multi-replica router (ROADMAP item 3) needs one boolean per replica:
"may I send you new work?".  Liveness is the HTTP server answering at
all; READINESS is this flag — flipped off by ``ServingEngine.drain()``
for the whole drain window (and by any other subsystem that wants
traffic to stop) and surfaced as ``GET /healthz`` → 200/503 on the
metrics server.

Deliberately tiny and lock-free on the read side (the serving loop and
the HTTP scrape threads both touch it): a single attribute read per
check, same contract as the metrics registry's disabled path.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

__all__ = ["HealthState", "get_health"]


class HealthState:
    def __init__(self) -> None:
        self.ready = True
        self.reason: Optional[str] = None
        self.since_unix = time.time()
        self._transitions = 0

    def set_ready(self) -> None:
        if not self.ready:
            self._transitions += 1
            self.since_unix = time.time()
        self.reason = None
        self.ready = True

    def set_not_ready(self, reason: str) -> None:
        if self.ready:
            self._transitions += 1
            self.since_unix = time.time()
        self.reason = str(reason)
        self.ready = False

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"ready": self.ready,
                               "since_unix": self.since_unix,
                               "transitions": self._transitions}
        if self.reason is not None:
            out["reason"] = self.reason
        return out


_HEALTH = HealthState()


def get_health() -> HealthState:
    """The process-global readiness flag the ``/healthz`` endpoint serves."""
    return _HEALTH
