"""Stdlib HTTP exporter for the metrics registry.

``MetricsServer(registry, port=0).start()`` serves two endpoints on a
daemon thread:

- ``GET /metrics`` — Prometheus text exposition (scrape target);
- ``GET /statz``  — the same registry as a JSON snapshot (humans, tests,
  and ``tools/metrics_dump.py``);
- ``GET /statz?window=<key>`` — rate-windowed deltas: each distinct
  ``window`` key remembers the snapshot of its previous scrape, and a
  request returns counter/histogram deltas (plus per-second rates) over
  the *actual* elapsed time since then — long-lived serving gets rates
  without a Prometheus server.  The first scrape of a key primes it
  (``"primed": true``, no deltas); scrape again after your window.
- ``GET /profilez?steps=N`` — on-demand device-true profile: parks a
  capture request on the process-global profile broker
  (profiling/device_trace.py); the next live engine step boundary claims
  it, captures N steps (training steps or serving scheduler iterations)
  with the perfetto export on, runs the post-processor, and the response
  is the JSON phase summary (the same numbers land in the ``ds_profile_*``
  registry series).  ``timeout=S`` bounds the wait (default 60s; 504 when
  nothing is stepping, 409 when a capture is already in flight, 501 on
  jax builds without the perfetto export).
- ``GET /healthz`` — READINESS (not liveness): 200 ``{"ready": true}``
  while the process accepts new work, 503 with a ``reason`` while it does
  not (``ServingEngine.drain()`` flips it for the whole drain window) —
  the router/load-balancer stop-sending signal (monitor/health.py).  A
  server built with ``health=`` serves that state instead of the
  process-global one (N replicas in one process each keep their own
  drain truth).
- ``POST /generate`` — replica inference endpoint (the router's dispatch
  target, ``serving/router.py``): available when a serving engine is
  attached (``init_serving(metrics_port=...)`` wires its handler); the
  JSON body ``{"prompt": [ids], "max_new_tokens", "eos_token_id"?,
  "timeout"?}`` blocks this worker thread until the request finishes and
  returns its tokens; 503 while the engine drains (the router re-sends
  elsewhere — no request is dropped on a drain).  With ``"stream":
  true`` the response is chunked ndjson — one JSON event per line as
  token blocks drain, then a terminal ``done``/``error`` event.
- ``POST /kv_offer`` / ``POST /kv_adopt`` — the disaggregated-serving
  KV-page handoff pair (decode-capable replicas): offer answers which
  page chunks this replica lacks; adopt writes the shipped pages and
  pins them into the local prefix cache (serving/handoff.py).
- ``GET /goodputz`` — run-level goodput ledger snapshot
  (monitor/goodput.py): telescoping wall-clock attribution over the
  closed category set plus the goodput ratio; ``{"enabled": false}``
  when no ledger is enabled in this process.
- ``GET /requestz`` — per-request span timelines from the request tracer
  (monitor/request_trace.py): recent completions, slowest exemplars, and
  the tail-attribution summary.  ``?n=`` bounds the lists;
  ``?format=perfetto`` returns trace-event JSON keyed to the clock anchor
  of the most recent profiler capture, so it loads in ONE Perfetto
  session next to a ``/profilez`` capture with aligned timestamps.

``port=0`` binds an ephemeral port (read it back from ``server.port``) —
the shape tests and multi-engine hosts need.  Zero dependencies: plain
``http.server`` over the registry's lock-free snapshot reads, so a scrape
never blocks the serving loop.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs

from deepspeed_tpu.monitor.metrics import (MetricsRegistry, get_registry,
                                           window_delta)
from deepspeed_tpu.utils.logging import logger

__all__ = ["MetricsServer"]


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry  # set by the server subclass

    def do_GET(self):  # noqa: N802 - http.server API
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            body = self.registry.prometheus_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path in ("/statz", "/statz/"):
            qs = parse_qs(query)
            window = qs.get("window", [None])[0]
            if window is not None:
                body = json.dumps(self._windowed(window),
                                  sort_keys=True).encode()
            elif "kinds" in qs:
                # instrument kinds alongside the snapshot: fleet
                # aggregation (tools/fleet_dump.py) must know whether to
                # SUM a scalar (counter) or min/max/mean it (gauge) —
                # the plain snapshot erases that.  Both maps derive from
                # ONE typed_snapshot so a metric registered mid-scrape
                # can't appear in metrics but not kinds.
                kinds: dict = {}
                metrics: dict = {}
                for (name, ls), (kind, value) in \
                        self.registry.typed_snapshot().items():
                    kinds[name] = kind
                    if ls:
                        metrics.setdefault(name, {})[ls] = value
                    else:
                        metrics[name] = value
                body = json.dumps(
                    {"enabled": self.registry.enabled,
                     "metrics": metrics,
                     "kinds": kinds}, sort_keys=True).encode()
            else:
                body = self.registry.statz_json().encode()
            ctype = "application/json"
        elif path in ("/requestz", "/requestz/"):
            from deepspeed_tpu.monitor.request_trace import (
                get_request_tracer, get_step_timeline)

            qs = parse_qs(query)
            # ?kind=train serves the training step timeline through the
            # same endpoint/format contract (one scrape surface for
            # fleet_dump --trace, whether the process serves or trains)
            tracer = (get_step_timeline()
                      if qs.get("kind", [""])[0] == "train"
                      else get_request_tracer())
            if qs.get("format", [""])[0] == "perfetto":
                body = json.dumps(tracer.perfetto_trace()).encode()
            else:
                try:
                    limit = int(qs.get("n", ["32"])[0])
                except ValueError:
                    self.send_error(400, "n must be an integer")
                    return
                body = json.dumps(tracer.snapshot(limit),
                                  sort_keys=True).encode()
            ctype = "application/json"
        elif path in ("/profilez/history", "/profilez/history/"):
            # latest continuous-profiler windows of every live engine in
            # this process (docs/OBSERVABILITY.md "Continuous profiling").
            # Serves {"engines": [], "windows": []} when no profiler is
            # armed — a cheap fleet scrape, never a capture trigger.
            from deepspeed_tpu.profiling.continuous import history_snapshot

            qs = parse_qs(query)
            try:
                limit = int(qs.get("n", ["8"])[0])
            except ValueError:
                self.send_error(400, "n must be an integer")
                return
            body = json.dumps(history_snapshot(limit),
                              sort_keys=True).encode()
            ctype = "application/json"
        elif path in ("/profilez", "/profilez/"):
            code, payload = self._profilez(parse_qs(query))
            body = json.dumps(payload, sort_keys=True).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        elif path in ("/goodputz", "/goodputz/"):
            # run-level goodput ledger snapshot (monitor/goodput.py):
            # telescoping wall-clock attribution for the live process —
            # {"enabled": false} when no ledger is enabled, else the
            # category breakdown + goodput_ratio (docs/OBSERVABILITY.md
            # "Goodput ledger").
            from deepspeed_tpu.monitor.goodput import get_goodput_ledger

            body = json.dumps(get_goodput_ledger().snapshot(),
                              sort_keys=True).encode()
            ctype = "application/json"
        elif path in ("/healthz", "/healthz/"):
            # READINESS, not liveness: 503 while draining (or any other
            # not-ready reason) is the router's stop-sending signal —
            # liveness is this server answering at all.  A server-scoped
            # HealthState (multi-replica hosts) wins over the global one.
            health = getattr(self.server, "health", None)
            if health is None:
                from deepspeed_tpu.monitor.health import get_health

                health = get_health()
            snap = health.snapshot()
            body = json.dumps(snap, sort_keys=True).encode()
            self.send_response(200 if snap["ready"] else 503)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        elif path == "/":
            body = json.dumps({"endpoints": ["/goodputz", "/healthz",
                                             "/metrics", "/statz",
                                             "/profilez",
                                             "/profilez/history",
                                             "/requestz",
                                             "/generate", "/kv_offer",
                                             "/kv_adopt"]}
                              ).encode()
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # POST endpoints and the server attribute holding each one's handler
    # (/kv_offer and /kv_adopt are the disaggregated-serving page-handoff
    # pair — wired only on decode-capable replicas by init_serving)
    POST_ROUTES = {"/generate": "generate_handler",
                   "/kv_offer": "kv_offer_handler",
                   "/kv_adopt": "kv_adopt_handler"}

    def do_POST(self):  # noqa: N802 - http.server API
        path, _, _ = self.path.partition("?")
        attr = self.POST_ROUTES.get(path.rstrip("/") or path)
        if attr is None:
            self.send_error(404)
            return
        handler = getattr(self.server, attr, None)
        if handler is None:
            code, payload = 503, {"error": "no serving engine attached "
                                           "to this metrics server"}
        else:
            try:
                length = int(self.headers.get("Content-Length") or 0)
                payload = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(payload, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, json.JSONDecodeError) as exc:
                code, payload = 400, {"error": f"bad JSON body: {exc}"}
            else:
                # distributed-trace propagation: the router stamps a
                # traceparent HEADER on its re-POST; surface it to the
                # engine handler as a payload field (an explicit payload
                # traceparent wins — it is the more deliberate signal)
                tp = self.headers.get("traceparent")
                if tp and "traceparent" not in payload:
                    payload["traceparent"] = tp
                # blocks this worker thread until the request completes
                # (ThreadingHTTPServer: scrapes stay responsive)
                code, payload = handler(payload)
        if not isinstance(payload, dict):
            # streaming /generate: the handler returned an EVENT ITERATOR
            # instead of a body — relay it as chunked ndjson, one JSON
            # object per line, flushed per event so TTFT is wire-visible
            self._stream_events(code, payload)
            return
        body = json.dumps(payload, sort_keys=True).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if code == 429 and isinstance(payload, dict) \
                and payload.get("retry_after_s") is not None:
            # the overload-shed contract (scheduler.QueueFull -> 429):
            # well-behaved clients honor the standard header; the body
            # carries the same value for the router's JSON path
            self.send_header("Retry-After",
                             str(max(1, int(payload["retry_after_s"]))))
        self.end_headers()
        self.wfile.write(body)

    def _stream_events(self, code: int, events) -> None:
        """Chunked-transfer ndjson relay for streaming /generate: each
        event is one JSON line in one HTTP chunk.  A client that hangs
        up mid-stream closes the generator (its engine-side request
        keeps running — an idempotent retry can resume and replay the
        unsent suffix); the generator itself signals failures in-band
        with a terminal ``error`` event."""
        self.send_response(code)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            for event in events:
                data = json.dumps(event, sort_keys=True).encode() + b"\n"
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass                 # client went away: stop relaying
        finally:
            close = getattr(events, "close", None)
            if close is not None:
                close()

    MAX_WINDOW_KEYS = 64

    def _windowed(self, key: str) -> dict:
        """Delta snapshot vs the previous scrape of the same ``window``
        key (state lives on the HTTP server object, shared across the
        handler instances it spawns per request).  Each key stores one
        full snapshot, and the key space is CLIENT-supplied — cap it and
        evict the least-recently-scraped key so a scraper that appends a
        timestamp (or a hostile client) cannot grow memory unboundedly."""
        now = time.monotonic()
        snap = self.registry.typed_snapshot()
        srv = self.server
        with srv.window_lock:
            prev = srv.window_state.get(key)
            srv.window_state[key] = (now, snap)
            while len(srv.window_state) > self.MAX_WINDOW_KEYS:
                oldest = min(srv.window_state,
                             key=lambda k: srv.window_state[k][0])
                del srv.window_state[oldest]
        if prev is None:
            return {"window": key, "primed": True, "window_s": 0.0,
                    "metrics": {}}
        dt = now - prev[0]
        return {"window": key, "primed": False,
                "window_s": round(dt, 6),
                "metrics": window_delta(prev[1], snap, dt)}

    def _profilez(self, qs: dict):
        """``/profilez?steps=N[&timeout=S]``: park a capture request on
        the profile broker and block this HTTP worker (ThreadingHTTPServer
        — the scrape endpoints stay responsive) until a live engine
        fulfills it.  Returns (status_code, json_payload)."""
        from deepspeed_tpu.profiling.device_trace import (get_profile_broker,
                                                          perfetto_supported)

        if not perfetto_supported():
            return 501, {"error": "this jax's start_trace has no "
                                  "create_perfetto_trace; device-true "
                                  "profiling unavailable"}
        try:
            steps = int(qs.get("steps", ["2"])[0])
            timeout = float(qs.get("timeout", ["60"])[0])
        except ValueError:
            return 400, {"error": "steps/timeout must be numeric"}
        broker = get_profile_broker()
        try:
            req = broker.submit(steps)
        except RuntimeError as exc:
            return 409, {"error": str(exc)}
        try:
            return 200, req.wait(timeout)
        except TimeoutError as exc:
            broker.cancel(req)
            return 504, {"error": str(exc)}
        except RuntimeError as exc:
            return 500, {"error": str(exc)}

    def log_message(self, fmt, *args):  # scrapes are not log lines
        pass


class MetricsServer:
    """Serve ``/metrics`` + ``/statz`` for a registry on a daemon thread."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1", health=None):
        self.registry = registry if registry is not None else get_registry()
        self._requested_port = port
        self.host = host
        # replica-scoped readiness (None = the process-global HealthState)
        self.health = health
        self._generate_handler = None
        self._kv_offer_handler = None
        self._kv_adopt_handler = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        """The BOUND port (differs from the requested one when port=0)."""
        return self._httpd.server_address[1] if self._httpd else \
            self._requested_port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        handler = type("Handler", (_Handler,), {"registry": self.registry})
        self._httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                          handler)
        self._httpd.daemon_threads = True
        # per-window-key previous snapshots for /statz?window= deltas
        self._httpd.window_state = {}
        self._httpd.window_lock = threading.Lock()
        self._httpd.health = self.health
        self._httpd.generate_handler = self._generate_handler
        self._httpd.kv_offer_handler = self._kv_offer_handler
        self._httpd.kv_adopt_handler = self._kv_adopt_handler
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="ds-metrics-http", daemon=True)
        self._thread.start()
        logger.info("metrics server: %s/metrics (Prometheus), %s/statz "
                    "(JSON)", self.url, self.url)
        return self

    def set_generate_handler(self, fn) -> None:
        """Attach the serving engine's ``POST /generate`` handler
        (``fn(payload: dict) -> (status_code, json_payload)``, where the
        payload may be an ndjson event ITERATOR for streaming
        dispatches); None detaches (subsequent POSTs get 503)."""
        self._generate_handler = fn
        if self._httpd is not None:
            self._httpd.generate_handler = fn

    def set_kv_handoff_handlers(self, offer_fn, adopt_fn) -> None:
        """Attach the decode-side KV-page handoff pair (``POST
        /kv_offer`` + ``POST /kv_adopt`` — disaggregated serving); None
        detaches either."""
        self._kv_offer_handler = offer_fn
        self._kv_adopt_handler = adopt_fn
        if self._httpd is not None:
            self._httpd.kv_offer_handler = offer_fn
            self._httpd.kv_adopt_handler = adopt_fn

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        self._thread = None
