"""Rolling-median step-time watchdog.

Long runs stall in ways nobody is watching for: a straggler host, a
network hiccup re-running a collective, a data loader blocking the
dispatch thread.  The watchdog watches the one signal every training loop
already has — wall time between optimizer boundaries — and when a step
exceeds ``factor`` x the rolling median it fires ONCE, arming a
flight-recorder dump plus (engine-side) a one-shot device-trace capture of
the following steps, closing the crash/stall post-mortem loop PR 3 left
open.

Steady-state cost contract (asserted in tests/unit/test_watchdog.py, the
PR 2 no-alloc style): after warmup, ``observe`` is ONE deque append + ONE
float comparison (+ an integer countdown).  The trick is a cached trip
*bound*: the true median is recomputed only when a sample exceeds the
bound (``median_recomputes`` counts those slow paths) — a suspect either
confirms as a trip or raises the bound, so steady traffic never sorts on
the suspect path.  Because the bound can also become STALE-HIGH when the
median falls (the warmup window swallows multi-second compiles, then real
steps are milliseconds — observed live: a 150x-median stall that never
tripped), it is additionally re-anchored to ``factor x median`` once per
``window`` samples (``bound_refreshes``; one 64-float sort amortized over
64 steps).  ``observe`` is REBOUND from the warmup method to the steady
method once the window has enough samples, so the steady path carries no
warmup branch at all.  After a trip the bound is parked at +inf and the
re-anchor is suppressed: one-shot by construction, no re-trigger storm;
``reset()`` re-arms.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Dict, Optional

__all__ = ["StepWatchdog"]


class StepWatchdog:
    def __init__(self, factor: float = 10.0, window: int = 64,
                 warmup: int = 5):
        if factor <= 1.0:
            raise ValueError(f"watchdog factor must be > 1, got {factor}")
        self.factor = float(factor)
        self.window = max(2, int(window))
        # warmup > window could never arm (the deque holds at most
        # `window` samples, so the warmup gate would never be reached)
        self.warmup = min(max(2, int(warmup)), self.window)
        self._dq: deque = deque(maxlen=self.window)
        self._bound = math.inf
        self._refresh = self.window
        self.fired = False
        self.last_trip: Optional[Dict[str, Any]] = None
        self.median_recomputes = 0
        self.bound_refreshes = 0
        self.observe = self._observe_warmup   # rebound to steady at warmup

    # -- warmup path (first `warmup` samples; never trips) --------------
    def _observe_warmup(self, seconds: float) -> bool:
        self._dq.append(seconds)
        if len(self._dq) >= self.warmup:
            self._bound = self.factor * self._median()
            self.observe = self._observe_steady
        return False

    # -- steady path: ONE append + ONE comparison (+ countdown) ---------
    def _observe_steady(self, seconds: float) -> bool:
        self._dq.append(seconds)
        if seconds <= self._bound:
            self._refresh -= 1
            if self._refresh <= 0:
                self._refresh = self.window
                if not self.fired:
                    # the median can FALL (compile-inflated warmup, caches
                    # warming): re-anchor the cached bound once per window
                    # so a stall vs the new fast median still trips
                    self._bound = self.factor * self._median()
                    self.bound_refreshes += 1
            return False
        return self._suspect(seconds)

    # -- slow path (a sample exceeded the cached bound) -----------------
    def _suspect(self, seconds: float) -> bool:
        # median over the window EXCLUDING the suspect itself (it was just
        # appended): the anomaly must not drag its own trip bar up
        self.median_recomputes += 1
        vals = list(self._dq)
        vals.pop()
        med = self._median(vals)
        if med > 0 and seconds > self.factor * med:
            self.fired = True
            self.last_trip = {"seconds": seconds, "median": med,
                              "factor": self.factor,
                              "ratio": seconds / med,
                              "samples": len(vals)}
            # one-shot: nothing compares above +inf until reset()
            self._bound = math.inf
            return True
        # false alarm (the median drifted up): refresh the cached bound so
        # the new normal stops taking the slow path
        self._bound = self.factor * max(med, seconds / self.factor)
        return False

    def _median(self, vals=None) -> float:
        vals = sorted(vals if vals is not None else self._dq)
        n = len(vals)
        if not n:
            return 0.0
        mid = n // 2
        return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])

    @property
    def median(self) -> float:
        """Current rolling median (reads sort; not the hot path)."""
        return self._median()

    def reset(self) -> None:
        """Re-arm after a trip (the engine calls this if configured to
        watch for repeat anomalies after the capture completes)."""
        self.fired = False
        self.last_trip = None
        self._dq.clear()
        self._bound = math.inf
        self._refresh = self.window
        self.observe = self._observe_warmup
