"""Zero-dependency serving/inference/training metrics registry.

The serving path (slot-pool KV cache, chunked prefill interleaved with
decode) is the hottest surface in the repo, and phase attribution — queue
wait vs. prefill vs. decode — is exactly what goodput optimization needs
(you cannot overlap phases you cannot see).  This module is the host-side
half of that story: ``Counter`` / ``Gauge`` / log-bucketed ``Histogram``
instruments behind a process-global :class:`MetricsRegistry`, exported as
Prometheus exposition text, a JSON snapshot, or ``MonitorMaster`` events
(CSV/TensorBoard).  The device-side half is the ``ds_serve_*``
``jax.profiler.TraceAnnotation`` ranges (profiling/trace.py), which carry
the same phase names into the xplane trace so host histograms and device
timelines line up.

Design constraints, in order:

- **Disabled is free.**  The registry starts disabled; every ``inc`` /
  ``set`` / ``record`` costs ONE attribute-load + branch and allocates
  nothing.  Serving code can therefore instrument unconditionally.
- **Lock-free single-writer.**  Recording happens on the engine thread;
  scrapes happen on the HTTP thread.  Instruments use plain int/float
  stores (atomic under the GIL) — no lock on the hot path.  Readers get
  snapshot-consistent views: a histogram snapshot copies the bucket list
  in one bytecode op and derives ``count`` from the copy, so ``count ==
  sum(buckets)`` always holds even mid-write (``sum`` may trail by the
  in-flight record; it never tears).
- **One schema.**  Training (wall-clock timers), inference (generate()),
  and serving (request lifecycle) all land in the same registry under the
  ``ds_`` namespace — see docs/OBSERVABILITY.md for the full name/label
  schema; tests/unit/test_metrics.py fails the suite if an undocumented
  or non-``ds_`` name is registered.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "window_delta", "DEFAULT_BUCKETS"]


def _render_labels(labels: Optional[Tuple[Tuple[str, str], ...]]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + body + "}"


class _Instrument:
    """Common core: a name, optional static labels, and the enabled check.

    Labels are STATIC (fixed at registration) — per-request dynamic label
    cardinality is a metrics-system footgun this layer deliberately omits;
    register one instrument per label value (e.g. the finish-reason
    counters) instead.
    """

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self._registry = registry
        self.name = name
        self.help = help
        self.labels = tuple(sorted((labels or {}).items()))

    # exposition -------------------------------------------------------
    def _label_str(self) -> str:
        return _render_labels(self.labels)

    def _event_name(self) -> str:
        """MonitorMaster event name: labels fold into the path."""
        tail = "/".join(v for _, v in self.labels)
        return f"{self.name}/{tail}" if tail else self.name


class Counter(_Instrument):
    """Monotonic count (requests, tokens, compiles)."""

    kind = "counter"

    def __init__(self, registry, name, help="", labels=None):
        super().__init__(registry, name, help, labels)
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if not self._registry._enabled:
            return
        self._value += n

    @property
    def value(self) -> int:
        return self._value

    def _reset(self) -> None:
        self._value = 0

    def _snapshot(self):
        return self._value

    def _prom_lines(self) -> List[str]:
        return [f"{self.name}{self._label_str()} {self._value}"]

    def _events(self, step: int):
        return [(self._event_name(), self._value, step)]


class Gauge(_Instrument):
    """Last-observed value (active slots, queue depth)."""

    kind = "gauge"

    def __init__(self, registry, name, help="", labels=None):
        super().__init__(registry, name, help, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        if not self._registry._enabled:
            return
        self._value = v

    @property
    def value(self) -> float:
        return self._value

    def _reset(self) -> None:
        self._value = 0.0

    def _snapshot(self):
        return self._value

    def _prom_lines(self) -> List[str]:
        return [f"{self.name}{self._label_str()} {_fmt(self._value)}"]

    def _events(self, step: int):
        return [(self._event_name(), self._value, step)]


def _log_buckets(lo: float, hi: float, per_decade: int) -> Tuple[float, ...]:
    """Log-spaced bucket upper bounds covering [lo, hi]."""
    n = int(math.ceil(per_decade * math.log10(hi / lo))) + 1
    return tuple(lo * 10 ** (i / per_decade) for i in range(n))


# 1us .. 100s at 4 buckets/decade (33 buckets): spans sub-ms decode steps
# through multi-second queue waits with <= ~78% relative bucket width, i.e.
# quantile estimates good to well under 2x — plenty for p50/p90/p99 latency
# attribution, at a fixed 33-slot footprint per histogram.
DEFAULT_BUCKETS = _log_buckets(1e-6, 100.0, 4)


class Histogram(_Instrument):
    """Fixed log-bucketed distribution with cheap quantile estimates.

    Single-writer: ``record`` does a branch, a bisect over the fixed bucket
    bounds, and two scalar stores — no allocation, no lock.  Readers use
    :meth:`snapshot`, which copies the bucket-count list atomically (one
    ``list()`` bytecode op under the GIL) and derives totals from the copy.
    """

    kind = "histogram"

    def __init__(self, registry, name, help="", labels=None,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help, labels)
        self.bounds = tuple(float(b) for b in buckets)
        # one extra overflow bucket (> bounds[-1], the +Inf bucket)
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0

    def record(self, v: float) -> None:
        if not self._registry._enabled:
            return
        bounds = self.bounds
        lo, hi = 0, len(bounds)
        while lo < hi:              # branchless-ish bisect, no imports
            mid = (lo + hi) // 2
            if v <= bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        self._counts[lo] += 1
        self._sum += v

    # -- reads ---------------------------------------------------------
    @property
    def count(self) -> int:
        return sum(self._counts)

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        n = self.count
        return self._sum / n if n else 0.0

    def snapshot(self) -> Dict[str, object]:
        counts = list(self._counts)          # atomic copy under the GIL
        n = sum(counts)
        q = {p: _quantile_from_counts(self.bounds, counts, p)
             for p in (0.5, 0.9, 0.99)}
        return {"count": n, "sum": self._sum,
                "mean": (self._sum / n if n else 0.0),
                "p50": q[0.5], "p90": q[0.9], "p99": q[0.99],
                "buckets": counts}

    def quantile(self, q: float) -> float:
        return _quantile_from_counts(self.bounds, list(self._counts), q)

    def _reset(self) -> None:
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0

    def _snapshot(self):
        return self.snapshot()

    def _prom_lines(self) -> List[str]:
        counts = list(self._counts)
        lines, cum = [], 0
        base = dict(self.labels)
        for b, c in zip(self.bounds, counts):
            cum += c
            labels = _render_labels(tuple(sorted({**base,
                                                  "le": _fmt(b)}.items())))
            lines.append(f"{self.name}_bucket{labels} {cum}")
        labels = _render_labels(tuple(sorted({**base, "le": "+Inf"}.items())))
        lines.append(f"{self.name}_bucket{labels} {cum + counts[-1]}")
        ls = self._label_str()
        lines.append(f"{self.name}_sum{ls} {_fmt(self._sum)}")
        lines.append(f"{self.name}_count{ls} {cum + counts[-1]}")
        return lines

    def _events(self, step: int):
        s = self.snapshot()
        base = self._event_name()
        return [(f"{base}/count", s["count"], step),
                (f"{base}/mean", s["mean"], step),
                (f"{base}/p50", s["p50"], step),
                (f"{base}/p99", s["p99"], step)]


def _quantile_from_counts(bounds: Tuple[float, ...], counts: List[int],
                          q: float) -> float:
    """Quantile estimate: find the bucket holding rank q*n and interpolate
    linearly inside it (the overflow bucket reports its lower bound)."""
    n = sum(counts)
    if n == 0:
        return 0.0
    rank = q * n
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= rank:
            if i >= len(bounds):         # overflow bucket: no upper bound
                return bounds[-1]
            lo = bounds[i - 1] if i > 0 else 0.0
            frac = (rank - cum) / c
            return lo + frac * (bounds[i] - lo)
        cum += c
    return bounds[-1]


def _fmt(v: float) -> str:
    """Prometheus float formatting: integral values render bare; others at
    9 significant digits (stable across scrapes, and distinct for every
    log bucket bound — adjacent bounds differ by ~78%)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return format(f, ".9g")


class MetricsRegistry:
    """Process-global instrument registry.

    ``counter`` / ``gauge`` / ``histogram`` create-or-return instruments
    keyed by (name, labels): calling twice with the same key returns the
    SAME instrument (engines re-instantiated in one process share series),
    while re-registering a name as a different kind raises — that is the
    duplicate-name bug the tier-1 guard test exists to catch.

    Registration takes a lock (cold path); recording does not (see module
    docstring).  ``enable()``/``disable()`` flip the one flag every record
    checks.
    """

    def __init__(self):
        self._enabled = False
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            _Instrument] = {}

    # -- switch --------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> "MetricsRegistry":
        self._enabled = True
        return self

    def disable(self) -> "MetricsRegistry":
        self._enabled = False
        return self

    # -- registration --------------------------------------------------
    def _register(self, cls, name, help, labels, **kw):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            inst = self._metrics.get(key)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {inst.kind}, "
                        f"cannot re-register as {cls.kind}")
                return inst
            existing = None
            for (n, lb), m in self._metrics.items():
                if n == name:
                    existing = (lb, m)
                    break
            if existing is not None:
                lb, m = existing
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind} "
                        f"(with other labels), cannot register as {cls.kind}")
                if bool(lb) != bool(key[1]):
                    # a name must be uniformly labeled or uniformly bare:
                    # mixing makes the snapshot's {name: value-or-family}
                    # shape ambiguous (it would crash or drop series at
                    # SCRAPE time, far from the offending registration)
                    raise ValueError(
                        f"metric {name!r} is already registered "
                        f"{'with' if lb else 'without'} labels; cannot "
                        f"register it {'without' if lb else 'with'} labels")
            inst = cls(self, name, help=help, labels=labels, **kw)
            self._metrics[key] = inst
            return inst

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._register(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._register(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, labels, buckets=buckets)

    def names(self) -> List[str]:
        with self._lock:
            return sorted({n for n, _ in self._metrics})

    def get(self, name: str,
            labels: Optional[Dict[str, str]] = None) -> Optional[_Instrument]:
        key = (name, tuple(sorted((labels or {}).items())))
        return self._metrics.get(key)

    def reset(self) -> None:
        """Zero every instrument's VALUES; registrations (and instrument
        identity — engines hold direct references) are kept.  Benchmarks
        reset between warm and recorded passes."""
        with self._lock:
            for m in self._metrics.values():
                m._reset()

    # -- export --------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """JSON-able snapshot: {name: value | histogram-dict |
        {label_str: ...} when a name carries labels}."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, object] = {}
        for (name, labels), m in items:
            v = m._snapshot()
            if labels:
                slot = out.setdefault(name, {})
                slot[_render_labels(labels)] = v
            else:
                out[name] = v
        return out

    def typed_snapshot(self) -> Dict[Tuple[str, str], Tuple[str, object]]:
        """Flat kind-tagged snapshot ``{(name, label_str): (kind, value)}``
        — the ``/statz?window=`` delta endpoint needs kinds to know whether
        to difference (counter/histogram) or report as-is (gauge); the
        plain :meth:`snapshot` erases them."""
        with self._lock:
            items = list(self._metrics.items())
        return {(name, _render_labels(labels)): (m.kind, m._snapshot())
                for (name, labels), m in items}

    def statz_json(self) -> str:
        return json.dumps({"enabled": self._enabled,
                           "metrics": self.snapshot()},
                          sort_keys=True)

    def prometheus_text(self) -> str:
        """Prometheus/OpenMetrics text exposition (one HELP/TYPE block per
        name; instruments sharing a name but differing in labels render
        under one block)."""
        with self._lock:
            items = sorted(self._metrics.items())
        lines: List[str] = []
        seen_header = set()
        for (name, _), m in items:
            if name not in seen_header:
                seen_header.add(name)
                if m.help:
                    lines.append(f"# HELP {name} {m.help}")
                lines.append(f"# TYPE {name} {m.kind}")
            lines.extend(m._prom_lines())
        return "\n".join(lines) + ("\n" if lines else "")

    def publish(self, monitor, step: int) -> None:
        """Bridge to a :class:`deepspeed_tpu.monitor.monitor.MonitorMaster`
        (CSV / TensorBoard / W&B fan-out): counters and gauges emit their
        value, histograms emit count/mean/p50/p99 sub-series."""
        if monitor is None or not getattr(monitor, "enabled", False):
            return
        with self._lock:
            items = sorted(self._metrics.items())
        events = []
        for _, m in items:
            events.extend(m._events(step))
        if events:
            monitor.write_events(events)


def window_delta(prev: Dict[Tuple[str, str], Tuple[str, object]],
                 cur: Dict[Tuple[str, str], Tuple[str, object]],
                 dt: float) -> Dict[str, object]:
    """Difference two :meth:`MetricsRegistry.typed_snapshot` results taken
    ``dt`` seconds apart into the ``/statz?window=`` response shape:

    - counters   -> ``{"delta", "per_sec"}``
    - histograms -> ``{"count_delta", "per_sec", "window_mean"}`` (mean of
      the values recorded *inside* the window)
    - gauges     -> ``{"value"}`` (last observed; deltas are meaningless)

    A series absent from ``prev`` (registered mid-window) baselines at
    zero, so its whole current value is the delta.  A current value BELOW
    the baseline means the registry was reset mid-window (``reset()`` is a
    public API the bench uses between passes) — Prometheus counter
    semantics apply: the baseline clamps to zero rather than emitting a
    negative rate.  Labeled families nest the same way
    :meth:`MetricsRegistry.snapshot` does.
    """
    rate = (1.0 / dt) if dt > 0 else 0.0
    out: Dict[str, object] = {}
    for (name, ls), (kind, v) in cur.items():
        if kind == "counter":
            base = prev.get((name, ls))
            d = v - (base[1] if base else 0)
            if d < 0:                      # reset between scrapes
                d = v
            entry = {"delta": d, "per_sec": d * rate}
        elif kind == "histogram":
            base = prev.get((name, ls))
            pc = base[1] if base else {"count": 0, "sum": 0.0}
            dc = v["count"] - pc["count"]
            ds = v["sum"] - pc["sum"]
            if dc < 0 or ds < 0:           # reset between scrapes
                dc, ds = v["count"], v["sum"]
            entry = {"count_delta": dc, "per_sec": dc * rate,
                     "window_mean": (ds / dc) if dc else 0.0}
        else:
            entry = {"value": v}
        if ls:
            out.setdefault(name, {})[ls] = entry
        else:
            out[name] = entry
    return out


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry every engine records into."""
    return _REGISTRY
