"""Monitoring backends.

TPU-native analog of the reference's ``deepspeed/monitor/`` (SURVEY.md §2.1
"Monitor"): ``MonitorMaster`` fans ``write_events([(name, value, step)])`` out
to TensorBoard / W&B / CSV backends per config.  CSV is always available;
TensorBoard and W&B engage only if their packages are importable (they are
optional in this environment).
"""

from __future__ import annotations

import csv
import os
from typing import Any, List, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, Any, int]


class Monitor:
    def __init__(self, config):
        self.config = config
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, events: Sequence[Event]) -> None:  # pragma: no cover - ABC-ish
        raise NotImplementedError


class csvMonitor(Monitor):  # noqa: N801 - reference class name
    def __init__(self, config):
        super().__init__(config)
        self._writers = {}
        if self.enabled:
            self.output_path = config.output_path or "./csv_monitor"
            self.job_name = config.job_name
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            fname = os.path.join(self.output_path, self.job_name,
                                 name.replace("/", "_") + ".csv")
            is_new = not os.path.exists(fname)
            with open(fname, "a", newline="") as fh:
                w = csv.writer(fh)
                if is_new:
                    w.writerow(["step", name])
                w.writerow([step, float(value)])


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter

                path = os.path.join(config.output_path or "./tensorboard", config.job_name)
                self.summary_writer = SummaryWriter(log_dir=path)
            except Exception as exc:
                logger.warning("tensorboard monitor disabled: %s", exc)
                self.enabled = False

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.enabled or self.summary_writer is None:
            return
        for name, value, step in events:
            self.summary_writer.add_scalar(name, float(value), step)
        self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        if self.enabled:
            try:
                import wandb

                wandb.init(team=config.team, project=config.project, group=config.group)
                self._wandb = wandb
            except Exception as exc:
                logger.warning("wandb monitor disabled: %s", exc)
                self.enabled = False

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self._wandb.log({name: value}, step=step)


class MonitorMaster(Monitor):
    """Fan-out to all enabled backends (reference: ``MonitorMaster``).  Only
    process 0 writes, matching the reference's rank-0 gating."""

    def __init__(self, ds_config):
        self.monitors: List[Monitor] = []
        import jax

        if jax.process_index() == 0:
            for cls, cfg in ((TensorBoardMonitor, ds_config.tensorboard),
                             (WandbMonitor, ds_config.wandb),
                             (csvMonitor, ds_config.csv_monitor)):
                m = cls(cfg)
                if m.enabled:
                    self.monitors.append(m)
        self.enabled = bool(self.monitors)

    def write_events(self, events: Sequence[Event]) -> None:
        for m in self.monitors:
            m.write_events(events)
