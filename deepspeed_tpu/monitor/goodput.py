"""GoodputLedger: the process-side wrapper over ``goodput_core``.

Owns the live :class:`~deepspeed_tpu.monitor.goodput_core.LedgerCore`,
its ``runledger.jsonl`` persistence, the ``ds_run_*`` metric export, and
the declarative SLO burn-rate watcher — the run-scope sibling of the
request tracer and step timeline (docs/OBSERVABILITY.md "Goodput
ledger").

Disabled-is-free contract (the repo-wide telemetry discipline): every
hot-path entry point (``push``/``pop``/``shift``/``add_tokens``/
``tick``) is one attribute load + one branch while disabled.  Engines
instrument unconditionally.

Enablement: ``goodput`` config block (training), serving config /
``init_serving``, or the ``DSTPU_RUNLEDGER=<path>`` environment variable
— the supervisors' channel: they export the path + ``DSTPU_RUN_ID`` to
every child incarnation, and each incarnation self-identifies via
``DS_SUPERVISOR_RESTART`` so ``stitch`` can fold the jsonl back into
one run timeline.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from deepspeed_tpu.monitor import goodput_core as core
from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
from deepspeed_tpu.monitor.metrics import get_registry

__all__ = ["GoodputLedger", "SloWatcher", "get_goodput_ledger",
           "CATEGORIES"]

CATEGORIES = core.CATEGORIES

_RUN_GOODPUT_HELP = ("fraction of run wall clock attributed to productive "
                     "compute (goodput ledger)")
_RUN_TIME_HELP = ("run wall-clock seconds attributed to this ledger "
                  "category (sums to run wall time)")
_SLO_BURN_HELP = "SLO burn events emitted by the declarative rule watcher"


class SloWatcher:
    """Declarative burn-rate rules over ledger + registry truths.

    ``rules`` is the ``slo:`` config block: a mapping of rule name ->
    threshold.  Supported rules (docs/OBSERVABILITY.md):

    - ``goodput_ratio`` (MIN): ledger goodput below the threshold burns.
    - ``ttft_p99_s`` (MAX): serving TTFT p99 (``ds_serve_ttft_seconds``)
      above the threshold burns.
    - ``shed_ratio`` (MAX): ``ds_serve_shed_total / ds_serve_submitted_total``
      above the threshold burns.

    Each evaluation that breaches emits one flight-recorder ``slo_burn``
    event, increments ``ds_slo_burn_total{rule=}``, and appends an
    ``slo_burn`` jsonl row — evaluations ride the ledger's (rate-limited)
    boundary ticks, so a sustained breach burns at tick cadence, the
    burn-rate framing.
    """

    KNOWN = ("goodput_ratio", "ttft_p99_s", "shed_ratio")

    def __init__(self, rules: Dict[str, float]):
        self.rules = {k: float(v) for k, v in (rules or {}).items()
                      if v is not None and k in self.KNOWN}
        self._counters: Dict[str, Any] = {}

    def _observe(self, rule: str,
                 snapshot: Dict[str, Any]) -> Optional[float]:
        reg = get_registry()
        if rule == "goodput_ratio":
            return float(snapshot.get("goodput_ratio", 0.0))
        if rule == "ttft_p99_s":
            hist = reg.get("ds_serve_ttft_seconds")
            if hist is None or not getattr(hist, "count", 0):
                return None
            return float(hist.quantile(0.99))
        if rule == "shed_ratio":
            shed = reg.get("ds_serve_shed_total")
            sub = reg.get("ds_serve_submitted_total")
            if shed is None or sub is None or not sub.value:
                return None
            return float(shed.value) / float(sub.value)
        return None

    def _breached(self, rule: str, observed: float) -> bool:
        if rule == "goodput_ratio":          # MIN rule
            return observed < self.rules[rule]
        return observed > self.rules[rule]   # MAX rules

    def evaluate(self, snapshot: Dict[str, Any],
                 ledger: "GoodputLedger") -> int:
        """One boundary-tick evaluation; returns breach count."""
        burns = 0
        flight = get_flight_recorder()
        reg = get_registry()
        for rule, target in self.rules.items():
            observed = self._observe(rule, snapshot)
            if observed is None or not self._breached(rule, observed):
                continue
            burns += 1
            c = self._counters.get(rule)
            if c is None:
                c = self._counters[rule] = reg.counter(
                    "ds_slo_burn_total", _SLO_BURN_HELP,
                    labels={"rule": rule})
            c.inc()
            flight.record("slo_burn", rule=rule, observed=round(observed, 6),
                          target=target)
            ledger._append(core.slo_burn_row(
                ledger.run_id, ledger.incarnation, rule, observed, target,
                time.time()))
        return burns


class GoodputLedger:
    """Process-global run ledger; see module docstring."""

    def __init__(self):
        self.enabled = False
        self._core: Optional[core.LedgerCore] = None
        self._path: Optional[str] = None
        self.run_id = ""
        self.incarnation = 0
        self.role = "train"
        self._min_tick_interval_s = 0.0
        self._last_tick_t = float("-inf")
        self._slo: Optional[SloWatcher] = None
        self._lock = threading.Lock()
        self._gauges: Dict[str, Any] = {}
        self._ratio_gauge = None
        self._event_seq = 0

    # -- lifecycle ------------------------------------------------------
    def enable(self, path: Optional[str] = None, run_id: Optional[str] = None,
               role: str = "train", incarnation: Optional[int] = None,
               min_tick_interval_s: Optional[float] = None,
               slo_rules: Optional[Dict[str, float]] = None) -> "GoodputLedger":
        """Idempotent; re-enabling updates the SLO rules/path but keeps
        the running attribution (two engines in one process share one
        run clock)."""
        with self._lock:
            if self._core is None:
                self._core = core.LedgerCore(time.perf_counter())
            self._path = (path or os.environ.get("DSTPU_RUNLEDGER")
                          or self._path)
            self.run_id = (run_id or os.environ.get("DSTPU_RUN_ID")
                           or self.run_id
                           or f"run-{os.getpid()}-{int(time.time())}")
            self.incarnation = int(
                incarnation if incarnation is not None
                else os.environ.get("DS_SUPERVISOR_RESTART", "0") or 0)
            self.role = role
            if min_tick_interval_s is not None:
                self._min_tick_interval_s = float(min_tick_interval_s)
            if slo_rules:
                self._slo = SloWatcher(slo_rules)
            first = not self.enabled
            self.enabled = True
            if first:
                self._start_unix = time.time()
                self._append(core.start_row(self.run_id, self.incarnation,
                                            role, self._start_unix))
        return self

    def disable(self) -> None:
        """Final tick + detach (process exit / test teardown)."""
        if not self.enabled:
            return
        self.tick(force=True)
        with self._lock:
            self.enabled = False
            self._core = None
            self._path = None
            self._slo = None
            self._gauges.clear()
            self._ratio_gauge = None
            self._last_tick_t = float("-inf")

    # -- hot-path attribution ------------------------------------------
    def push(self, category: str) -> None:
        if not self.enabled:
            return
        self._core.push(category, time.perf_counter())

    def pop(self) -> float:
        """Close the innermost region; returns its DIRECT seconds (time
        not attributed to nested regions)."""
        if not self.enabled:
            return 0.0
        return self._core.pop(time.perf_counter())[1]

    def shift(self, src: str, dst: str, seconds: float) -> float:
        if not self.enabled:
            return 0.0
        return self._core.shift(src, dst, seconds)

    def add_tokens(self, n: int) -> None:
        if not self.enabled:
            return
        self._core.tokens += int(n)

    def set_steps(self, n: int) -> None:
        if not self.enabled:
            return
        self._core.steps = int(n)

    # -- reading / exporting -------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        if not self.enabled:
            return {"enabled": False}
        snap = self._core.snapshot(time.perf_counter())
        snap["enabled"] = True
        snap["run_id"] = self.run_id
        snap["incarnation"] = self.incarnation
        snap["role"] = self.role
        snap["path"] = self._path
        return snap

    def note_event(self, event: str, dur_s: float, **extra: Any) -> str:
        """Durable event row sharing an id with the flight recorder
        (the checkpoint reconciliation satellite); returns the id."""
        if not self.enabled:
            return ""
        self._event_seq += 1
        event_id = f"{self.run_id}:{self.incarnation}:{event}:{self._event_seq}"
        self._append(core.event_row(self.run_id, self.incarnation, event,
                                    event_id, time.time(), dur_s=dur_s,
                                    **extra))
        return event_id

    def tick(self, force: bool = False) -> Optional[Dict[str, Any]]:
        """Boundary tick: export gauges, persist a cumulative jsonl row,
        evaluate SLO rules.  Rate-limited by ``min_tick_interval_s``
        (0 = every call)."""
        if not self.enabled:
            return None
        now = time.perf_counter()
        if not force and now - self._last_tick_t < self._min_tick_interval_s:
            return None
        self._last_tick_t = now
        snap = self._core.snapshot(now)
        reg = get_registry()
        if reg.enabled:
            if self._ratio_gauge is None:
                self._ratio_gauge = reg.gauge("ds_run_goodput_ratio",
                                              _RUN_GOODPUT_HELP)
            self._ratio_gauge.set(snap["goodput_ratio"])
            for cat, v in snap["categories"].items():
                g = self._gauges.get(cat)
                if g is None:
                    g = self._gauges[cat] = reg.gauge(
                        "ds_run_time_seconds", _RUN_TIME_HELP,
                        labels={"category": cat})
                g.set(v)
        self._append(core.tick_row(self.run_id, self.incarnation,
                                   time.time(), snap["wall_s"], snap))
        if self._slo is not None:
            self._slo.evaluate(snap, self)
        return snap

    # -- internals ------------------------------------------------------
    def _append(self, row: Dict[str, Any]) -> None:
        if self._path:
            core.append_row(self._path, row)


_ledger: Optional[GoodputLedger] = None
_ledger_lock = threading.Lock()


def get_goodput_ledger() -> GoodputLedger:
    global _ledger
    if _ledger is None:
        with _ledger_lock:
            if _ledger is None:
                _ledger = GoodputLedger()
    return _ledger
