"""Per-request span tracing for the serving stack.

The PR 2/4 serving metrics are *aggregate*: a p99 TTFT histogram can say
the tail is slow, but not WHICH requests were slow or WHY — queue wait vs
chunked prefill vs decode stretch vs paged-KV preemption.  This module is
the per-request half: the scheduler and serving engine already own every
lifecycle edge (submit, admit, each prefill chunk, first-token dispatch,
decode blocks, preempt/requeue, EOS-drain fetch, finish), and the
:class:`RequestTracer` records them into one timeline per request.

Two layers per timeline, with distinct semantics:

- **edges** — lifecycle transitions ``(t, phase_entered)``.  The phase a
  request is in between two consecutive edges is the phase entered at the
  first, so the per-request phase durations (``queue`` / ``prefill`` /
  ``decode`` / ``preempted_wait``) TELESCOPE to exactly
  ``t_finish - t_submit``: the phase-attribution histograms
  (``ds_serve_phase_*_seconds``, recorded at finish) reconcile with the
  existing ``ds_serve_request_latency_seconds`` observations by
  construction (tested).  ``prefill`` here is admit → first-token
  dispatch (it includes the slot's share of interleaving with other
  slots' chunks — that IS the latency the request experienced);
  ``preempted_wait`` is preempt → re-admission.
- **spans** — the measured host dispatch windows inside those phases
  (``prefill_chunk`` / ``decode_block`` / ``drain_fetch``, each with its
  token count), capped per request so a pathological run cannot grow a
  timeline unboundedly.

Retention is fixed-size: a ring of the most recently completed timelines
plus a top-K slowest-exemplar heap (the tail survives even when the ring
has churned past it).  Disabled (the default) every hook is ONE
attribute-load + branch and allocates nothing — the same hot-path
contract as ``monitor/metrics.py``; enable via
``init_serving(request_trace=True)`` or ``get_request_tracer().enable()``.

Exports (see docs/OBSERVABILITY.md "Request spans"):

- ``GET /requestz`` on the metrics server — JSON snapshot (recent ring,
  slowest exemplars, tail attribution);
- ``GET /requestz?format=perfetto`` — trace-event JSON whose timestamps
  are keyed to the clock anchor of the most recent profiler capture
  (:func:`set_trace_clock_anchor`, stamped by ``TraceCapture`` at
  ``start_trace`` time — the trace file's ts epoch), so request spans
  and a ``/profilez`` device capture load in ONE Perfetto session on a
  shared clock;
- :meth:`RequestTracer.tail_attribution` — the dominant phase among
  requests above the p99 latency cut, attached to the bench serving
  record and rendered by ``tools/metrics_dump.py --requests``.

Zero dependencies: stdlib + the metrics registry (itself stdlib-only).
"""

from __future__ import annotations

import heapq
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.monitor.metrics import get_registry

__all__ = ["RequestTracer", "get_request_tracer", "PHASES",
           "StepTimeline", "get_step_timeline",
           "set_trace_clock_anchor", "get_trace_clock_anchor"]

# the edge-partition phases; each gets a ds_serve_phase_<phase>_seconds
# histogram recorded once per finished request
PHASES = ("queue", "prefill", "decode", "preempted_wait")

DEFAULT_RING = 256
DEFAULT_SLOWEST_K = 32
DEFAULT_MAX_SPANS = 512


# ---------------------------------------------------------------------------
# trace clock anchor
# ---------------------------------------------------------------------------
# jax's perfetto export writes timestamps in microseconds RELATIVE to the
# profiler-session start (measured: the first event lands within ~100us of
# the start_trace call).  TraceCapture stamps this anchor immediately
# before start_trace, so mapping a perf_counter reading t to
# (t - anchor_perf) * 1e6 puts host spans in the SAME clock domain as the
# capture's device rows.  Before any capture runs, the anchor is the
# process import time (spans still export, just in a process-relative
# domain nothing else shares).

_ANCHOR: Dict[str, Any] = {"perf": time.perf_counter(), "unix": time.time(),
                           "source": "process"}

# dslint DSL006 contract (enforced statically, tools/dslint.py): the
# anchor is read lock-free by /requestz and the perfetto exporter — it
# may only be REBOUND whole, never patched field-by-field (a torn
# perf/unix pair was the PR 7 scrape-race class)
_DSLINT_SHARED_GLOBALS = {"_ANCHOR": "swap"}


def set_trace_clock_anchor() -> Dict[str, Any]:
    """Stamp 'now' as the trace-session clock epoch; returns a copy.
    Called by ``TraceCapture.maybe_start`` immediately before
    ``jax.profiler.start_trace`` (the perfetto file's ts epoch).  The
    global is swapped whole (never mutated in place) so a concurrent
    scrape can't read a torn perf/unix pair."""
    global _ANCHOR
    anchor = {"perf": time.perf_counter(), "unix": time.time(),
              "source": "trace_session"}
    _ANCHOR = anchor
    return dict(anchor)


def get_trace_clock_anchor() -> Dict[str, Any]:
    """The most recent capture's clock anchor (process-start fallback)."""
    return dict(_ANCHOR)


def _perfetto_doc(events: List[Dict[str, Any]],
                  anchor: Dict[str, Any]) -> Dict[str, Any]:
    """The ONE trace-event envelope every exporter in this process emits
    (request spans and the training step timeline both go through it):
    ``ts`` values are microseconds since ``anchor["perf"]``, and
    ``otherData.clock_anchor_unix`` is that same instant on the WALL
    clock — the per-endpoint translation key ``tools/fleet_dump.py
    --trace`` uses to merge N processes' exports onto one shared clock
    (ts' = ts + (anchor_unix_source - anchor_unix_reference) * 1e6)."""
    return {"displayTimeUnit": "ns", "traceEvents": events,
            "otherData": {"clock_anchor_unix": anchor["unix"],
                          "clock_source": anchor["source"],
                          "domain": "microseconds since the last "
                                    "profiler-session start"}}


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


class RequestTracer:
    """Process-global per-request span recorder (see module docstring).

    Single-writer like the metrics instruments: all hooks run on the
    engine thread; ``/requestz`` scrapes read completed timelines, which
    are append-only dicts swapped in whole (GIL-atomic)."""

    # dslint DSL006: scrape threads snapshot-copy these (list(self._ring))
    # — every writer-side mutation must be ONE GIL-atomic op (append /
    # heappush / whole rebind); published records are immutable
    _dslint_shared = {"_ring": "atomic", "_slowest": "atomic"}

    def __init__(self, ring: int = DEFAULT_RING,
                 slowest_k: int = DEFAULT_SLOWEST_K,
                 max_spans: int = DEFAULT_MAX_SPANS):
        self.enabled = False
        self._open: Dict[int, Dict[str, Any]] = {}
        self._ring: deque = deque(maxlen=max(1, int(ring)))
        self._slowest_k = max(1, int(slowest_k))
        self._slowest: List[Tuple[float, int, Dict[str, Any]]] = []
        self._max_spans = max(1, int(max_spans))
        self._seq = 0                 # completion order (heap tiebreak)
        self.completed_total = 0
        # phase-attribution histograms (registered unconditionally so the
        # namespace guard covers them; record() gates on the registry)
        reg = get_registry()
        self._h_phase = {
            p: reg.histogram(
                f"ds_serve_phase_{p}_seconds",
                f"per-request time in the {p} phase (edge partition; the "
                f"four phases sum to ds_serve_request_latency_seconds)")
            for p in PHASES}

    # -- switches -------------------------------------------------------
    def enable(self) -> "RequestTracer":
        self.enabled = True
        return self

    def disable(self) -> "RequestTracer":
        """Stop tracing and drop in-flight timelines: their finish edges
        will never be recorded while disabled, so keeping them would leak
        them as phantom 'open' requests forever (retained completions are
        kept)."""
        self.enabled = False
        self._open.clear()
        return self

    def configure(self, ring: Optional[int] = None,
                  slowest_k: Optional[int] = None,
                  max_spans: Optional[int] = None) -> "RequestTracer":
        """Resize the retention structures IN PLACE: existing completions
        are kept (the slowest heap is trimmed to the new K; the ring to
        its new length) — call :meth:`reset` for a clean slate.  The
        bench sizes the ring to its wave so tail attribution sees every
        request."""
        if ring is not None:
            self._ring = deque(self._ring, maxlen=max(1, int(ring)))
        if slowest_k is not None:
            self._slowest_k = max(1, int(slowest_k))
            self._slowest = heapq.nsmallest(
                self._slowest_k, self._slowest,
                key=lambda it: (-it[0], it[1]))
            heapq.heapify(self._slowest)
        if max_spans is not None:
            self._max_spans = max(1, int(max_spans))
        return self

    def reset(self) -> None:
        """Drop every retained and open timeline (bench warm-pass
        hygiene, mirrors ``registry.reset()``)."""
        self._open.clear()
        self._ring.clear()
        self._slowest = []
        self.completed_total = 0

    # -- hot path: lifecycle edges --------------------------------------
    # Every hook is one attribute-load + branch while disabled; arguments
    # are plain scalars so a disabled call allocates nothing.

    def submit(self, rid: int, t: float, prompt_len: int,
               max_new: int, trace: str = "") -> None:
        """``trace`` is the propagated distributed-trace id (the 32-hex
        trace-id from the router's ``traceparent`` header, empty for
        direct submits): it keys this replica's timeline to the router's
        hop spans so a fleet merge can join them."""
        if not self.enabled:
            return
        self._open[rid] = {"id": rid, "prompt_len": prompt_len,
                           "max_new": max_new, "t_submit": t, "slot": -1,
                           "trace": trace,
                           "preemptions": 0, "spans_dropped": 0,
                           "edges": [(t, "queue")], "spans": []}

    def admit(self, rid: int, slot: int, t: float) -> None:
        if not self.enabled:
            return
        rec = self._open.get(rid)
        if rec is None:        # submitted while tracing was off
            return
        rec["slot"] = slot
        rec["edges"].append((t, "prefill"))

    def decode_start(self, rid: int, t: float) -> None:
        """Prefix fully cache-resident; first-token dispatched (or
        re-reached after a preempt-resume re-prefill)."""
        if not self.enabled:
            return
        rec = self._open.get(rid)
        if rec is None:
            return
        if "t_first_token" not in rec:
            rec["t_first_token"] = t
        rec["edges"].append((t, "decode"))

    def preempt(self, rid: int, t: float) -> None:
        """Pages reclaimed under pool pressure; requeued at the head."""
        if not self.enabled:
            return
        rec = self._open.get(rid)
        if rec is None:
            return
        rec["preemptions"] += 1
        rec["edges"].append((t, "preempted_wait"))

    def span(self, rid: int, kind: str, t0: float, t1: float,
             tokens: int) -> None:
        """One measured host dispatch window (``prefill_chunk`` /
        ``decode_block`` / ``drain_fetch``) with its token count."""
        if not self.enabled:
            return
        rec = self._open.get(rid)
        if rec is None:
            return
        spans = rec["spans"]
        if len(spans) >= self._max_spans:
            rec["spans_dropped"] += 1
            return
        spans.append((kind, t0, t1, tokens))

    def finish(self, rid: int, t: float, reason: str, n_out: int) -> None:
        """Terminal edge: close the timeline, compute the phase partition,
        record the phase histograms, retain the completed record."""
        if not self.enabled:
            return
        rec = self._open.pop(rid, None)
        if rec is None:
            return
        edges = rec["edges"]
        edges.append((t, "finish"))
        phases = dict.fromkeys(PHASES, 0.0)
        for (t0, phase), (t1, _) in zip(edges, edges[1:]):
            if phase in phases:
                phases[phase] += t1 - t0
        rec["phases"] = phases
        rec["t_finish"] = t
        rec["latency_s"] = t - rec["t_submit"]
        rec["reason"] = reason
        rec["tokens_out"] = n_out
        for p, v in phases.items():
            self._h_phase[p].record(v)
        self.completed_total += 1
        self._seq += 1
        self._ring.append(rec)
        item = (rec["latency_s"], self._seq, rec)
        if len(self._slowest) < self._slowest_k:
            heapq.heappush(self._slowest, item)
        elif item[0] > self._slowest[0][0]:
            heapq.heapreplace(self._slowest, item)

    # -- reads ----------------------------------------------------------
    @property
    def open_count(self) -> int:
        return len(self._open)

    def open_ids(self) -> List[int]:
        return sorted(self._open)

    def completed(self) -> List[Dict[str, Any]]:
        """Every retained completed timeline (ring ∪ slowest heap,
        deduplicated), oldest completion first.  Copies both containers
        C-level-atomically first: scrapes run on the HTTP server thread
        while the engine thread appends (a Python-level loop over the
        live deque would race 'mutated during iteration')."""
        seen: Dict[int, Dict[str, Any]] = {}
        for rec in list(self._ring):
            seen[id(rec)] = rec
        for _, _, rec in list(self._slowest):
            seen.setdefault(id(rec), rec)
        return sorted(seen.values(), key=lambda r: (r["t_finish"], r["id"]))

    def slowest(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        out = sorted(self._slowest, key=lambda it: (-it[0], it[1]))
        if n is not None:
            out = out[:n]
        return [rec for _, _, rec in out]

    def tail_attribution(self, p: float = 0.99) -> Dict[str, Any]:
        """Dominant phase among retained requests ABOVE the p-quantile
        latency cut: the "why is my p99 slow" answer.  ``phase_share`` is
        each phase's share of total tail latency; ``exemplars`` the
        slowest tail request ids (drill into them via ``/requestz``)."""
        recs = self.completed()
        if not recs:
            return {"p": p, "n": 0, "tail_n": 0, "cut_s": 0.0,
                    "dominant_phase": None, "phase_share": {},
                    "exemplars": []}
        lats = sorted(r["latency_s"] for r in recs)
        idx = min(len(lats) - 1, int(p * len(lats)))
        cut = lats[idx]
        tail = [r for r in recs if r["latency_s"] >= cut]
        totals = dict.fromkeys(PHASES, 0.0)
        for r in tail:
            for ph, v in r["phases"].items():
                totals[ph] += v
        denom = sum(totals.values()) or 1.0
        dominant = max(totals, key=lambda ph: totals[ph])
        tail_sorted = sorted(tail, key=lambda r: -r["latency_s"])
        return {"p": p, "n": len(recs), "tail_n": len(tail),
                "cut_s": cut,
                "dominant_phase": dominant,
                "phase_share": {ph: v / denom for ph, v in totals.items()},
                "exemplars": [r["id"] for r in tail_sorted[:8]]}

    # -- exports --------------------------------------------------------
    @staticmethod
    def _rec_json(rec: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(rec)
        out["edges"] = [[t, ph] for t, ph in rec["edges"]]
        out["spans"] = [[k, t0, t1, n] for k, t0, t1, n in rec["spans"]]
        return out

    def snapshot(self, limit: int = 32) -> Dict[str, Any]:
        """The ``/requestz`` JSON body."""
        limit = max(0, int(limit))
        recent = list(self._ring)[-limit:] if limit else []
        return {"enabled": self.enabled,
                "open": self.open_count,
                "open_ids": self.open_ids(),
                "completed_total": self.completed_total,
                "retained": len(self._ring),
                "clock": get_trace_clock_anchor(),
                "tail_attribution": self.tail_attribution(),
                "slowest": [self._rec_json(r)
                            for r in self.slowest(int(limit))],
                "recent": [self._rec_json(r) for r in recent]}

    def perfetto_trace(self,
                       anchor: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
        """Trace-event JSON of every retained timeline, timestamped in
        the clock domain of the most recent profiler capture (see module
        docstring): load next to a ``/profilez`` capture in one Perfetto
        session and a request's host spans line up with the device phase
        tracks.  Per request: one thread of phase slices (the edge
        partition) and one of measured dispatch spans."""
        if anchor is None:
            anchor = get_trace_clock_anchor()
        a = anchor["perf"]

        def us(t):
            return round((t - a) * 1e6, 3)

        events: List[Dict[str, Any]] = [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "ds_requests"}}]
        for rec in self.completed():
            rid = rec["id"]
            trace = rec.get("trace") or ""
            t_ph, t_sp = 2 * rid, 2 * rid + 1
            events.append({"ph": "M", "pid": 1, "tid": t_ph,
                           "name": "thread_name",
                           "args": {"name": f"req {rid} phases"}})
            edges = rec["edges"]
            for (t0, phase), (t1, _) in zip(edges, edges[1:]):
                if t1 <= t0:
                    continue
                args = {"request_id": rid, "reason": rec["reason"]}
                if trace:
                    args["trace"] = trace
                events.append({"ph": "X", "pid": 1, "tid": t_ph,
                               "name": phase, "ts": us(t0),
                               "dur": round((t1 - t0) * 1e6, 3),
                               "args": args})
            if rec["spans"]:
                events.append({"ph": "M", "pid": 1, "tid": t_sp,
                               "name": "thread_name",
                               "args": {"name": f"req {rid} spans"}})
            for kind, t0, t1, n in rec["spans"]:
                args = {"request_id": rid, "tokens": n}
                if trace:
                    args["trace"] = trace
                events.append({"ph": "X", "pid": 1, "tid": t_sp,
                               "name": kind, "ts": us(t0),
                               "dur": round(max(0.0, t1 - t0) * 1e6, 3),
                               "args": args})
        return _perfetto_doc(events, anchor)


_TRACER = RequestTracer()


def get_request_tracer() -> RequestTracer:
    """The process-global tracer the serving scheduler and engine record
    into (one per process, like the metrics registry)."""
    return _TRACER


# ---------------------------------------------------------------------------
# training step timeline
# ---------------------------------------------------------------------------


class StepTimeline:
    """Training-side per-boundary timeline — the serve tracer's twin for
    the DeepSpeedEngine (docs/OBSERVABILITY.md "Distributed tracing").

    The engine marks every micro-batch dispatch and every optimizer
    boundary; anomaly skips and elastic resumes land as instant events.
    Each closed step retains its micro spans, the analytic comm plan
    (rendered as byte-weighted OVERLAY slices in the perfetto export —
    attribution, not device truth), and the pipeline ``bubble_share``
    when pipeline parallelism is on.  Exports go through the SAME
    envelope as :meth:`RequestTracer.perfetto_trace`
    (:func:`_perfetto_doc`), so ``tools/trace_report.py --timeline`` and
    ``tools/fleet_dump.py --trace`` render train and serve with one code
    path.

    Disabled (the default) every hook is one attribute-load + branch —
    the monitor/metrics.py hot-path contract.  Single-writer: all hooks
    run on the training (engine) thread; scrapes copy the ring
    GIL-atomically."""

    # dslint DSL006: the completed-step ring is appended by the engine
    # thread and list()-copied by scrape threads — one atomic op per
    # mutation, published records immutable
    _dslint_shared = {"_ring": "atomic"}

    def __init__(self, ring: int = DEFAULT_RING):
        self.enabled = False
        self._ring: deque = deque(maxlen=max(1, int(ring)))
        self._cur: Optional[Dict[str, Any]] = None
        self._t_open: Optional[float] = None   # previous boundary time
        self.steps_total = 0
        reg = get_registry()
        self._m_steps = reg.counter(
            "ds_trace_train_steps_total",
            "optimizer boundaries recorded by the training step timeline")
        self._m_events = reg.counter(
            "ds_trace_train_events_total",
            "instant events (anomaly skips, elastic resumes) recorded on "
            "the training step timeline")

    # -- switches -------------------------------------------------------
    def enable(self) -> "StepTimeline":
        self.enabled = True
        return self

    def disable(self) -> "StepTimeline":
        """Stop recording and drop the open step (its boundary will never
        arrive while disabled); closed steps are kept."""
        self.enabled = False
        self._cur = None
        self._t_open = None
        return self

    def reset(self) -> None:
        self._cur = None
        self._t_open = None
        self._ring.clear()
        self.steps_total = 0

    # -- hot path (engine thread) --------------------------------------
    def _open_step(self, step: Optional[int], t: float) -> Dict[str, Any]:
        t0 = self._t_open if self._t_open is not None else t
        cur = {"step": step, "t0": t0, "micros": [], "events": []}
        self._cur = cur
        return cur

    def micro(self, step: int, idx: int, t: float) -> None:
        """One micro-batch dispatched (called at micro end); the span
        runs from the previous mark (step open / prior micro) to ``t``."""
        if not self.enabled:
            return
        cur = self._cur
        if cur is None:
            cur = self._open_step(step, t)
        last = cur["micros"][-1][2] if cur["micros"] else cur["t0"]
        cur["micros"].append((idx, last, t))

    def event(self, kind: str, t: float, **args: Any) -> None:
        """Instant event (``anomaly_skip`` / ``elastic_resume``), parked
        on the open step (one opens if needed — elastic resumes can land
        between boundaries)."""
        if not self.enabled:
            return
        cur = self._cur
        if cur is None:
            cur = self._open_step(None, t)
        cur["events"].append((kind, t, args))
        self._m_events.inc()

    def boundary(self, step: int, t: float, comm_plan=None,
                 bubble_share=None) -> None:
        """Optimizer boundary: close the open step as ``[t_open, t]``,
        attach the analytic comm plan and the pipeline bubble share, and
        retain it.  ``t`` becomes the next step's open time."""
        if not self.enabled:
            return
        cur = self._cur if self._cur is not None \
            else self._open_step(step, t)
        self._cur = None
        self._t_open = t
        cur["step"] = step
        cur["t1"] = t
        if bubble_share is not None:
            cur["bubble_share"] = bubble_share
        if comm_plan:
            entries = list(comm_plan.get("micro") or []) \
                + list(comm_plan.get("boundary") or [])
            cur["comm_plan"] = [list(e[:5]) for e in entries]
        self._ring.append(cur)
        self.steps_total += 1
        self._m_steps.inc()

    # -- exports --------------------------------------------------------
    def steps(self) -> List[Dict[str, Any]]:
        return list(self._ring)

    def snapshot(self, limit: int = 32) -> Dict[str, Any]:
        limit = max(0, int(limit))
        recent = list(self._ring)[-limit:] if limit else []
        return {"enabled": self.enabled,
                "steps_total": self.steps_total,
                "retained": len(self._ring),
                "clock": get_trace_clock_anchor(),
                "steps": [
                    {**{k: v for k, v in r.items()
                        if k not in ("micros", "events")},
                     "micros": [[i, a, b] for i, a, b in r["micros"]],
                     "events": [[k, t, a] for k, t, a in r["events"]]}
                    for r in recent]}

    def perfetto_trace(self,
                       anchor: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
        """Trace-event JSON of every retained step, in the shared clock
        domain (same envelope + anchor contract as the request tracer):
        tid 1 = step slices, tid 2 = micro spans, tid 3 = the analytic
        comm-plan OVERLAY (each step's window split across the plan's
        entries proportional to their payload bytes — attribution, not a
        device measurement), tid 4 = instant events."""
        if anchor is None:
            anchor = get_trace_clock_anchor()
        a = anchor["perf"]

        def us(t):
            return round((t - a) * 1e6, 3)

        events: List[Dict[str, Any]] = [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "ds_train_steps"}}]
        for tid, name in ((1, "steps"), (2, "micros"),
                          (3, "comm plan (analytic)"), (4, "events")):
            events.append({"ph": "M", "pid": 1, "tid": tid,
                           "name": "thread_name", "args": {"name": name}})
        for rec in self.steps():
            t0, t1 = rec["t0"], rec.get("t1", rec["t0"])
            args: Dict[str, Any] = {"step": rec["step"]}
            if "bubble_share" in rec:
                args["bubble_share"] = rec["bubble_share"]
            if t1 > t0:
                events.append({"ph": "X", "pid": 1, "tid": 1,
                               "name": f"step {rec['step']}", "ts": us(t0),
                               "dur": round((t1 - t0) * 1e6, 3),
                               "args": args})
            for idx, m0, m1 in rec["micros"]:
                if m1 <= m0:
                    continue
                events.append({"ph": "X", "pid": 1, "tid": 2,
                               "name": f"micro {idx}", "ts": us(m0),
                               "dur": round((m1 - m0) * 1e6, 3),
                               "args": {"step": rec["step"]}})
            plan = rec.get("comm_plan")
            if plan and t1 > t0:
                total = sum(e[2] for e in plan) or 1
                tc = t0
                for op, calls, nbytes, dtype, world in plan:
                    dur = (t1 - t0) * (nbytes / total)
                    events.append({"ph": "X", "pid": 1, "tid": 3,
                                   "name": op, "ts": us(tc),
                                   "dur": round(dur * 1e6, 3),
                                   "args": {"bytes": nbytes, "calls": calls,
                                            "dtype": str(dtype),
                                            "world": world,
                                            "analytic": True}})
                    tc += dur
            for kind, t, eargs in rec["events"]:
                events.append({"ph": "i", "pid": 1, "tid": 4, "s": "t",
                               "name": kind, "ts": us(t),
                               "args": dict(eargs)})
        return _perfetto_doc(events, anchor)


_TIMELINE = StepTimeline()


def get_step_timeline() -> StepTimeline:
    """The process-global training step timeline the DeepSpeedEngine
    records into (one per process, like the request tracer)."""
    return _TIMELINE
