"""Run-level goodput ledger core: telescoping wall-clock attribution.

The PR 7 request-trace idiom (every microsecond of a request belongs to
exactly one phase, phases sum to latency by construction) lifted to RUN
scope: every second of a run's wall clock is attributed to exactly one
category of the closed set :data:`CATEGORIES`, and the category sum
telescopes to ``now - run_start`` — the ZeRO-Infinity (arXiv:2104.07857)
/ T3 (arXiv:2401.16677) exposed-time framing as an always-on ledger
instead of a one-off analysis.

Attribution model
-----------------
A region STACK plus a cursor.  Time between two transitions belongs to
the innermost open region (the stack top); with no region open it is
``idle``.  ``idle`` is never accumulated directly — it is the RESIDUAL
``wall - sum(measured categories)`` computed at snapshot time, which
makes the telescoping identity exact by construction instead of "exact
up to N float additions" (the residual absorbs fp drift; it can read a
few ulps negative on a run with zero true idle, documented).  ``shift``
moves already-attributed seconds between categories (exposed comm out
of compute, a skipped step's compute into ``anomaly_skip``) and
preserves the sum.

Persistence / stitching
-----------------------
One process appends rows to ``runledger.jsonl`` (``append_row``): a
``start`` row at enable, ``tick`` rows carrying cumulative totals, and
``event``/``slo_burn``/``supervisor`` rows.  Rows survive process death
by being flushed per append.  :func:`stitch` folds any number of
incarnations (same ``run_id``, increasing ``DS_SUPERVISOR_RESTART``)
into one run timeline: per-incarnation uptime is the last tick's
``uptime_s``, the gap between an incarnation's last-known-alive unix
time and the next incarnation's start is ``restart_downtime``, and the
stitched wall is ``sum(uptimes) + sum(gaps)`` — so the stitched ledger
telescopes by construction too.

Pure stdlib ON PURPOSE: ``tools/goodput_report.py`` loads this file by
path (the ``elasticity/supervisor.py`` idiom) inside DSL003's jax-free
import closure.  Do not import jax, numpy, or any ``deepspeed_tpu``
module here.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

SCHEMA_VERSION = 1

# the closed category set (docs/OBSERVABILITY.md "Goodput ledger");
# order is the render order: productive first, overheads, then residual
CATEGORIES = (
    "compute",           # device dispatch windows that advanced training/serving
    "exposed_comm",      # analytic/device-measured comm NOT hidden under compute
    "host_stall",        # dataloader waits + offload host relay
    "checkpoint_save",
    "checkpoint_load",
    "recompile",         # step-program (re)builds
    "anomaly_skip",      # compute spent on steps the anomaly select dropped
    "rollback",          # anomaly rollback windows (minus the nested load)
    "restart_downtime",  # process-death -> next incarnation healthy (stitch)
    "drain",             # serving drain windows (minus nested compute)
    "handoff",           # disaggregated-serving KV page capture/adopt IO
    "idle",              # the residual: wall - everything above
)

# categories that count toward the goodput ratio (produced tokens)
GOOD_CATEGORIES = ("compute",)

# the telescoping contract: |sum(categories) - wall| <= REL_TOL * wall
REL_TOL = 1e-9

_MEASURED = tuple(c for c in CATEGORIES if c != "idle")


def _utcnow_iso(t_unix: float) -> str:
    import datetime

    return datetime.datetime.fromtimestamp(
        t_unix, datetime.timezone.utc).isoformat(timespec="seconds")


class LedgerCore:
    """The in-process attribution state machine (one incarnation).

    All times are in ONE caller-chosen monotonic clock domain
    (``time.perf_counter`` in the engines); unix time only appears in
    the jsonl rows, never in attribution arithmetic.
    """

    def __init__(self, start: float):
        self.start = float(start)
        self._cursor = float(start)
        # measured categories only; idle is the snapshot residual
        self.totals: Dict[str, float] = {c: 0.0 for c in _MEASURED}
        self._stack: List[List[Any]] = []   # frames: [category, direct_s]
        self.tokens = 0
        self.steps = 0

    # -- attribution ----------------------------------------------------
    def _advance(self, t: float) -> None:
        dt = t - self._cursor
        if dt <= 0.0:       # clock retreat / duplicate edge: nothing to do
            return
        if self._stack:
            frame = self._stack[-1]
            self.totals[frame[0]] += dt
            frame[1] += dt
        self._cursor = t    # stack empty: the span is idle (residual)

    def push(self, category: str, t: float) -> None:
        if category not in self.totals:
            raise ValueError(f"unknown ledger category {category!r} "
                             f"(closed set: {CATEGORIES})")
        self._advance(t)
        self._stack.append([category, 0.0])

    def pop(self, t: float) -> Tuple[Optional[str], float]:
        """Close the innermost region; returns ``(category, direct_s)``
        where ``direct_s`` excludes time attributed to nested regions.
        Popping with no region open is a no-op (crash tolerance)."""
        self._advance(t)
        if not self._stack:
            return None, 0.0
        cat, direct = self._stack.pop()
        return cat, direct

    def shift(self, src: str, dst: str, seconds: float) -> float:
        """Reattribute up to ``seconds`` from ``src`` to ``dst`` (clamped
        at what ``src`` holds); sum-preserving.  Returns the moved amount."""
        if src not in self.totals or dst not in self.totals:
            raise ValueError(f"unknown ledger category in shift "
                             f"({src!r} -> {dst!r})")
        moved = min(float(seconds), self.totals[src])
        if moved <= 0.0:
            return 0.0
        self.totals[src] -= moved
        self.totals[dst] += moved
        return moved

    # -- reading --------------------------------------------------------
    def snapshot(self, now: float) -> Dict[str, Any]:
        """Point-in-time totals including the open region's accrual and
        the idle residual; does not mutate attribution state."""
        cats = dict(self.totals)
        dt = now - self._cursor
        if dt > 0.0 and self._stack:
            cats[self._stack[-1][0]] += dt
        wall = max(0.0, now - self.start)
        measured = sum(cats.values())
        cats["idle"] = wall - measured
        good = sum(cats[c] for c in GOOD_CATEGORIES)
        return {"wall_s": wall,
                "categories": {c: cats[c] for c in CATEGORIES},
                "goodput_ratio": (good / wall) if wall > 0.0 else 0.0,
                "tokens": self.tokens,
                "steps": self.steps,
                "open_regions": [f[0] for f in self._stack]}


# ---------------------------------------------------------------------------
# analytic comm time (the bench-honesty satellite): a comm-plan entry list
# -> seconds at an assumed flat link bandwidth.  Entries are the
# OverlapSchedule tuples ``(op, calls, nbytes, dtype, world[, dense])``
# with nbytes the TOTAL payload of the entry's calls (CommMetrics.commit
# semantics).
# ---------------------------------------------------------------------------
def analytic_comm_seconds(entries: Iterable[Sequence[Any]],
                          gbps: float) -> float:
    if gbps <= 0.0:
        return 0.0
    total_bytes = 0
    for e in entries or ():
        try:
            total_bytes += int(e[2])
        except (IndexError, TypeError, ValueError):
            continue
    return total_bytes / (gbps * 1e9)


# ---------------------------------------------------------------------------
# jsonl persistence (append-only; one row per line; flushed per append)
# ---------------------------------------------------------------------------
def append_row(path: str, row: Dict[str, Any]) -> None:
    """Append one ledger row; crash-durable (flush + per-line).  Write
    failures are swallowed — a full disk must not take the run down."""
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a") as fh:
            fh.write(json.dumps(row, sort_keys=True, default=str) + "\n")
            fh.flush()
    except OSError:
        pass


def read_rows(path: str) -> List[Dict[str, Any]]:
    """All parseable rows; a torn final line (crash mid-append) is
    skipped, not fatal."""
    rows: List[Dict[str, Any]] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict):
                    rows.append(row)
    except OSError:
        pass
    return rows


def start_row(run_id: str, incarnation: int, role: str,
              t_unix: float) -> Dict[str, Any]:
    return {"v": SCHEMA_VERSION, "kind": "start", "run_id": run_id,
            "incarnation": int(incarnation), "role": role,
            "pid": os.getpid(), "t_unix": float(t_unix)}


def tick_row(run_id: str, incarnation: int, t_unix: float,
             uptime_s: float, snapshot: Dict[str, Any]) -> Dict[str, Any]:
    return {"v": SCHEMA_VERSION, "kind": "tick", "run_id": run_id,
            "incarnation": int(incarnation), "t_unix": float(t_unix),
            "uptime_s": float(uptime_s),
            "categories": dict(snapshot["categories"]),
            "goodput_ratio": snapshot["goodput_ratio"],
            "tokens": snapshot["tokens"], "steps": snapshot["steps"]}


def event_row(run_id: str, incarnation: int, event: str, event_id: str,
              t_unix: float, dur_s: Optional[float] = None,
              **extra: Any) -> Dict[str, Any]:
    row = {"v": SCHEMA_VERSION, "kind": "event", "run_id": run_id,
           "incarnation": int(incarnation), "event": event,
           "event_id": event_id, "t_unix": float(t_unix)}
    if dur_s is not None:
        row["dur_s"] = float(dur_s)
    row.update(extra)
    return row


def slo_burn_row(run_id: str, incarnation: int, rule: str, observed: float,
                 target: float, t_unix: float) -> Dict[str, Any]:
    return {"v": SCHEMA_VERSION, "kind": "slo_burn", "run_id": run_id,
            "incarnation": int(incarnation), "rule": rule,
            "observed": float(observed), "target": float(target),
            "t_unix": float(t_unix)}


def supervisor_row(run_id: str, event: str, t_unix: float,
                   **extra: Any) -> Dict[str, Any]:
    row = {"v": SCHEMA_VERSION, "kind": "supervisor", "run_id": run_id,
           "event": event, "t_unix": float(t_unix)}
    row.update(extra)
    return row


# ---------------------------------------------------------------------------
# cross-incarnation stitching
# ---------------------------------------------------------------------------
def stitch(rows: Iterable[Dict[str, Any]],
           run_id: Optional[str] = None) -> Dict[str, Any]:
    """Fold ledger rows into ONE run report.

    Incarnation boundaries come from ``start`` rows (in file order; the
    jsonl is append-only so file order IS time order).  Per-incarnation
    truth is its LAST tick; the window between an incarnation's
    last-known-alive unix time (``start.t_unix + uptime_s``) and the
    next incarnation's start is ``restart_downtime`` (clamped >= 0 —
    clock skew must not create negative downtime).  Stitched wall =
    ``sum(uptimes) + sum(gaps)``, so the stitched category sum
    telescopes by construction.
    """
    incs: List[Dict[str, Any]] = []
    burns: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    supervisor: List[Dict[str, Any]] = []
    rid = run_id
    for row in rows:
        kind = row.get("kind")
        if run_id is not None and row.get("run_id") not in (None, run_id):
            continue
        if rid is None and row.get("run_id"):
            rid = row["run_id"]
        if kind == "start":
            incs.append({"incarnation": row.get("incarnation", len(incs)),
                         "role": row.get("role", "?"),
                         "start_unix": float(row.get("t_unix", 0.0)),
                         "uptime_s": 0.0,
                         "categories": {c: 0.0 for c in CATEGORIES},
                         "goodput_ratio": 0.0, "tokens": 0, "steps": 0,
                         "ticks": 0})
        elif kind == "tick" and incs:
            cur = incs[-1]
            cur["uptime_s"] = float(row.get("uptime_s", cur["uptime_s"]))
            cats = row.get("categories") or {}
            cur["categories"] = {c: float(cats.get(c, 0.0))
                                 for c in CATEGORIES}
            cur["goodput_ratio"] = row.get("goodput_ratio", 0.0)
            cur["tokens"] = row.get("tokens", cur["tokens"])
            cur["steps"] = row.get("steps", cur["steps"])
            cur["ticks"] += 1
        elif kind == "slo_burn":
            burns.append(row)
        elif kind == "event":
            events.append(row)
        elif kind == "supervisor":
            supervisor.append(row)

    gaps: List[float] = []
    for prev, cur in zip(incs, incs[1:]):
        dead_at = prev["start_unix"] + prev["uptime_s"]
        gaps.append(max(0.0, cur["start_unix"] - dead_at))
    totals = {c: 0.0 for c in CATEGORIES}
    for inc in incs:
        for c in CATEGORIES:
            totals[c] += inc["categories"][c]
    totals["restart_downtime"] += sum(gaps)
    wall = sum(inc["uptime_s"] for inc in incs) + sum(gaps)
    good = sum(totals[c] for c in GOOD_CATEGORIES)
    burn_counts: Dict[str, int] = {}
    for b in burns:
        burn_counts[b.get("rule", "?")] = burn_counts.get(
            b.get("rule", "?"), 0) + 1
    return {"schema_version": SCHEMA_VERSION,
            "run_id": rid or "?",
            "incarnations": incs,
            "restart_gaps_s": gaps,
            "wall_s": wall,
            "categories": totals,
            "goodput_ratio": (good / wall) if wall > 0.0 else 0.0,
            "tokens": sum(inc["tokens"] for inc in incs),
            "steps": max([inc["steps"] for inc in incs] or [0]),
            "slo_burns": burn_counts,
            "events": events,
            "supervisor": supervisor}


def telescopes(report_or_snapshot: Dict[str, Any],
               rel_tol: float = REL_TOL) -> bool:
    """The acceptance predicate: category sum == wall at ``rel_tol``."""
    wall = float(report_or_snapshot["wall_s"])
    total = sum(report_or_snapshot["categories"].values())
    return abs(total - wall) <= max(rel_tol * max(abs(wall), 1.0), 1e-12)


# ---------------------------------------------------------------------------
# rendering (tools/goodput_report.py + /goodputz?format=text)
# ---------------------------------------------------------------------------
def render_lines(report: Dict[str, Any]) -> List[str]:
    wall = report["wall_s"]
    cats = report["categories"]
    lines = [f"run {report['run_id']}: wall {wall:.3f}s over "
             f"{len(report.get('incarnations', []))} incarnation(s), "
             f"goodput {report['goodput_ratio']:.4f}"]
    for c in CATEGORIES:
        v = cats.get(c, 0.0)
        share = (v / wall) if wall > 0 else 0.0
        bar = "#" * int(round(share * 40))
        lines.append(f"  {c:<17} {v:>10.3f}s  {share:>7.2%}  {bar}")
    total = sum(cats.values())
    lines.append(f"  {'sum':<17} {total:>10.3f}s  "
                 f"(telescopes: {telescopes(report)})")
    if report.get("tokens"):
        lines.append(f"  tokens {report['tokens']}  steps "
                     f"{report.get('steps', 0)}  "
                     f"tok/s(wall) {report['tokens'] / wall:.1f}" if wall > 0
                     else f"  tokens {report['tokens']}")
    for inc in report.get("incarnations", []):
        lines.append(f"  incarnation {inc['incarnation']} ({inc['role']}): "
                     f"up {inc['uptime_s']:.3f}s from "
                     f"{_utcnow_iso(inc['start_unix'])}, "
                     f"{inc['ticks']} tick(s)")
    for i, g in enumerate(report.get("restart_gaps_s", [])):
        lines.append(f"  restart gap {i}: {g:.3f}s")
    if report.get("slo_burns"):
        for rule, n in sorted(report["slo_burns"].items()):
            lines.append(f"  slo_burn {rule}: {n} breach(es)")
    return lines


def diff_lines(a: Dict[str, Any], b: Dict[str, Any]) -> List[str]:
    """Category-share diff between two runs (B relative to A)."""
    wa, wb = a["wall_s"], b["wall_s"]
    lines = [f"goodput {a['goodput_ratio']:.4f} -> {b['goodput_ratio']:.4f} "
             f"({b['goodput_ratio'] - a['goodput_ratio']:+.4f}) | wall "
             f"{wa:.3f}s -> {wb:.3f}s"]
    for c in CATEGORIES:
        sa = (a["categories"].get(c, 0.0) / wa) if wa > 0 else 0.0
        sb = (b["categories"].get(c, 0.0) / wb) if wb > 0 else 0.0
        if sa == 0.0 and sb == 0.0:
            continue
        lines.append(f"  {c:<17} {sa:>7.2%} -> {sb:>7.2%}  "
                     f"({sb - sa:+.2%})")
    return lines
