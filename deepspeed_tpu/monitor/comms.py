"""Per-collective communication accounting (the training-side half of
docs/OBSERVABILITY.md).

Successor of the old ``comm/comm.py`` ``CommsLogger`` (the reference's
``deepspeed/comms/logging.py`` role): the same trace-time op/bytes dicts and
``log_summary()`` API, now also feeding the process-global metrics registry
(monitor/metrics.py) so per-collective traffic is scrapable under the ``ds_``
schema next to the serving/inference/training series.

Three feed paths, with honest and distinct semantics:

- :meth:`CommMetrics.record` — **trace-time** accounting for in-jit
  collectives (the ``comm.all_reduce``/``all_gather``/... wrappers and the
  quantized ZeRO++ variants).  Inside jit a collective cannot be
  wall-clocked individually, so this records (op, dtype, bytes) once per
  *trace* of the enclosing program — re-executions of a compiled program do
  not re-count.  Latency for these ops lives in the xplane trace, where the
  ``ds_comm_<op>`` ``jax.named_scope`` ranges emitted by the wrappers name
  the device ops.
- :meth:`CommMetrics.commit` — **per-execution** accounting for paths where
  the host knows what a dispatched program moved (the engine's analytic
  ZeRO comm plan: what GSPMD *must* transfer for the configured stage).
  Advances the same counters per step, and records the measured host
  dispatch-window time into the latency histograms (byte-weighted across
  the ops sharing one window); derived algorithmic/bus bandwidth gauges
  follow.  Device-measured per-op truth still lives in the xplane trace —
  the committed latency attributes the *host window* that contained the
  collective.
- :meth:`CommMetrics.span` — wall-clocked **eager** collectives (the
  control-plane broadcast/barrier tier): full count/bytes/latency/bandwidth
  per call, the only tier where per-op host latency is exact.

Schema (see docs/OBSERVABILITY.md):

- ``ds_comm_<op>_calls_total``                 counter
- ``ds_comm_<op>_bytes_total{dtype=...}``      counter (payload bytes)
- ``ds_comm_<op>_seconds``                     histogram (commit/span feeds)
- ``ds_comm_<op>_algbw_gbps``                  gauge (bytes / seconds)
- ``ds_comm_<op>_busbw_gbps``                  gauge (algbw x collective
                                               factor, NCCL-tests style)

Disabled is free: ``record``/``commit``/``span`` are one attribute-load +
branch while ``enabled`` is False, and the registry instruments themselves
no-op while the registry is disabled — instrument unconditionally, pay only
when observing.  Enable via the ds_config ``comms_logger`` block, the
``deepspeed_tpu.init_telemetry()`` API, or ``comm_metrics.configure()``.
"""

from __future__ import annotations

import re
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Tuple

# relative imports: this stdlib-only subgraph (comms/flight_recorder/
# metrics/utils.logging) is loaded by file path under stub parents on
# jax-less operator boxes (tools/trace_report.py; dslint DSL003)
from .flight_recorder import get_flight_recorder
from .metrics import MetricsRegistry, get_registry
from ..utils.logging import logger

__all__ = ["CommMetrics", "comm_metrics", "busbw_factor", "KNOWN_OPS",
           "QUANTIZED_OPS"]


# Every op slug the framework records today; ensure_registered() registers
# the full family so the docs namespace-guard covers series that only
# materialize on multi-axis meshes.
#
# ``ppermute``/``q_ppermute`` carry BOTH ring call sites — the
# sequence-parallel KV rotation (comm/collectives_q.py seq ring) and the
# pipeline stage-boundary rings (runtime/pipe/spmd.py: forward activation
# hops + reverse-ring cotangent hops).  Feed disjointness per the rules
# above: standalone pipeline callers record trace-time; under the engine
# the model's ledger is off (``pp_comm_record=False``) and the analytic
# pipeline plan entries commit per executed micro-batch instead.
KNOWN_OPS = (
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all", "ppermute",
    "broadcast", "broadcast_object", "barrier",
    "q_all_reduce", "q_all_gather", "q_reduce_scatter", "q_all_to_all",
    "q_ppermute",
    "compressed_allreduce", "compressed_allgather",
    "zpp_q_all_gather", "zpp_all_gather", "zpp_reduce_scatter",
    "zpp_q_all_gather_hpz", "zpp_all_gather_hpz",
)

# Ops with a quantized transport: these additionally feed the
# ``ds_comm_<op>_dense_bytes_total`` dense-twin series so the compression
# ratio is measurable on ONE trace (comm/collectives_q.py).
QUANTIZED_OPS = (
    "q_all_reduce", "q_all_gather", "q_reduce_scatter", "q_all_to_all",
    "q_ppermute", "zpp_q_all_gather", "zpp_q_all_gather_hpz",
)


def _slug(op: str) -> str:
    """Metric-safe op name: 'zpp_q_all_gather(hpz)' -> 'zpp_q_all_gather_hpz'."""
    return re.sub(r"[^a-z0-9_]+", "_", op.lower()).strip("_")


def busbw_factor(op: str, world: int) -> float:
    """NCCL-tests style bus-bandwidth factor: the ratio of bytes a link
    actually carries to the logical payload, for a ring implementation.

    - all_reduce (incl. the 1-bit compressed form): ``2(P-1)/P``
    - all_gather / reduce_scatter / all_to_all (incl. quantized): ``(P-1)/P``
    - point-to-point / broadcast / barrier: ``1``
    """
    if world <= 1:
        return 1.0
    op = _slug(op)
    if "all_reduce" in op or "allreduce" in op:
        return 2.0 * (world - 1) / world
    if ("all_gather" in op or "allgather" in op or "reduce_scatter" in op
            or "all_to_all" in op):
        return (world - 1) / world
    return 1.0


def _dtype_name(x: Any) -> str:
    dt = getattr(x, "dtype", None)
    return getattr(dt, "name", str(dt)) if dt is not None else "unknown"


class CommMetrics:
    """Per-collective accounting: trace-time dicts (back-compat CommsLogger
    surface) + registry series + flight-recorder breadcrumbs."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry if registry is not None else get_registry()
        self.enabled = False
        self.verbose = False
        # back-compat CommsLogger surface (tests and log_summary read these)
        self.counts: Dict[str, int] = defaultdict(int)
        self.bytes: Dict[str, int] = defaultdict(int)
        # lazily-built registry instruments, keyed by op slug (+ dtype)
        self._calls: Dict[str, Any] = {}
        self._bytes_c: Dict[Tuple[str, str], Any] = {}
        self._dense_c: Dict[Tuple[str, str], Any] = {}
        self._hists: Dict[str, Any] = {}
        self._algbw: Dict[str, Any] = {}
        self._busbw: Dict[str, Any] = {}

    # -- switches -------------------------------------------------------
    def configure(self, enabled: bool = False, verbose: bool = False,
                  **_: Any) -> None:
        self.enabled = enabled
        self.verbose = verbose

    @property
    def active(self) -> bool:
        """Comm accounting on AND the registry recording."""
        return self.enabled and self._registry._enabled

    # -- instrument plumbing (cold path; registration takes the registry
    # lock once per (op, dtype)) ---------------------------------------
    def _ins_calls(self, op: str):
        c = self._calls.get(op)
        if c is None:
            c = self._registry.counter(
                f"ds_comm_{op}_calls_total",
                f"{op} collective calls (trace-time records count per "
                f"compilation; commits count per execution)")
            self._calls[op] = c
        return c

    def _ins_bytes(self, op: str, dtype: str):
        key = (op, dtype)
        c = self._bytes_c.get(key)
        if c is None:
            c = self._registry.counter(
                f"ds_comm_{op}_bytes_total",
                f"{op} payload bytes by dtype", labels={"dtype": dtype})
            self._bytes_c[key] = c
        return c

    def _ins_dense(self, op: str, dtype: str):
        key = (op, dtype)
        c = self._dense_c.get(key)
        if c is None:
            c = self._registry.counter(
                f"ds_comm_{op}_dense_bytes_total",
                f"dense-equivalent payload bytes the quantized {op} "
                f"transport REPLACED — the compression denominator on the "
                f"same trace", labels={"dtype": dtype})
            self._dense_c[key] = c
        return c

    def _ins_hist(self, op: str):
        h = self._hists.get(op)
        if h is None:
            h = self._registry.histogram(
                f"ds_comm_{op}_seconds",
                f"host-measured {op} latency (eager spans: exact per call; "
                f"engine commits: byte-weighted share of the dispatch "
                f"window — device truth is in the xplane trace)")
            self._hists[op] = h
        return h

    def _ins_bw(self, op: str):
        a = self._algbw.get(op)
        if a is None:
            a = self._registry.gauge(f"ds_comm_{op}_algbw_gbps",
                                     f"last observed {op} algorithmic "
                                     f"bandwidth (payload GB/s)")
            b = self._registry.gauge(f"ds_comm_{op}_busbw_gbps",
                                     f"last observed {op} bus bandwidth "
                                     f"(algbw x collective factor)")
            self._algbw[op], self._busbw[op] = a, b
        return self._algbw[op], self._busbw[op]

    def ensure_registered(self, dtypes: Iterable[str] = ("float32",)) -> None:
        """Register the full known-op instrument family (namespace-guard and
        exporter warm-up; recording still no-ops while disabled)."""
        for op in KNOWN_OPS:
            self._ins_calls(op)
            self._ins_hist(op)
            self._ins_bw(op)
            for dt in dtypes:
                self._ins_bytes(op, dt)
        for op in QUANTIZED_OPS:
            for dt in dtypes:
                self._ins_dense(op, dt)

    # -- feed paths -----------------------------------------------------
    def record(self, op: str, axis: Any, x: Any) -> None:
        """Trace-time record for an in-jit collective (see module doc)."""
        if not self.enabled:
            return
        try:
            nbytes = int(x.size) * x.dtype.itemsize
        except Exception:
            nbytes = 0
        key = f"{op}@{axis}"
        self.counts[key] += 1
        self.bytes[key] += nbytes
        if self._registry._enabled:
            slug = _slug(op)
            self._ins_calls(slug).inc()
            self._ins_bytes(slug, _dtype_name(x)).inc(nbytes)
        if self.verbose:
            logger.info("comm trace: %s shape=%s bytes=%d", key,
                        getattr(x, "shape", None), nbytes)

    def record_q(self, op: str, axis: Any, parts: Iterable[Any],
                 dense_like: Any) -> None:
        """Trace-time record for a QUANTIZED in-jit collective: one call,
        payload bytes summed over ``parts`` (the int8 codes + fp32 scales
        that actually cross the wire, by dtype), plus the
        ``ds_comm_<op>_dense_bytes_total`` dense-twin series sized from
        ``dense_like`` (the tensor the dense collective would have moved) —
        so the compression ratio reads off ONE trace."""
        if not self.enabled:
            return
        parts = [p for p in parts if p is not None]

        def nb(a) -> int:
            # works for traced arrays AND bare ShapeDtypeStructs (the
            # dense twin of an hpZ gather is never materialized — only
            # its shape/dtype exist); stdlib-only on purpose (DSL003)
            try:
                size = a.size
            except Exception:
                size = 1
                for d in getattr(a, "shape", ()):
                    size *= int(d)
            try:
                return int(size) * int(a.dtype.itemsize)
            except Exception:
                return 0

        nbytes = sum(nb(p) for p in parts)
        dense = nb(dense_like)
        key = f"{op}@{axis}"
        self.counts[key] += 1
        self.bytes[key] += nbytes
        if self._registry._enabled:
            slug = _slug(op)
            self._ins_calls(slug).inc()
            for p in parts:
                self._ins_bytes(slug, _dtype_name(p)).inc(nb(p))
            self._ins_dense(slug, _dtype_name(dense_like)).inc(dense)
        if self.verbose:
            logger.info("comm trace: %s bytes=%d dense=%d", key, nbytes,
                        dense)

    def commit(self, entries, seconds: float) -> None:
        """Per-execution commit: ``entries`` is a list of
        ``(op, calls, nbytes, dtype, world)`` tuples — optionally extended
        with a sixth element for quantized ops feeding the dense-twin
        series: either ``dense_nbytes`` (labeled with the entry's dtype)
        or ``(dense_nbytes, dense_dtype)`` (so the twin carries the DENSE
        payload's dtype, matching :meth:`record_q`'s labeling) —
        describing what one dispatched program moved; ``seconds`` is the
        measured host window that contained them (latency attribution is
        byte-weighted)."""
        if not self.active or not entries:
            return
        total = sum(e[2] for e in entries)
        rec = get_flight_recorder()
        for entry in entries:
            op, calls, nbytes, dtype, world = entry[:5]
            dense_nbytes = entry[5] if len(entry) > 5 else None
            dense_dtype = dtype
            if isinstance(dense_nbytes, (tuple, list)):
                dense_nbytes, dense_dtype = dense_nbytes
            slug = _slug(op)
            self._ins_calls(slug).inc(calls)
            self._ins_bytes(slug, dtype).inc(nbytes)
            if dense_nbytes is not None:
                self._ins_dense(slug, dense_dtype).inc(dense_nbytes)
            # byte-weighted window attribution; a zero-byte commit (barrier
            # spans) must still keep its measured wall time — a 5s straggler
            # barrier showing p99=0 would hide exactly the hang signal
            share = (seconds * (nbytes / total) if total > 0
                     else seconds / len(entries))
            self._ins_hist(slug).record(share)
            if share > 0 and nbytes > 0:
                alg = nbytes / share / 1e9
                algg, busg = self._ins_bw(slug)
                algg.set(alg)
                busg.set(alg * busbw_factor(slug, world))
            rec.record("collective", op=slug, calls=calls, bytes=nbytes,
                       dtype=dtype, world=world, seconds=round(share, 6))

    @contextmanager
    def span(self, op: str, nbytes: int, dtype: str = "unknown",
             world: int = 1):
        """Wall-clock an eager collective; caller wraps the op body."""
        if not self.active:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.commit([(op, 1, nbytes, dtype, world)],
                        time.perf_counter() - t0)

    # -- back-compat CommsLogger surface --------------------------------
    def log_summary(self) -> str:
        lines = ["Comms summary (trace-time counts; use jax.profiler for "
                 "latency):"]
        for key in sorted(self.counts):
            lines.append(f"  {key}: count={self.counts[key]} "
                         f"bytes={self.bytes[key]:,}")
        text = "\n".join(lines)
        logger.info("%s", text)
        return text

    def reset(self) -> None:
        """Clear the trace-time dicts (registry series reset via
        ``get_registry().reset()`` like every other ``ds_`` metric)."""
        self.counts.clear()
        self.bytes.clear()


comm_metrics = CommMetrics()
