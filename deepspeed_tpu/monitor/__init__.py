"""Monitoring: event-stream backends (monitor.py — the reference's
``deepspeed/monitor`` role: CSV/TensorBoard/W&B fan-out) plus the
request-lifecycle metrics registry (metrics.py) and its Prometheus/JSON
HTTP exporter (server.py).  See docs/OBSERVABILITY.md."""

from deepspeed_tpu.monitor.comms import CommMetrics, busbw_factor, comm_metrics  # noqa: F401
from deepspeed_tpu.monitor.flight_recorder import (FlightRecorder,  # noqa: F401
                                                   get_flight_recorder)
from deepspeed_tpu.monitor.memory import MemoryTelemetry  # noqa: F401
from deepspeed_tpu.monitor.metrics import (Counter, Gauge, Histogram,  # noqa: F401
                                           MetricsRegistry, get_registry)
from deepspeed_tpu.monitor.monitor import MonitorMaster  # noqa: F401
from deepspeed_tpu.monitor.server import MetricsServer  # noqa: F401

__all__ = ["CommMetrics", "Counter", "FlightRecorder", "Gauge", "Histogram",
           "MemoryTelemetry", "MetricsRegistry", "MetricsServer",
           "MonitorMaster", "busbw_factor", "comm_metrics",
           "get_flight_recorder", "get_registry"]
