"""Flight recorder: a fixed-size ring of structured runtime events, dumped
as JSON (plus stack snapshots of every thread) when something goes wrong.

Long training runs die in ways the metrics registry cannot explain after
the fact: an unhandled exception mid-step loses the collective that
preceded it; a hang leaves nothing at all.  The flight recorder keeps the
last N events — step begin/end, collective commits, checkpoint saves,
program builds — in a preallocated ring (O(1) per event, no growth), and
serializes them in order:

- on an unhandled exception inside the engine's ``forward``/``step``/
  ``train_step`` (the engine dumps before re-raising);
- on ``SIGUSR2``, when :meth:`FlightRecorder.install_signal_handler` was
  explicitly requested (``kill -USR2 <pid>`` on a hung run) — the handler
  is never installed implicitly;
- on demand via :meth:`FlightRecorder.dump`.

The dump is a single JSON object: ``{"reason", "time_unix", "pid",
"events": [...oldest->newest...], "threads": {thread_name: [frames...]}}``.
Events carry a monotonically increasing ``seq`` so ordering survives the
ring wraparound.  Disabled (the default) every ``record()`` is one
attribute-load + branch.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from ..utils.logging import logger

__all__ = ["FlightRecorder", "get_flight_recorder"]

DEFAULT_CAPACITY = 512
_UNSET = object()   # enable(): "dump_dir not mentioned" vs "reset to cwd"


class FlightRecorder:
    # dslint DSL006: dump()/events() may run on a crashing or signal
    # thread while the engine thread records — ring writes must stay
    # single-slot swaps (self._buf[i] = ev); records are immutable once
    # published
    _dslint_shared = {"_buf": "atomic"}

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self.enabled = False
        self.dump_dir: Optional[str] = None
        self._buf: List[Optional[Dict[str, Any]]] = [None] * self.capacity
        self._n = 0                      # total events ever recorded
        self._installed_signal = None    # signum once installed
        self._prev_handler = None
        self._dump_count = 0

    # -- switches -------------------------------------------------------
    def enable(self, capacity: Optional[int] = None,
               dump_dir=_UNSET) -> "FlightRecorder":
        """Arm the ring.  ``dump_dir`` accepts an explicit ``None`` to
        reset to the default (cwd) — omitting it keeps the current
        setting, so a config-driven enable can't silently inherit a stale
        directory from an earlier caller."""
        if capacity is not None and int(capacity) != self.capacity:
            self.capacity = max(1, int(capacity))
            self._buf = [None] * self.capacity
            self._n = 0
        if dump_dir is not _UNSET:
            self.dump_dir = dump_dir
        self.enabled = True
        return self

    def disable(self) -> "FlightRecorder":
        self.enabled = False
        return self

    # -- hot path -------------------------------------------------------
    def record(self, kind: str, **fields: Any) -> None:
        """Append one event; one branch + no work while disabled."""
        if not self.enabled:
            return
        ev = {"seq": self._n, "t": time.time(), "kind": kind}
        if fields:
            ev.update(fields)
        self._buf[self._n % self.capacity] = ev
        self._n += 1

    # -- reads ----------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        """Ring contents oldest -> newest."""
        if self._n <= self.capacity:
            return [e for e in self._buf[: self._n] if e is not None]
        i = self._n % self.capacity
        return [e for e in self._buf[i:] + self._buf[:i] if e is not None]

    @staticmethod
    def _thread_stacks() -> Dict[str, List[str]]:
        names = {t.ident: t.name for t in threading.enumerate()}
        out = {}
        for ident, frame in sys._current_frames().items():
            name = names.get(ident, f"thread-{ident}")
            out[name] = [ln.rstrip("\n")
                         for ln in traceback.format_stack(frame)]
        return out

    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> str:
        """Serialize the ring + all thread stacks to ``path`` (default:
        ``<dump_dir or cwd>/ds_flight_<pid>_<n>.json``); returns the path."""
        if path is None:
            self._dump_count += 1
            path = os.path.join(
                self.dump_dir or ".",
                f"ds_flight_{os.getpid()}_{self._dump_count}.json")
        payload = {"reason": reason, "time_unix": time.time(),
                   "pid": os.getpid(), "total_events": self._n,
                   "capacity": self.capacity, "events": self.events(),
                   "threads": self._thread_stacks()}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(payload, fh, indent=1, default=str)
        logger.warning("flight recorder: dumped %d events -> %s (%s)",
                       len(payload["events"]), path, reason)
        return path

    # -- signal trigger (install ONLY on request) -----------------------
    def install_signal_handler(self, signum: Optional[int] = None) -> bool:
        """Install a dump-on-signal handler (default SIGUSR2).  Returns
        False on platforms without the signal.  Never called implicitly —
        a library must not take over process signals unasked."""
        import signal as _signal

        if signum is None:
            signum = getattr(_signal, "SIGUSR2", None)
        if signum is None:
            return False
        if self._installed_signal == signum:
            return True

        def _handler(_sig, _frame):
            self.record("signal", signum=signum)
            try:
                self.dump(reason=f"signal {signum}")
            except Exception as exc:  # a broken disk must not kill the run
                logger.error("flight recorder: dump-on-signal failed: %s",
                             exc)

        try:
            self._prev_handler = _signal.signal(signum, _handler)
        except (ValueError, OSError):   # non-main thread / unsupported
            return False
        self._installed_signal = signum
        return True

    def uninstall_signal_handler(self) -> None:
        if self._installed_signal is None:
            return
        import signal as _signal

        try:
            _signal.signal(self._installed_signal,
                           self._prev_handler or _signal.SIG_DFL)
        except (ValueError, OSError):
            pass
        self._installed_signal = None
        self._prev_handler = None

    @property
    def signal_installed(self) -> bool:
        return self._installed_signal is not None

    def reset(self) -> None:
        self._buf = [None] * self.capacity
        self._n = 0


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """The process-global recorder every subsystem appends to."""
    return _RECORDER
