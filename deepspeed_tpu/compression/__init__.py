"""Compression (reference: ``deepspeed/compression/``, SURVEY.md §2.1):
layer reduction, weight quantization (QAT + int8 export), pruning — as
param-tree transforms over the functional models."""

from deepspeed_tpu.compression.compress import (  # noqa: F401
    CompressedParams, CompressionScheduler, fake_quantize,
    head_pruning_masks, init_compression, magnitude_mask, quantize_weights,
    redundancy_clean, reduce_layers, row_mask, row_pruning_masks)
