"""Compression: layer reduction, weight quantization (QAT), pruning.

Reference: ``deepspeed/compression/`` (SURVEY.md §2.1 "Compression"):
``init_compression`` applies the ``compression_training`` config to a model
and ``redundancy_clean`` bakes the compression in.  The reference swaps
torch modules for ``LinearLayer_Compress``; the TPU-native equivalents are
*param-tree transforms* (functional models have no modules to swap):

- **layer reduction**: slice the stacked [L, ...] layer weights to the kept
  layer ids — a pure gather on the leading axis.
- **weight quantization**: fake-quant (quantize-dequantize) params for QAT,
  or export real int8 + scales (``quantize_weights``) for serving.
- **sparse/row pruning**: magnitude masks applied to the param tree; masks
  can be re-applied each step via ``apply_masks`` (the reference reapplies
  after each optimizer step).

All transforms are jit-friendly jnp ops; schedule gating (``schedule_offset``)
is honored by the caller passing ``global_step``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------------------
# weight quantization
# ---------------------------------------------------------------------------

def fake_quantize(w, bits: int = 8, symmetric: bool = True, axis: Optional[int] = None):
    """Quantize-dequantize (QAT forward behavior).  Per-tensor, or
    per-channel when ``axis`` is given."""
    w32 = w.astype(jnp.float32)
    qmax = 2.0 ** (bits - 1) - 1
    if symmetric:
        red_axes = tuple(i for i in range(w32.ndim) if i != axis) or None
        absmax = jnp.max(jnp.abs(w32), axis=red_axes, keepdims=True)
        scale = jnp.where(absmax == 0, 1.0, absmax / qmax)
        q = jnp.clip(jnp.round(w32 / scale), -qmax, qmax)
        return (q * scale).astype(w.dtype)
    mn = jnp.min(w32)
    mx = jnp.max(w32)
    scale = jnp.where(mx == mn, 1.0, (mx - mn) / (2.0 ** bits - 1))
    q = jnp.round((w32 - mn) / scale)
    return (q * scale + mn).astype(w.dtype)


def quantize_weights(w, bits: int = 8) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Real int8 export: returns (q int8, scale fp32 per output channel)."""
    assert bits == 8, "int8 export only"
    w32 = w.astype(jnp.float32)
    red = tuple(range(w32.ndim - 1))
    absmax = jnp.max(jnp.abs(w32), axis=red, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


# ---------------------------------------------------------------------------
# pruning
# ---------------------------------------------------------------------------

def magnitude_mask(w, density: float):
    """Keep the top ``density`` fraction by |magnitude| (unstructured)."""
    k = max(1, int(w.size * density))
    thresh = jnp.sort(jnp.abs(w).reshape(-1))[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def row_mask(w, density: float, axis: int = -1):
    """Structured row/head pruning: keep top rows by L2 norm along ``axis``."""
    norms = jnp.linalg.norm(w.astype(jnp.float32), axis=axis)
    k = max(1, int(norms.size * density))
    thresh = jnp.sort(norms.reshape(-1))[-k]
    keep = (norms >= thresh).astype(w.dtype)
    return jnp.expand_dims(keep, axis)


def _topk_keep(norms, density: float):
    """Per-leading-row top-k keep mask: norms [L, N] -> bool [L, N] keeping
    the top ``density`` fraction of each row."""
    L, N = norms.shape
    k = max(1, int(N * density))
    thresh = jnp.sort(norms, axis=-1)[:, -k][:, None]
    return norms >= thresh


def head_pruning_masks(attn: Dict[str, Any], num_heads: int, density: float):
    """Structured attention-head pruning (reference ``head_pruning``, scored
    on the attention output matrix): heads ranked by the L2 norm of their
    ``wo`` input rows; pruned heads get their ``wo`` rows AND ``wq`` output
    columns zeroed — the head's contribution vanishes exactly.

    attn: stacked {"wq" [L, D, H*Dh], "wo" [L, H*Dh, D], ...}.
    Returns {"wq": [L, 1, H*Dh], "wo": [L, H*Dh, 1]} masks.
    """
    wo = attn["wo"]
    L, HDh, D = wo.shape
    Dh = HDh // num_heads
    norms = jnp.linalg.norm(
        wo.astype(jnp.float32).reshape(L, num_heads, Dh * D), axis=-1)
    keep = _topk_keep(norms, density)                             # [L, H]
    col = jnp.repeat(keep, Dh, axis=-1).astype(wo.dtype)          # [L, H*Dh]
    return {"wq": col[:, None, :], "wo": col[:, :, None]}


def row_pruning_masks(mlp: Dict[str, Any], density: float):
    """Structured FFN row pruning + the paired channel pruning (reference
    ``row_pruning`` on fc1 with ``related_modules`` channel pruning on fc2):
    hidden units ranked by their ``w_up`` output-column norm; pruned units
    get the ``w_up`` column, its bias entry, and the matching ``w_down``
    input row zeroed.

    mlp: stacked {"w_up" [L, D, F], "w_down" [L, F, D], ...}.
    Returns masks keyed like ``mlp`` for the touched leaves.
    """
    w_up = mlp["w_up"]
    L, D, F = w_up.shape
    norms = jnp.linalg.norm(w_up.astype(jnp.float32), axis=1)     # [L, F]
    if "w_gate" in mlp:   # gated MLP: a unit spans both up and gate
        norms = norms + jnp.linalg.norm(mlp["w_gate"].astype(jnp.float32),
                                        axis=1)
    keep = _topk_keep(norms, density).astype(w_up.dtype)          # [L, F]
    masks = {"w_up": keep[:, None, :], "w_down": keep[:, :, None]}
    if "w_gate" in mlp:
        masks["w_gate"] = keep[:, None, :]
    if "b_up" in mlp:
        masks["b_up"] = keep
    if "b_gate" in mlp:
        masks["b_gate"] = keep
    return masks


# ---------------------------------------------------------------------------
# layer reduction
# ---------------------------------------------------------------------------

def reduce_layers(params: Dict[str, Any], keep_layers: List[int]) -> Dict[str, Any]:
    """Slice stacked [L, ...] layer params down to ``keep_layers`` (the
    reference's ``layer_reduction`` with ``teacher_layer`` ids)."""
    idx = jnp.asarray(keep_layers)
    out = dict(params)
    out["layers"] = jax.tree.map(lambda a: jnp.take(a, idx, axis=0),
                                 params["layers"])
    return out


# ---------------------------------------------------------------------------
# config-driven entry points (reference API)
# ---------------------------------------------------------------------------

class CompressionConfig:
    def __init__(self, d: Dict[str, Any]):
        d = d.get("compression_training", d) or {}
        wq = d.get("weight_quantization", {}).get("shared_parameters", {})
        self.wq_enabled = wq.get("enabled", False)
        self.wq_bits = 8
        for group in d.get("weight_quantization", {}).get(
                "different_groups", {}).values():
            bits = group.get("params", {}).get("target_bits")
            if isinstance(bits, int):
                self.wq_bits = bits
                break

        def method(name, default_density=0.5):
            sec = d.get(name, {})
            sh = sec.get("shared_parameters", {})
            density = sh.get("dense_ratio", default_density)
            for group in sec.get("different_groups", {}).values():
                dr = group.get("params", {}).get("dense_ratio")
                if dr is not None:
                    density = dr
                    break
            return (sh.get("enabled", False), density,
                    sh.get("schedule_offset", 0))

        self.sp_enabled, self.sp_density, self.sp_offset = method("sparse_pruning")
        self.rp_enabled, self.rp_density, self.rp_offset = method("row_pruning")
        self.hp_enabled, self.hp_density, self.hp_offset = method("head_pruning")
        # channel pruning rides row pruning's paired masks in this layout
        # (reference ties them via related_modules); a standalone section
        # maps onto the same transform
        cp_en, cp_density, cp_off = method("channel_pruning")
        if cp_en and not self.rp_enabled:
            self.rp_enabled, self.rp_density, self.rp_offset = (
                True, cp_density, cp_off)
        lr_ = d.get("layer_reduction", {})
        self.lr_enabled = lr_.get("enabled", False)
        self.keep_layers = lr_.get("teacher_layer", [])

    @property
    def any_pruning(self) -> bool:
        return self.sp_enabled or self.rp_enabled or self.hp_enabled

    @property
    def any_enabled(self) -> bool:
        return (self.any_pruning or self.wq_enabled or self.lr_enabled)


class CompressedParams:
    """Holds masks + config; ``apply(params)`` returns the compressed view
    (called in forward for QAT, or once at export)."""

    def __init__(self, config: Dict[str, Any], num_heads: Optional[int] = None):
        self.cfg = CompressionConfig(config)
        self.num_heads = num_heads
        self.masks: Dict[str, Any] = {}
        self.structured_masks: Dict[str, Dict[str, Any]] = {}

    def init_masks(self, params) -> None:
        if self.cfg.sp_enabled:
            self.init_sparse_masks(params.get("layers", {}))
        self.init_structured_masks(params)

    def init_sparse_masks(self, layers) -> None:
        """Magnitude masks from the CURRENT weights (single construction
        point for the scheduler, export, and init paths)."""
        self.masks = jax.tree.map(
            lambda w: magnitude_mask(w, self.cfg.sp_density)
            if getattr(w, "ndim", 0) >= 2 else jnp.ones_like(w), layers)

    def init_structured_masks(self, params) -> None:
        """Head/row/channel masks on the stacked layer tree (built from the
        CURRENT weights — pruning decisions snapshot at activation, the
        reference scheduler's semantics)."""
        ly = params.get("layers", {})
        if self.cfg.hp_enabled and "attn" in ly:
            if not self.num_heads:
                raise ValueError("head_pruning needs the model's num_heads "
                                 "(pass num_heads= to CompressedParams / use "
                                 "init_compression on a model with a config)")
            self.structured_masks["attn"] = head_pruning_masks(
                ly["attn"], self.num_heads, self.cfg.hp_density)
        if self.cfg.rp_enabled and "mlp" in ly and "w_up" in ly["mlp"]:
            if getattr(ly["mlp"]["w_up"], "ndim", 3) != 3:
                raise ValueError(
                    "row/channel pruning supports dense MLPs only (stacked "
                    "[L, D, F] w_up); this tree's w_up has shape "
                    f"{ly['mlp']['w_up'].shape} (MoE experts — prune via "
                    "expert dropping instead)")
            self.structured_masks["mlp"] = row_pruning_masks(
                ly["mlp"], self.cfg.rp_density)

    def _masked_layers(self, layers, global_step: int):
        return _apply_mask_groups(
            layers,
            self.masks if (self.cfg.sp_enabled and self.masks
                           and global_step >= self.cfg.sp_offset) else None,
            (self.structured_masks.get("attn")
             if global_step >= self.cfg.hp_offset else None),
            (self.structured_masks.get("mlp")
             if global_step >= self.cfg.rp_offset else None))

    def apply(self, params, global_step: int = 10**9):
        out = params
        # masks were built against the FULL layer stack: apply them before
        # any layer reduction slices the leading dim
        layers = self._masked_layers(out["layers"], global_step)
        if layers is not out["layers"]:
            out = {**out, "layers": layers}
        if self.cfg.lr_enabled and self.cfg.keep_layers:
            out = reduce_layers(out, self.cfg.keep_layers)
        if self.cfg.wq_enabled:
            out = {**out, "layers": jax.tree.map(
                lambda w: fake_quantize(w, bits=self.cfg.wq_bits)
                if getattr(w, "ndim", 0) >= 2 else w, out["layers"])}
        return out


def _apply_mask_groups(layers, sp, attn_masks, mlp_masks):
    """The single mask-application: elementwise sparse masks plus the
    structured attn/mlp group masks.  Shared by the export path
    (``CompressedParams._masked_layers``) and the scheduler's per-step jit
    so the two can't drift."""
    out = layers
    if sp is not None:
        out = jax.tree.map(lambda w, m: w * m, out, sp)
    if attn_masks is not None:
        attn = dict(out["attn"])
        for k, m in attn_masks.items():
            attn[k] = attn[k] * m
        out = {**out, "attn": attn}
    if mlp_masks is not None:
        mlp = dict(out["mlp"])
        for k, m in mlp_masks.items():
            mlp[k] = mlp[k] * m
        out = {**out, "mlp": mlp}
    return out


class CompressionScheduler:
    """Step-driven compression activation the ENGINE consults (reference
    ``compression/scheduler.py`` role; VERDICT r4 item 8 — the old
    caller-passes-global_step contract was too easy to misuse).

    After every optimizer step the engine calls :meth:`after_step` with the
    live param tree and its step counter; once a pruning method's
    ``schedule_offset`` is reached, the masks are built from the
    then-current weights and re-applied to the params each step (the
    reference reapplies masks after each optimizer step so the optimizer
    cannot regrow pruned weights)."""

    def __init__(self, comp: CompressedParams):
        self.comp = comp
        self._fns: Dict[Any, Any] = {}

    def _active(self, step: int):
        c = self.comp.cfg
        return {"sp": c.sp_enabled and step >= c.sp_offset,
                "hp": c.hp_enabled and step >= c.hp_offset,
                "rp": c.rp_enabled and step >= c.rp_offset}

    def after_step(self, params, global_step: int):
        """Returns the masked param tree, or None when no method is active
        yet (so the engine skips the update entirely)."""
        act = self._active(global_step)
        if not any(act.values()):
            return None
        ly = params.get("layers") if isinstance(params, dict) else None
        if ly is None:
            if not getattr(self, "_warned_no_layers", False):
                self._warned_no_layers = True
                logger.warning(
                    "compression scheduler: param tree has no 'layers' "
                    "stack — pruning is configured but will NOT run for "
                    "this model")
            return None
        comp = self.comp
        # masks snapshot from the CURRENT weights at first activation
        if act["sp"] and not comp.masks:
            comp.init_sparse_masks(ly)
        if (act["hp"] or act["rp"]) and not comp.structured_masks:
            comp.init_structured_masks(params)
        sp_m = comp.masks if act["sp"] else None
        at_m = comp.structured_masks.get("attn") if act["hp"] else None
        ml_m = comp.structured_masks.get("mlp") if act["rp"] else None
        key = (sp_m is not None, at_m is not None, ml_m is not None)
        fn = self._fns.get(key)
        if fn is None:
            fn = jax.jit(_apply_mask_groups, donate_argnums=(0,))
            self._fns[key] = fn
        masked = fn(ly, sp_m, at_m, ml_m)
        return {**params, "layers": masked}


def init_compression(model, deepspeed_config: Dict[str, Any], mpu=None):
    """Reference entry: attach a CompressedParams transform to the model.
    The engine consults its :class:`CompressionScheduler` after each
    optimizer step (``schedule_offset`` is honored without the caller
    threading global_step)."""
    comp = CompressedParams(
        deepspeed_config,
        num_heads=getattr(getattr(model, "config", None), "num_heads", None))
    if hasattr(model, "config"):
        model._compression = comp
    logger.info("compression initialized: wq=%s sp=%s row/hd pruning=%s/%s "
                "layer_reduction=%s", comp.cfg.wq_enabled, comp.cfg.sp_enabled,
                comp.cfg.rp_enabled, comp.cfg.hp_enabled, comp.cfg.lr_enabled)
    return model, comp


def redundancy_clean(model, deepspeed_config: Dict[str, Any], params=None):
    """Reference entry: bake compression into the weights (export)."""
    comp = getattr(model, "_compression", None)
    if comp is None:
        comp = CompressedParams(
            deepspeed_config,
            num_heads=getattr(getattr(model, "config", None), "num_heads",
                              None))
    if params is None:
        return model
    if "layers" not in params:
        if comp.cfg.any_pruning:
            logger.warning("redundancy_clean: param tree has no 'layers' "
                           "stack — pruning config ignored at export")
        return comp.apply(params) if comp.cfg.wq_enabled else params
    # per-method init: one method's masks existing (e.g. the scheduler built
    # sparse masks mid-training) must not skip another's
    if comp.cfg.sp_enabled and not comp.masks:
        comp.init_sparse_masks(params["layers"])
    if ((comp.cfg.hp_enabled or comp.cfg.rp_enabled)
            and not comp.structured_masks):
        comp.init_structured_masks(params)
    return comp.apply(params)
