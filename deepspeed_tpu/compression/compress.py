"""Compression: layer reduction, weight quantization (QAT), pruning.

Reference: ``deepspeed/compression/`` (SURVEY.md §2.1 "Compression"):
``init_compression`` applies the ``compression_training`` config to a model
and ``redundancy_clean`` bakes the compression in.  The reference swaps
torch modules for ``LinearLayer_Compress``; the TPU-native equivalents are
*param-tree transforms* (functional models have no modules to swap):

- **layer reduction**: slice the stacked [L, ...] layer weights to the kept
  layer ids — a pure gather on the leading axis.
- **weight quantization**: fake-quant (quantize-dequantize) params for QAT,
  or export real int8 + scales (``quantize_weights``) for serving.
- **sparse/row pruning**: magnitude masks applied to the param tree; masks
  can be re-applied each step via ``apply_masks`` (the reference reapplies
  after each optimizer step).

All transforms are jit-friendly jnp ops; schedule gating (``schedule_offset``)
is honored by the caller passing ``global_step``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------------------
# weight quantization
# ---------------------------------------------------------------------------

def fake_quantize(w, bits: int = 8, symmetric: bool = True, axis: Optional[int] = None):
    """Quantize-dequantize (QAT forward behavior).  Per-tensor, or
    per-channel when ``axis`` is given."""
    w32 = w.astype(jnp.float32)
    qmax = 2.0 ** (bits - 1) - 1
    if symmetric:
        red_axes = tuple(i for i in range(w32.ndim) if i != axis) or None
        absmax = jnp.max(jnp.abs(w32), axis=red_axes, keepdims=True)
        scale = jnp.where(absmax == 0, 1.0, absmax / qmax)
        q = jnp.clip(jnp.round(w32 / scale), -qmax, qmax)
        return (q * scale).astype(w.dtype)
    mn = jnp.min(w32)
    mx = jnp.max(w32)
    scale = jnp.where(mx == mn, 1.0, (mx - mn) / (2.0 ** bits - 1))
    q = jnp.round((w32 - mn) / scale)
    return (q * scale + mn).astype(w.dtype)


def quantize_weights(w, bits: int = 8) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Real int8 export: returns (q int8, scale fp32 per output channel)."""
    assert bits == 8, "int8 export only"
    w32 = w.astype(jnp.float32)
    red = tuple(range(w32.ndim - 1))
    absmax = jnp.max(jnp.abs(w32), axis=red, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


# ---------------------------------------------------------------------------
# pruning
# ---------------------------------------------------------------------------

def magnitude_mask(w, density: float):
    """Keep the top ``density`` fraction by |magnitude| (unstructured)."""
    k = max(1, int(w.size * density))
    thresh = jnp.sort(jnp.abs(w).reshape(-1))[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def row_mask(w, density: float, axis: int = -1):
    """Structured row/head pruning: keep top rows by L2 norm along ``axis``."""
    norms = jnp.linalg.norm(w.astype(jnp.float32), axis=axis)
    k = max(1, int(norms.size * density))
    thresh = jnp.sort(norms.reshape(-1))[-k]
    keep = (norms >= thresh).astype(w.dtype)
    return jnp.expand_dims(keep, axis)


# ---------------------------------------------------------------------------
# layer reduction
# ---------------------------------------------------------------------------

def reduce_layers(params: Dict[str, Any], keep_layers: List[int]) -> Dict[str, Any]:
    """Slice stacked [L, ...] layer params down to ``keep_layers`` (the
    reference's ``layer_reduction`` with ``teacher_layer`` ids)."""
    idx = jnp.asarray(keep_layers)
    out = dict(params)
    out["layers"] = jax.tree.map(lambda a: jnp.take(a, idx, axis=0),
                                 params["layers"])
    return out


# ---------------------------------------------------------------------------
# config-driven entry points (reference API)
# ---------------------------------------------------------------------------

class CompressionConfig:
    def __init__(self, d: Dict[str, Any]):
        d = d.get("compression_training", d) or {}
        wq = d.get("weight_quantization", {}).get("shared_parameters", {})
        self.wq_enabled = wq.get("enabled", False)
        self.wq_bits = 8
        for group in d.get("weight_quantization", {}).get(
                "different_groups", {}).values():
            bits = group.get("params", {}).get("target_bits")
            if isinstance(bits, int):
                self.wq_bits = bits
                break
        sp = d.get("sparse_pruning", {}).get("shared_parameters", {})
        self.sp_enabled = sp.get("enabled", False)
        self.sp_density = d.get("sparse_pruning", {}).get("different_groups", {}).get(
            "sp1", {}).get("params", {}).get("dense_ratio", sp.get("dense_ratio", 0.5))
        self.sp_offset = sp.get("schedule_offset", 0)
        lr_ = d.get("layer_reduction", {})
        self.lr_enabled = lr_.get("enabled", False)
        self.keep_layers = lr_.get("teacher_layer", [])


class CompressedParams:
    """Holds masks + config; ``apply(params)`` returns the compressed view
    (called in forward for QAT, or once at export)."""

    def __init__(self, config: Dict[str, Any]):
        self.cfg = CompressionConfig(config)
        self.masks: Dict[str, Any] = {}

    def init_masks(self, params) -> None:
        if not self.cfg.sp_enabled:
            return
        self.masks = jax.tree.map(
            lambda w: magnitude_mask(w, self.cfg.sp_density)
            if getattr(w, "ndim", 0) >= 2 else jnp.ones_like(w),
            params["layers"])

    def apply(self, params, global_step: int = 10**9):
        out = params
        # masks were built against the FULL layer stack: apply them before
        # any layer reduction slices the leading dim
        if self.cfg.sp_enabled and self.masks and global_step >= self.cfg.sp_offset:
            out = {**out, "layers": jax.tree.map(lambda w, m: w * m,
                                                 out["layers"], self.masks)}
        if self.cfg.lr_enabled and self.cfg.keep_layers:
            out = reduce_layers(out, self.cfg.keep_layers)
        if self.cfg.wq_enabled:
            out = {**out, "layers": jax.tree.map(
                lambda w: fake_quantize(w, bits=self.cfg.wq_bits)
                if getattr(w, "ndim", 0) >= 2 else w, out["layers"])}
        return out


def init_compression(model, deepspeed_config: Dict[str, Any], mpu=None):
    """Reference entry: attach a CompressedParams transform to the model.
    The model's forward applies it when present (built-in models call
    ``maybe_compress`` via the engine loss fn wrapper)."""
    comp = CompressedParams(deepspeed_config)
    if hasattr(model, "config"):
        model._compression = comp
    logger.info("compression initialized: wq=%s sp=%s layer_reduction=%s",
                comp.cfg.wq_enabled, comp.cfg.sp_enabled, comp.cfg.lr_enabled)
    return model, comp


def redundancy_clean(model, deepspeed_config: Dict[str, Any], params=None):
    """Reference entry: bake compression into the weights (export)."""
    comp = getattr(model, "_compression", None) or CompressedParams(deepspeed_config)
    if params is None:
        return model
    if comp.cfg.sp_enabled and not comp.masks:
        comp.init_masks(params)
    return comp.apply(params)
