"""Copy-on-write prefix caching over the paged KV pool.

Repeated-prefix traffic (shared system prompts, multi-turn chat, a
preempted request re-prefilling its own history) re-computes prefill for
tokens whose KV already sits in the page pool.  This module is the
vLLM/SGLang radix-cache idiom mapped onto ``serving/paged_kv.py``: a
**page-granular trie** over prompt token ids whose nodes name physical
pages, so a new request's admission can pre-populate its page table with
pages another request already computed and start prefill at the match
frontier.  The flash-decode kernel already indirects every read through
the per-slot page table, so the read path needs ZERO kernel changes —
sharing is purely allocator bookkeeping (refcounts) plus one device-side
page copy for the partially-matched boundary page a request will write
into (copy-on-write; the engine owns the copy, this module only the
matching).

Structure: one trie node per ``page_tokens``-sized chunk of token ids
(children keyed by the exact chunk tuple — a radix tree whose edge labels
are all page-length, which makes every match page-aligned by
construction).  ``match`` walks the prompt down the trie and returns the
pages of the longest cached prefix; ``insert`` (at request finish) adds
the request's full-prompt pages, pinning newly-added pages in the pool so
they survive the request's release.  Under pool pressure the engine calls
``evict_lru``: the least-recently-used LEAF whose page no live slot
references is unpinned back to the free list — cached pages are
reclaimed BEFORE any live request is preempted, and leaf-first eviction
keeps every remaining root-path intact (a match can never dangle).

Host-side bookkeeping only — no jax, and importable without the
``deepspeed_tpu`` package (``tools/router.py`` does not need it, but the
no-jax loading idiom is shared with ``serving/router.py``).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PrefixCache"]


class _Node:
    """One cached page: the chunk of token ids it holds, the physical
    page, and its LRU tick (monotone counter, not wall time — eviction
    order is deterministic under test)."""

    __slots__ = ("chunk", "page", "parent", "children", "tick")

    def __init__(self, chunk: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.tick = 0


class PrefixCache:
    """Page-granular radix/trie prefix cache over a :class:`~deepspeed_tpu.
    serving.paged_kv.PagedKVPool`.

    The cache owns no device memory: it maps token-id prefixes to
    physical page ids and pins those pages in the pool
    (:meth:`~deepspeed_tpu.serving.paged_kv.PagedKVPool.pin`) so the
    allocator parks them instead of freeing.  All mutation happens on the
    engine's scheduling thread.
    """

    def __init__(self, pool, registry=None):
        self.pool = pool
        self.page = pool.page
        self._children: Dict[Tuple[int, ...], _Node] = {}   # root level
        self._nodes = 0
        self._tick = itertools.count(1)
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry

            registry = get_registry()
        self._m_pages = registry.gauge(
            "ds_serve_prefix_cache_pages",
            "physical pages pinned by the prefix cache")
        self._m_evictions = registry.counter(
            "ds_serve_prefix_evictions_total",
            "cached pages evicted (LRU) under pool pressure")

    def __len__(self) -> int:
        return self._nodes

    # ------------------------------------------------------------------
    def match(self, tokens: np.ndarray) -> List[int]:
        """Pages of the longest cached prefix of ``tokens`` (whole pages
        only — the trie's edges are page-length, so the returned length
        is ``len(result) * page_tokens`` by construction).  Touches the
        matched path's LRU ticks."""
        pages: List[int] = []
        children = self._children
        tick = next(self._tick)
        toks = np.asarray(tokens)
        for i in range(len(toks) // self.page):
            chunk = tuple(int(t) for t in
                          toks[i * self.page:(i + 1) * self.page])
            node = children.get(chunk)
            if node is None:
                break
            node.tick = tick
            pages.append(node.page)
            children = node.children
        return pages

    def insert(self, tokens: np.ndarray, pages: List[int]) -> int:
        """Insert the full-page prefix of ``tokens`` backed by ``pages``
        (the finishing request's first ``len(pages)`` page-table entries,
        in order).  Chunks already cached keep their EXISTING page — a
        concurrent duplicate computation's page simply is not pinned and
        frees with its request; only genuinely new pages are pinned.
        Returns how many pages were newly added."""
        toks = np.asarray(tokens)
        n_full = min(len(toks) // self.page, len(pages))
        children = self._children
        parent: Optional[_Node] = None
        tick = next(self._tick)
        added = 0
        for i in range(n_full):
            chunk = tuple(int(t) for t in
                          toks[i * self.page:(i + 1) * self.page])
            node = children.get(chunk)
            if node is None:
                node = _Node(chunk, int(pages[i]), parent)
                children[chunk] = node
                self.pool.pin(node.page)
                self._nodes += 1
                added += 1
            node.tick = tick
            parent = node
            children = node.children
        if added:
            self._m_pages.set(self.pool.pages_cached)
        return added

    # ------------------------------------------------------------------
    def evict_lru(self) -> int:
        """Evict the least-recently-used LEAF whose page no live slot
        references (refcount 0): unpin it back to the pool's free list.
        Returns the number of pages freed (0 = nothing evictable — every
        cached page is either shared by a live slot or an interior node
        with live descendants; the caller falls back to preemption).
        Leaf-first keeps all remaining root-paths matchable.

        The victim search is a full O(nodes) walk per eviction — a
        deliberate trade at today's pool scales (hundreds to low
        thousands of tiny nodes; microseconds on the admission path,
        and evictions only happen under pool pressure).  If pools grow
        to where bulk reclaim matters, keep evictable leaves in an
        incrementally-maintained tick-ordered structure instead."""
        victim: Optional[_Node] = None
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif self.pool.ref(node.page) == 0 and (
                    victim is None or node.tick < victim.tick):
                victim = node
        if victim is None:
            return 0
        siblings = (victim.parent.children if victim.parent is not None
                    else self._children)
        del siblings[victim.chunk]
        self._nodes -= 1
        self.pool.unpin(victim.page)
        self._m_evictions.inc()
        self._m_pages.set(self.pool.pages_cached)
        return 1

    def clear(self) -> int:
        """Drop every cached page (tests / explicit cache reset); returns
        pages unpinned."""
        n = 0
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self.pool.unpin(node.page)
            n += 1
        self._children = {}
        self._nodes = 0
        self._m_pages.set(self.pool.pages_cached)
        return n
