"""Copy-on-write prefix caching over the paged KV pool, with a host tier.

Repeated-prefix traffic (shared system prompts, multi-turn chat, a
preempted request re-prefilling its own history) re-computes prefill for
tokens whose KV already sits in the page pool.  This module is the
vLLM/SGLang radix-cache idiom mapped onto ``serving/paged_kv.py``: a
**page-granular trie** over prompt token ids whose nodes name physical
pages, so a new request's admission can pre-populate its page table with
pages another request already computed and start prefill at the match
frontier.  The flash-decode kernel already indirects every read through
the per-slot page table, so the read path needs ZERO kernel changes —
sharing is purely allocator bookkeeping (refcounts) plus one device-side
page copy for the partially-matched boundary page a request will write
into (copy-on-write; the engine owns the copy, this module only the
matching).

Structure: one trie node per ``page_tokens``-sized chunk of token ids
(children keyed by the exact chunk tuple — a radix tree whose edge labels
are all page-length, which makes every match page-aligned by
construction).  ``match``/``match_nodes`` walk the prompt down the trie;
``insert`` (at request finish/preempt) adds the request's full-prompt
pages, pinning newly-added pages in the pool so they survive the
request's release.

**Eviction** (``evict_lru``, called by the engine under pool pressure)
walks an INTRUSIVE LRU list over cached device pages — every match/insert
moves the touched nodes to the MRU tail, so the victim scan starts at the
genuine LRU head and only skips the (rare) entries a live slot still
references, replacing the PR 9 O(nodes) full-trie walk (deliberate then;
the host tier makes eviction hot).  What eviction DOES depends on the
tier:

- no host tier (``kv_host_tier_pages=0``): the LRU **leaf** whose page no
  live slot references is unpinned back to the free list and its node
  removed (leaf-first keeps every remaining root-path matchable) — the
  PR 9 semantics, bit-for-bit;
- host tier attached: the LRU ref-0 node's page payload is copied
  device->host into the bounded :class:`~deepspeed_tpu.serving.host_tier.
  HostPageStore` ("demote") and the node STAYS in the trie, now
  host-resident (``page == -1``) — the trie structure is preserved, so
  interior nodes demote as freely as leaves.  A later admission that
  matches the chunk allocates a fresh device page, streams the payload
  back ("promote"), and re-pins it — byte-identical KV, so greedy outputs
  cannot change.  The effective prefix cache is host-RAM-sized.

Host-side bookkeeping only — no jax; the engine owns all device<->host
copies and hands them in as ``fetch_page`` (demote reader).  All mutation
happens on the engine's scheduling thread.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PrefixCache"]


class _Node:
    """One cached chunk: the token ids it holds and WHERE its KV lives —
    a device page (``page >= 0``, pinned in the pool, linked into the
    LRU list) or the host tier (``page == -1``, ``host_key`` names the
    :class:`HostPageStore` entry).  ``tick`` is a monotone touch counter
    kept for introspection; eviction order is the intrusive list."""

    __slots__ = ("chunk", "page", "host_key", "parent", "children", "tick",
                 "lru_prev", "lru_next")

    def __init__(self, chunk: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.chunk = chunk
        self.page = page
        self.host_key: Optional[int] = None
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.tick = 0
        self.lru_prev: Optional["_Node"] = None
        self.lru_next: Optional["_Node"] = None


class PrefixCache:
    """Page-granular radix/trie prefix cache over a :class:`~deepspeed_tpu.
    serving.paged_kv.PagedKVPool`, optionally backed by a
    :class:`~deepspeed_tpu.serving.host_tier.HostPageStore`.

    The cache owns no device memory: it maps token-id prefixes to
    physical page ids and pins those pages in the pool
    (:meth:`~deepspeed_tpu.serving.paged_kv.PagedKVPool.pin`) so the
    allocator parks them instead of freeing.  With a host tier attached
    (``host_store`` + the engine's ``fetch_page`` device->host reader),
    eviction demotes instead of dropping.
    """

    def __init__(self, pool, registry=None, host_store=None, fetch_page=None):
        self.pool = pool
        self.page = pool.page
        self.host_store = host_store
        self._fetch_page = fetch_page
        if host_store is not None and fetch_page is None:
            raise ValueError("host_store needs the engine's fetch_page "
                             "(device->host page reader)")
        self._children: Dict[Tuple[int, ...], _Node] = {}   # root level
        self._nodes = 0
        self._tick = itertools.count(1)
        self._host_nodes: Dict[int, _Node] = {}   # store key -> node
        # intrusive LRU list over DEVICE-paged nodes: head = LRU victim,
        # tail = MRU; sentinel closes the ring
        self._lru = _Node((), -2, None)
        self._lru.lru_prev = self._lru.lru_next = self._lru
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry

            registry = get_registry()
        self._m_pages = registry.gauge(
            "ds_serve_prefix_cache_pages",
            "physical pages pinned by the prefix cache")
        self._m_evictions = registry.counter(
            "ds_serve_prefix_evictions_total",
            "cached pages evicted from the device pool (LRU) under pool "
            "pressure (demoted to the host tier when one is attached, "
            "dropped otherwise)")

    def __len__(self) -> int:
        return self._nodes

    @property
    def host_pages(self) -> int:
        return len(self.host_store) if self.host_store is not None else 0

    # -- intrusive LRU list -------------------------------------------
    def _lru_remove(self, node: _Node) -> None:
        p, n = node.lru_prev, node.lru_next
        if p is not None:
            p.lru_next = n
            n.lru_prev = p
        node.lru_prev = node.lru_next = None

    def _lru_append(self, node: _Node) -> None:
        tail = self._lru.lru_prev
        tail.lru_next = node
        node.lru_prev = tail
        node.lru_next = self._lru
        self._lru.lru_prev = node

    def _lru_touch(self, node: _Node) -> None:
        self._lru_remove(node)
        self._lru_append(node)

    # ------------------------------------------------------------------
    def _walk(self, tokens: np.ndarray):
        """Yield matched nodes chunk by chunk (no touching)."""
        children = self._children
        toks = np.asarray(tokens)
        for i in range(len(toks) // self.page):
            chunk = tuple(int(t) for t in
                          toks[i * self.page:(i + 1) * self.page])
            node = children.get(chunk)
            if node is None:
                return
            yield node
            children = node.children

    def match_nodes(self, tokens: np.ndarray) -> List[_Node]:
        """Nodes of the longest cached prefix of ``tokens`` (whole pages
        only).  Touches the matched path (LRU) in both tiers; a node
        whose host entry aged out of the bounded store ends the match and
        is pruned (with its now-unreachable subtree)."""
        out: List[_Node] = []
        tick = next(self._tick)
        for node in self._walk(tokens):
            if node.page < 0:
                if (self.host_store is None
                        or not self.host_store.touch(node.host_key)):
                    self._drop_subtree(node)
                    break
            else:
                self._lru_touch(node)
            node.tick = tick
            out.append(node)
        return out

    def match(self, tokens: np.ndarray) -> List[int]:
        """Device pages of the longest DEVICE-resident cached prefix (the
        pre-host-tier contract: page ids ready to adopt as-is; a
        host-resident chunk ends the walk — promoting is the engine's
        call, via :meth:`match_nodes`)."""
        pages: List[int] = []
        for node in self.match_nodes(tokens):
            if node.page < 0:
                break
            pages.append(node.page)
        return pages

    def host_payload(self, node: _Node):
        """The demoted payload backing a host-resident node (None if it
        aged out — the caller should treat the match as ended)."""
        if self.host_store is None or node.host_key is None:
            return None
        return self.host_store.get(node.host_key)

    # ------------------------------------------------------------------
    def insert(self, tokens: np.ndarray, pages: List[int]) -> int:
        """Insert the full-page prefix of ``tokens`` backed by ``pages``
        (the finishing request's first ``len(pages)`` page-table entries,
        in order).  Chunks already cached keep their EXISTING page — a
        concurrent duplicate computation's page simply is not pinned and
        frees with its request; a chunk that was DEMOTED to host is
        re-homed onto the newcomer's freshly-computed device page (the
        data is identical; the host entry drops).  Returns how many pages
        were newly pinned."""
        toks = np.asarray(tokens)
        n_full = min(len(toks) // self.page, len(pages))
        children = self._children
        parent: Optional[_Node] = None
        tick = next(self._tick)
        added = 0
        for i in range(n_full):
            chunk = tuple(int(t) for t in
                          toks[i * self.page:(i + 1) * self.page])
            node = children.get(chunk)
            if node is None:
                node = _Node(chunk, int(pages[i]), parent)
                children[chunk] = node
                self.pool.pin(node.page)
                self._lru_append(node)
                self._nodes += 1
                added += 1
            elif node.page < 0:
                # host-resident chunk re-computed by this request: promote
                # in place to the newcomer's device page (same bytes)
                self._drop_host_entry(node)
                node.page = int(pages[i])
                self.pool.pin(node.page)
                self._lru_append(node)
                added += 1
            else:
                self._lru_touch(node)
            node.tick = tick
            parent = node
            children = node.children
        if added:
            self._m_pages.set(self.pool.pages_cached)
        return added

    # ------------------------------------------------------------------
    def adopt_chunks(self, chunks: List[Tuple[int, ...]],
                     payloads: Dict[int, dict],
                     alloc_page, write_page) -> int:
        """Adopt a HANDED-OFF prefix (disaggregated serving): ``chunks``
        is the sender's manifest (page-sized token chunks from the
        root), ``payloads`` maps chunk index -> page payload for the
        chunks the sender shipped (it skips ones we reported as already
        held).  Chunks already cached are touched in place, either tier;
        a missing chunk with a payload gets a fresh page from
        ``alloc_page`` (the engine's pressure-aware allocator — it may
        evict through THIS cache mid-walk, which is safe: the walk
        re-reads ``children`` each step and eviction never unpins a
        just-pinned node), written via ``write_page``, and pinned into
        the trie exactly like :meth:`insert`.  The walk stops at the
        first chunk it can neither find nor fill (missing payload, pool
        exhausted) — everything past it would be unmatchable anyway.
        Returns pages newly adopted."""
        children = self._children
        parent: Optional[_Node] = None
        tick = next(self._tick)
        adopted = 0
        for i, chunk in enumerate(chunks):
            chunk = tuple(int(t) for t in chunk)
            node = children.get(chunk)
            if node is not None and node.page == -1:
                if (self.host_store is None
                        or not self.host_store.touch(node.host_key)):
                    # host entry aged out: path is dead — prune and fall
                    # through to re-homing it from the payload
                    self._drop_subtree(node)
                    node = None
            if node is None:
                payload = payloads.get(i)
                if payload is None:
                    break
                page = alloc_page()
                if page is None:
                    break
                write_page(page, payload)
                node = _Node(chunk, int(page), parent)
                children[chunk] = node
                self.pool.pin(node.page)
                self._lru_append(node)
                self._nodes += 1
                adopted += 1
            elif node.page >= 0:
                self._lru_touch(node)
            node.tick = tick
            parent = node
            children = node.children
        if adopted:
            self._m_pages.set(self.pool.pages_cached)
        return adopted

    # ------------------------------------------------------------------
    def _drop_host_entry(self, node: _Node) -> None:
        if node.host_key is not None:
            self._host_nodes.pop(node.host_key, None)
            if self.host_store is not None:
                self.host_store.drop(node.host_key)
            node.host_key = None

    def _detach(self, node: _Node) -> None:
        siblings = (node.parent.children if node.parent is not None
                    else self._children)
        if siblings.get(node.chunk) is node:
            del siblings[node.chunk]

    def _drop_subtree(self, node: _Node) -> None:
        """Remove ``node`` and everything under it (unpin device pages,
        drop host entries) — used when a host entry ages out of the
        bounded store, making the path unmatchable.  Dropped nodes are
        TOMBSTONED (``page == -2``): an admission holding a stale
        ``match_nodes`` snapshot must not adopt a page that just went
        back to the free list (an eviction triggered by the admission's
        OWN promotion pressure can land here mid-walk)."""
        self._detach(node)
        stack = [node]
        while stack:
            cur = stack.pop()
            stack.extend(cur.children.values())
            cur.children = {}
            if cur.page >= 0:
                self._lru_remove(cur)
                self.pool.unpin(cur.page)
            self._drop_host_entry(cur)
            cur.page = -2
            self._nodes -= 1
        self._m_pages.set(self.pool.pages_cached)

    def invalidate_host_keys(self, keys: List[int]) -> None:
        """The bounded host store evicted these entries (oldest-first
        overflow): prune the trie paths that pointed at them."""
        for key in keys:
            node = self._host_nodes.pop(key, None)
            if node is not None:
                node.host_key = None      # store already dropped it
                self._drop_subtree(node)

    # ------------------------------------------------------------------
    def evict_lru(self) -> int:
        """Reclaim ONE device page from the cache under pool pressure:
        the least-recently-used pinned page no live slot references
        (refcount 0), found by walking the intrusive LRU list from its
        head (live-referenced entries are skipped in place).  Without a
        host tier the victim must also be a LEAF and its node is removed
        (the PR 9 drop semantics); with one, the payload demotes to the
        host store and the node stays matchable.  Returns pages freed
        (0 = nothing evictable — the caller falls back to preemption)."""
        demote = self.host_store is not None
        node = self._lru.lru_next
        victim: Optional[_Node] = None
        while node is not self._lru:
            if self.pool.ref(node.page) == 0 and (demote
                                                  or not node.children):
                victim = node
                break
            node = node.lru_next
        if victim is None:
            return 0
        page = victim.page
        if demote:
            payload = self._fetch_page(page)
            key, overflow = self.host_store.put(payload)
            self._host_nodes[key] = victim
            victim.host_key = key
            victim.page = -1
            self._lru_remove(victim)
            self.pool.unpin(page)
            # the bounded store may have pushed out older host entries;
            # their paths are no longer matchable
            self.invalidate_host_keys(overflow)
        else:
            self._detach(victim)
            self._lru_remove(victim)
            self._nodes -= 1
            self.pool.unpin(page)
        self._m_evictions.inc()
        self._m_pages.set(self.pool.pages_cached)
        return 1

    def promote(self, node: _Node, page: int) -> None:
        """Re-home a host-resident node onto ``page`` (the engine just
        streamed the payload into it): pin it, drop the host entry, and
        rejoin the device LRU.  The engine counts the promote on the
        store's ``ds_serve_kv_promote_total``."""
        assert node.page < 0, "promote of a device-resident node"
        self._drop_host_entry(node)
        node.page = int(page)
        self.pool.pin(node.page)
        self._lru_append(node)
        self._m_pages.set(self.pool.pages_cached)

    def clear(self) -> int:
        """Drop every cached page, both tiers (tests / explicit cache
        reset); returns device pages unpinned."""
        n = 0
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.page >= 0:
                self._lru_remove(node)
                self.pool.unpin(node.page)
                n += 1
            self._drop_host_entry(node)
        self._children = {}
        self._nodes = 0
        self._host_nodes = {}
        if self.host_store is not None:
            self.host_store.clear()
        self._m_pages.set(self.pool.pages_cached)
        return n

    # ------------------------------------------------------------------
    def check_no_leak(self) -> None:
        """Invariant probe over the {device, host} node partition (tests;
        the pool-side probe is ``PagedKVPool.check_no_leak``): every
        device node's page is pinned in the pool and linked into the LRU
        list exactly once; every host node's key is live in the store;
        store entries and host nodes are in bijection; node count adds
        up."""
        dev_pages, host_keys, total = [], [], 0
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            total += 1
            if node.page >= 0:
                assert node.host_key is None, "node resident in both tiers"
                dev_pages.append(node.page)
            else:
                assert self.host_store is not None and node.host_key is not None
                host_keys.append(node.host_key)
                assert self._host_nodes.get(node.host_key) is node
        assert total == self._nodes, (total, self._nodes)
        assert sorted(host_keys) == sorted(self._host_nodes), \
            "host-node map out of sync with the trie"
        if self.host_store is not None:
            assert sorted(host_keys) == sorted(self.host_store.keys()), (
                f"store/trie mismatch: {sorted(host_keys)} vs "
                f"{sorted(self.host_store.keys())}")
        assert len(set(dev_pages)) == len(dev_pages), "page cached twice"
        assert set(dev_pages) == set(self.pool._cached), (
            f"pins out of sync: trie={sorted(dev_pages)} "
            f"pool={sorted(self.pool._cached)}")
        linked = []
        node = self._lru.lru_next
        while node is not self._lru:
            linked.append(node.page)
            node = node.lru_next
        assert sorted(linked) == sorted(dev_pages), (
            f"LRU list out of sync: {sorted(linked)} vs {sorted(dev_pages)}")
