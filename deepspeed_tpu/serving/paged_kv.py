"""Paged KV cache: a block allocator over one shared pool of token pages.

The fixed-slot serving cache (PR 1) reserves ``max_out_tokens`` of KV per
slot, so a slot holding a 30-token chat reply pins the same HBM as one
decoding 2k tokens — with bimodal chat-like lengths most of the
reservation is dead weight and the slot count (goodput) is bounded by the
worst-case request.  This module is the vLLM/PagedAttention answer mapped
onto the existing flash-decode stack, and the serving-time counterpart of
the ZeRO-Infinity argument (arXiv:2104.07857): treat KV memory as a
managed pool, not a static reservation.

Layout: the physical cache is ``[L, num_pages, Hkv, page_tokens, Dh]``
(one pool shared by every slot) and each slot owns an ordered list of
pages recorded in a ``[num_slots, slot_pages]`` int32 **page table**:
logical token ``t`` of a slot lives at row ``t % page_tokens`` of
physical page ``page_table[slot, t // page_tokens]``.  The table is host
state, shipped into every compiled program; reads indirect through it
(the Pallas flash-decode index map DMAs the right physical page per
block; the XLA fallback gathers a logical view) and per-row appends
scatter through it.

Physical **page 0 is reserved as the junk page**: it is never allocated,
and a released slot's table rows all point at it, so the parked row's
junk K/V writes (inactive rows still execute in the static-shape compiled
step) land somewhere no live slot ever reads.

Allocation is host-side bookkeeping only (``ensure`` before a dispatch
covers the tokens it will write; ``release`` on finish) — the pool's
device arrays are owned and donated by the engine.  When the pool runs
dry the engine first evicts unreferenced prefix-cache pages (LRU), then
preempts the youngest-admitted slot (LIFO) and requeues it at the head of
the wait queue; the oldest request always keeps its pages, so admission
pressure cannot livelock the pool.

Prefix caching (``serving/prefix_cache.py``) rides on two extensions:

- **per-page refcounts** — a physical page may appear in several slots'
  page tables at once (``adopt`` INCREFs pages a new request shares
  read-only; ``release`` DECREFs, returning a page to the free list only
  when its last referencing slot lets go).  Shared pages are never
  written: prefill (re)starts at the match frontier, decode writes only
  at/after it, and a partially-matched boundary page is copied to a
  private page before the slot writes into it (copy-on-write — the
  engine's device-side page copy; the pool only swaps the bookkeeping).
- **cache pins** — pages held by the prefix cache (``pin``/``unpin``) are
  kept OFF the free list even at refcount 0, so a finished request's
  prompt KV survives for future admissions; eviction (``unpin``) is the
  cache's LRU decision, taken under pool pressure BEFORE any live slot is
  preempted.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.decoding import DECODE_BLOCK


def default_page_tokens(max_out_tokens: int) -> int:
    """Page granularity when the config leaves it 0: the flash-decode
    block (pages ARE the kernel's DMA blocks), capped at the smallest
    power of two covering the per-slot budget so tiny configs don't round
    a 64-token budget up to one 256-token page."""
    from deepspeed_tpu.inference.engine import pow2_bucket

    return min(DECODE_BLOCK, pow2_bucket(max_out_tokens, lo=8))


def init_paged_kv_cache(cfg, num_pages: int, page_tokens: int,
                        dtype=jnp.bfloat16,
                        quantized: bool = False) -> Dict[str, Any]:
    """Device arrays for the shared page pool — the paged analog of
    :func:`~deepspeed_tpu.models.decoding.init_kv_cache`, with the slot
    dim replaced by the page dim and the sequence dim by the page depth."""
    L, Hkv, Dh = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    if quantized:
        return {
            "k": jnp.zeros((L, num_pages, Hkv, page_tokens, Dh), jnp.int8),
            "v": jnp.zeros((L, num_pages, Hkv, page_tokens, Dh), jnp.int8),
            "k_scale": jnp.zeros((L, num_pages, Hkv, page_tokens, 1),
                                 jnp.float32),
            "v_scale": jnp.zeros((L, num_pages, Hkv, page_tokens, 1),
                                 jnp.float32),
            "x_dtype": jnp.zeros((), dtype),
        }
    return {
        "k": jnp.zeros((L, num_pages, Hkv, page_tokens, Dh), dtype),
        "v": jnp.zeros((L, num_pages, Hkv, page_tokens, Dh), dtype),
    }


class PagedKVPool:
    """Host-side free-list allocator for the page pool.

    Parameters
    ----------
    num_slots:
        Slots (page-table rows) sharing the pool.
    max_out_tokens:
        Per-slot LOGICAL budget (prompt + generation), same meaning as the
        fixed-slot cache; rounded up to a page multiple for the physical
        table depth (``cache_len``).
    page_tokens:
        Tokens per page (0 = :func:`default_page_tokens`).
    pool_tokens:
        Total pool capacity in tokens (0 = ``num_slots * cache_len`` — the
        same HBM as the fixed layout, but allocated on demand).  Setting
        it lower oversubscribes slots against a fixed HBM budget; the pool
        always holds at least one slot's full budget so a lone request can
        never deadlock.
    """

    def __init__(self, num_slots: int, max_out_tokens: int, *,
                 page_tokens: int = 0, pool_tokens: int = 0):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.page = int(page_tokens) or default_page_tokens(max_out_tokens)
        self.slot_pages = -(-int(max_out_tokens) // self.page)
        self.cache_len = self.slot_pages * self.page
        want = int(pool_tokens) or num_slots * self.cache_len
        usable = max(self.slot_pages, -(-want // self.page))
        self.num_pages = usable + 1          # + the reserved junk page 0
        self.num_slots = num_slots
        # unallocated entries point at the junk page
        self.page_table = np.zeros((num_slots, self.slot_pages), np.int32)
        self._owned: List[List[int]] = [[] for _ in range(num_slots)]
        # per-page SLOT refcount (prefix-cache sharing: the same physical
        # page may sit in several slots' tables); the junk page is never
        # counted
        self._ref = np.zeros(self.num_pages, np.int32)
        # pages pinned by the prefix cache: kept off the free list even at
        # refcount 0 until the cache evicts them (unpin)
        self._cached: set = set()
        # LIFO free list: released pages are reused first (locality, and
        # deterministic reuse for the preempt-resume tests)
        self._free: List[int] = list(range(usable, 0, -1))

    # -- allocation ----------------------------------------------------
    def ensure(self, slot: int, tokens: int) -> bool:
        """Grow the slot's table to cover ``tokens`` logical tokens.
        Returns False when the pool is exhausted — pages already granted
        stay with the slot (the caller evicts cached pages / preempts a
        victim and retries)."""
        if tokens > self.cache_len:
            raise ValueError(f"slot needs {tokens} tokens > per-slot budget "
                             f"{self.cache_len}")
        owned = self._owned[slot]
        need = -(-int(tokens) // self.page)
        while len(owned) < need:
            if not self._free:
                return False
            p = self._free.pop()
            self.page_table[slot, len(owned)] = p
            owned.append(p)
            self._ref[p] += 1
        return True

    def append_shared(self, slot: int, page: int) -> None:
        """Append ONE already-populated page to the slot's table, shared
        READ-ONLY (INCREF'd) — the unit step :meth:`adopt` loops, exposed
        separately so the host-tier admission can interleave adopting
        device-resident pages with promoting host-resident ones."""
        assert page != 0, "cannot adopt the junk page"
        owned = self._owned[slot]
        assert len(owned) < self.slot_pages, f"slot {slot} table full"
        self.page_table[slot, len(owned)] = page
        owned.append(page)
        self._ref[page] += 1

    def adopt(self, slot: int, pages: List[int]) -> None:
        """Pre-populate a freshly-admitted slot's table with pages another
        request already computed (prefix-cache hit): each page is INCREF'd
        and shared READ-ONLY — the adopting request's prefill starts past
        them and its decode writes only into later, privately-allocated
        pages.  The slot must not own anything yet (admission-time only)."""
        owned = self._owned[slot]
        assert not owned, f"adopt into non-empty slot {slot}: {owned}"
        for p in pages:
            self.append_shared(slot, p)

    def alloc_page(self) -> Optional[int]:
        """Pop one free page WITHOUT binding it to a slot (refcount 0,
        unpinned) — the host-tier promotion target: the engine streams the
        demoted payload into it, then the cache pins it and the admitting
        slot adopts it, all within one admission (the page is never left
        dangling across a scheduler step).  None when the pool is dry —
        the caller evicts/demotes and retries."""
        if not self._free:
            return None
        return self._free.pop()

    def release(self, slot: int) -> int:
        """DECREF every page the slot references and park its table rows
        on the junk page; returns the number of pages actually returned to
        the free list (shared/cache-pinned pages survive their owners)."""
        owned = self._owned[slot]
        freed = 0
        for p in owned:
            self._ref[p] -= 1
            if self._ref[p] == 0 and p not in self._cached:
                self._free.append(p)
                freed += 1
        owned.clear()
        self.page_table[slot, :] = 0
        return freed

    # -- prefix-cache pins ---------------------------------------------
    def pin(self, page: int) -> None:
        """Keep ``page`` alive for the prefix cache: once its last slot
        releases it, it parks as a cached page instead of going free."""
        assert page != 0, "cannot pin the junk page"
        self._cached.add(page)

    def unpin(self, page: int) -> None:
        """Cache eviction: drop the pin; a page no slot references goes
        straight to the free list (its KV content stays intact until the
        page is reallocated and overwritten)."""
        self._cached.discard(page)
        if self._ref[page] == 0:
            self._free.append(page)

    def ref(self, page: int) -> int:
        """Live-slot references on ``page`` (the prefix cache's eviction
        eligibility check: only refcount-0 pages may be evicted)."""
        return int(self._ref[page])

    # -- accounting ----------------------------------------------------
    @property
    def pages_used(self) -> int:
        """Distinct physical pages referenced by at least one slot (a
        shared page counts once — it occupies one page of HBM)."""
        return int((self._ref > 0).sum())

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_cached(self) -> int:
        """Pages pinned by the prefix cache (shared pages a live slot
        also references are included — the pin is what outlives them)."""
        return len(self._cached)

    def slot_pages_used(self, slot: int) -> int:
        return len(self._owned[slot])

    def owned(self, slot: int) -> List[int]:
        """The slot's page ids in logical order (a copy — the engine's
        prefix-cache insertion reads the prompt's page span from here)."""
        return list(self._owned[slot])

    def utilization(self, live_tokens: int) -> float:
        """live-tokens / allocated-page-tokens (1.0 = every allocated page
        row holds a live token; the fixed-slot layout's equivalent is
        live / (num_slots * cache_len)).  With prefix sharing the ratio
        can exceed 1 — several slots' live tokens backed by one physical
        page is precisely the memory the cache saves."""
        alloc = self.pages_used * self.page
        return (live_tokens / alloc) if alloc else 0.0

    def check_no_leak(self) -> None:
        """Invariant probe (tests): every non-junk page is accounted for
        exactly once across {slot-referenced, cache-pinned, free} —
        refcounts equal the number of owning slots, pages no slot or cache
        holds are all on the free list, and nothing live is free."""
        counts: Dict[int, int] = {}
        for o in self._owned:
            assert len(o) == len(set(o)), f"slot owns a page twice: {o}"
            for p in o:
                counts[p] = counts.get(p, 0) + 1
        assert 0 not in counts and 0 not in self._free \
            and 0 not in self._cached, "junk page allocated"
        for p in range(1, self.num_pages):
            assert self._ref[p] == counts.get(p, 0), (
                f"page {p}: refcount {self._ref[p]} != "
                f"{counts.get(p, 0)} owning slot(s)")
        free = set(self._free)
        assert len(free) == len(self._free), "page on the free list twice"
        live = set(counts) | self._cached
        assert not (free & live), f"live pages on the free list: {free & live}"
        assert sorted(free | live) == list(range(1, self.num_pages)), (
            f"leaked pages: referenced={sorted(counts)} "
            f"cached={sorted(self._cached)} free={sorted(free)}")
