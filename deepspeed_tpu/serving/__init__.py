"""Continuous-batching serving layer (Orca-style iteration-level
scheduling over a slot-based KV cache; the role DeepSpeed ships as
MII / DeepSpeed-FastGen's dynamic batching on top of the reference
inference engine).

- :mod:`deepspeed_tpu.serving.scheduler` — request queue + iteration-level
  scheduler: finished sequences free their slot immediately; queued
  requests are admitted mid-flight.
- :mod:`deepspeed_tpu.serving.engine` — :class:`ServingEngine`: a fixed
  pool of KV-cache slots decoding in lock-step with PER-ROW positions
  (every slot at its own depth), chunked per-slot prefill interleaved with
  decode so decode latency stays bounded, and an active-slot mask so the
  compiled step keeps a static shape while occupancy varies.
"""

from deepspeed_tpu.serving.scheduler import (FINISHED, PREFILLING, QUEUED,
                                             RUNNING, IterationScheduler,
                                             Request)
from deepspeed_tpu.serving.engine import ServingEngine

__all__ = ["Request", "IterationScheduler", "ServingEngine",
           "QUEUED", "PREFILLING", "RUNNING", "FINISHED"]
