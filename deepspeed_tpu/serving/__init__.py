"""Continuous-batching serving layer (Orca-style iteration-level
scheduling over a slot-based KV cache; the role DeepSpeed ships as
MII / DeepSpeed-FastGen's dynamic batching on top of the reference
inference engine).

- :mod:`deepspeed_tpu.serving.scheduler` — request queue + iteration-level
  scheduler: finished sequences free their slot immediately; queued
  requests are admitted mid-flight.
- :mod:`deepspeed_tpu.serving.paged_kv` — :class:`PagedKVPool`: block
  allocator over one shared pool of fixed-size KV token pages (per-slot
  page tables, alloc-on-append, free-on-finish, LIFO preempt-and-requeue
  under pool pressure) — the vLLM/PagedAttention role, on by default.
- :mod:`deepspeed_tpu.serving.engine` — :class:`ServingEngine`: KV-cache
  slots decoding in lock-step with PER-ROW positions (every slot at its
  own depth), chunked per-slot prefill interleaved with decode so decode
  latency stays bounded, an active-slot mask so the compiled step keeps a
  static shape while occupancy varies, and device-resident pos/active
  carries so neither no-EOS nor EOS workloads sync the host per step.
- :mod:`deepspeed_tpu.serving.prefix_cache` — :class:`PrefixCache`:
  copy-on-write prefix caching over the page pool (page-granular radix
  trie; shared system prompts / multi-turn histories skip prefill).
- :mod:`deepspeed_tpu.serving.router` — :class:`Router` /
  :class:`RouterServer`: the multi-replica front-end (least-loaded
  dispatch off live ``/statz`` gauges, session affinity for prefix
  locality, ``/healthz``-driven membership, drain-aware redistribution).
  jax-free; ``tools/router.py`` runs it standalone on an operator box.
"""

from deepspeed_tpu.serving.scheduler import (FINISHED, PREFILLING, QUEUED,
                                             RUNNING, IterationScheduler,
                                             QueueFull, Request)
from deepspeed_tpu.serving.host_tier import HostPageStore
from deepspeed_tpu.serving.paged_kv import PagedKVPool, init_paged_kv_cache
from deepspeed_tpu.serving.prefix_cache import PrefixCache
from deepspeed_tpu.serving.engine import ServingEngine
from deepspeed_tpu.serving.router import Router, RouterServer

__all__ = ["Request", "IterationScheduler", "QueueFull", "ServingEngine",
           "PagedKVPool", "init_paged_kv_cache", "PrefixCache",
           "HostPageStore", "Router", "RouterServer", "QUEUED",
           "PREFILLING", "RUNNING", "FINISHED"]
