"""Continuous-batching serving engine over a paged (or fixed-slot) KV cache.

The static-batch :class:`~deepspeed_tpu.inference.engine.InferenceEngine`
decodes the whole batch in lock-step on one scalar position: no request can
join or leave until the slowest row finishes, and mixed-length traffic
burns most of the batch on padding and head-of-line blocking.  This engine
is the Orca / DeepSpeed-FastGen answer, mapped onto the existing fused
Pallas decode stack:

- a KV cache shared by ``num_slots`` slots — by default a PAGED pool
  (``serving/paged_kv.py``: fixed-size token pages, per-slot page tables,
  alloc-on-append, free-on-finish, LIFO preempt-and-requeue under pool
  pressure), so HBM tracks the tokens actually live instead of reserving
  ``max_out_tokens`` per slot; ``paged_kv_cache=False`` keeps the PR 1
  contiguous per-slot layout ([L, num_slots, Hkv, Smax, Dh]);
- PER-ROW decode positions: every slot sits at its own depth, threaded
  through ``forward_with_cache`` / ``decode_step`` / the flash-decode
  kernel (which masks, DMA-clamps, and — paged — page-table-indirects per
  row);
- iteration-level scheduling: each :meth:`step` admits queued requests
  into freed slots, advances at most ``max_prefill_chunks`` prompt chunks
  (chunked per-slot prefill, interleaved with decode so decode latency
  stays bounded), then decodes ``decode_block_tokens`` tokens for every
  active slot in one compiled program;
- a traced active-slot mask: compiled shapes stay static while occupancy
  varies, so there is exactly ONE decode program regardless of how many
  slots are live.

Sync-free scheduling: the per-slot position AND active mask are
DEVICE-RESIDENT carries of the compiled decode block (EOS termination —
sampled-token-vs-eos — is folded into the compiled step), so the host
scheduler never blocks on the block it just dispatched:

- no-EOS requests: completion is pure position arithmetic; the host runs
  AHEAD of the device, blocks dispatch back-to-back, and sampled tokens
  are fetched lazily (refcounted) when a request finishes;
- EOS requests: token values gate slot turnover, but the device already
  stopped the row the step its EOS appeared — the host merely LEARNS of
  it from a DEFERRED drain: after dispatching block ``i`` it fetches
  block ``i-1``'s (tokens, valid) pair, so the fetch RTT overlaps live
  device work and the only per-request sync left is the prefill
  first-token check.  Slot frees land at most one decode block late.

Slot-reuse safety (why freed slots need no cache zeroing): a query at
position p only attends cache rows <= p, and every row <= p has been
written by the CURRENT occupant before it is first attended — prefill
writes [0, S) before the first decode, and each decode step writes its own
row before attending it.  Inactive slots are "parked": they still run in
the compiled step (static shapes) but write their junk K/V at their own
frozen position — their own rows in the fixed layout, the reserved junk
page 0 in the paged layout (a released slot's page table points there).
"""

from __future__ import annotations

import functools
import os
import threading
import time
from collections import deque
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.inference.engine import InferenceEngine, pow2_bucket
from deepspeed_tpu.models.decoding import (forward_with_cache, init_kv_cache,
                                           sample_token)
from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
from deepspeed_tpu.monitor.goodput import get_goodput_ledger
from deepspeed_tpu.monitor.health import get_health
from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.monitor.request_trace import get_request_tracer
from deepspeed_tpu.profiling.trace import annotate
from deepspeed_tpu.serving.host_tier import HostPageStore
from deepspeed_tpu.serving.paged_kv import PagedKVPool, init_paged_kv_cache
from deepspeed_tpu.serving.prefix_cache import PrefixCache
from deepspeed_tpu.serving.scheduler import (PREFILLING, QUEUED, RUNNING,
                                             IterationScheduler, QueueFull,
                                             Request)
from deepspeed_tpu.utils.logging import log_dist


class ServingEngine:
    """Continuous-batching serving over an :class:`InferenceEngine`'s
    weights (plain + kernel-injected views, dtype, mesh all reused).

    Parameters
    ----------
    model / config / params / mesh:
        As :func:`deepspeed_tpu.init_inference`; alternatively pass an
        existing ``engine=`` to share its weights.
    num_slots:
        KV-cache slots = max concurrently-decoding requests (the compiled
        batch).  Defaults to ``config.num_slots``.
    prefill_chunk:
        Max prompt tokens prefilled per scheduler iteration per slot
        (chunked prefill; bounds the decode stall a long prompt causes).
    decode_block_tokens:
        Decode steps per compiled block (per host dispatch) — the serving
        analog of ``decode_unroll``.

    Paged-KV knobs ride on the config: ``paged_kv_cache`` (default on),
    ``kv_page_tokens`` (page granularity), ``kv_pool_tokens`` (total pool
    capacity — set it below ``num_slots * max_out_tokens`` to oversubscribe
    slots against a fixed HBM budget; pool pressure preempts the
    youngest-admitted slot LIFO and requeues it at the queue head).
    """

    # HTTP /generate worker threads share the idempotent-dispatch map
    # with each other (reserve-then-fill): every _idem write holds the
    # lock; the KV-handoff work queue is single-producer-append /
    # engine-thread-popleft, GIL-atomic deque ops only (dslint DSL006,
    # docs/LINT.md)
    _dslint_shared = {"_idem": "lock:_idem_lock",
                      "_idem_order": "lock:_idem_lock",
                      "_handoffs": "atomic"}

    def __init__(self, model=None, config=None, *, engine: Optional[InferenceEngine] = None,
                 num_slots: int = 0, prefill_chunk: int = 0,
                 decode_block_tokens: int = 0, params: Any = None, mesh=None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0, registry=None,
                 health=None, role: str = "both",
                 handoff_wire: str = "int8"):
        if engine is None:
            if config is None:
                config = {}
            if not isinstance(config, DeepSpeedInferenceConfig):
                config = DeepSpeedInferenceConfig(**config)
            engine = InferenceEngine(model, config, params=params, mesh=mesh)
        elif any(a is not None for a in (model, config, params, mesh)):
            # silently preferring engine.config over a passed config would
            # discard the caller's settings with no indication
            raise ValueError(
                "pass EITHER engine= (its model/config/params/mesh are "
                "reused) OR model/config/params/mesh, not both")
        self.engine = engine
        self.module = engine.module
        self._config = engine.config
        self.num_slots = int(num_slots or self._config.num_slots)
        self.prefill_chunk = int(prefill_chunk or self._config.prefill_chunk)
        self._K = int(decode_block_tokens or self._config.decode_block_tokens
                      or max(1, self._config.decode_unroll))
        self.max_prefill_chunks = max(1, int(self._config.max_prefill_chunks))
        self._sample = (bool(do_sample), float(temperature), int(top_k),
                        float(top_p))
        # disaggregated serving role (docs/RESILIENCE.md "Disaggregated
        # serving"): "prefill" replicas answer phase-prefill requests and
        # ship KV pages, "decode" replicas adopt them; "both" (the
        # default) serves monolithically.  The role is ADVISORY — every
        # engine can serve every request shape, so a role-split fleet
        # degrades to monolithic service instead of failing.
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both|prefill|decode, got {role!r}")
        self.role = role
        if handoff_wire not in ("int8", "raw"):
            raise ValueError(
                f"handoff_wire must be int8|raw, got {handoff_wire!r}")
        self._handoff_wire = handoff_wire
        # replica-scoped observability: by default both land on the
        # process-global registry / health flag (single-replica processes,
        # the existing contract); a multi-replica host passes one
        # MetricsRegistry + HealthState PER engine so the router's /statz
        # poll and /healthz drain signal stay per-replica truths
        self._registry = registry if registry is not None else get_registry()
        self.health = health if health is not None else get_health()
        self.scheduler = IterationScheduler(
            self.num_slots, registry=self._registry,
            max_queue_depth=int(self._config.max_queue_depth),
            shed_retry_after_s=float(self._config.shed_retry_after_s))

        cfg = self.module.config
        self.paged = bool(self._config.paged_kv_cache)
        if self.paged:
            self.pool = PagedKVPool(
                self.num_slots, self._config.max_out_tokens,
                page_tokens=self._config.kv_page_tokens,
                pool_tokens=self._config.kv_pool_tokens)
            self._cache = init_paged_kv_cache(
                cfg, self.pool.num_pages, self.pool.page,
                dtype=engine.dtype,
                quantized=self._config.quantize_kv_cache)
            # per-slot LOGICAL window (page-table depth x page); the
            # PHYSICAL pool may hold fewer tokens than num_slots windows
            self.cache_len = self.pool.cache_len
        else:
            self.pool = None
            self._cache = init_kv_cache(
                cfg, self.num_slots, self._config.max_out_tokens,
                dtype=engine.dtype, quantized=self._config.quantize_kv_cache)
            # cache_len is the PHYSICAL depth (init_kv_cache rounds up to a
            # flash-decode block multiple)
            self.cache_len = int(self._cache["k"].shape[-2])
        # copy-on-write prefix caching over the page pool (a fixed-slot
        # engine has no pages to share — the knob is paged-only), with an
        # optional HOST TIER: kv_host_tier_pages > 0 bounds an LRU host
        # store that eviction victims demote into (instead of dropping)
        # and admissions promote back out of — the effective prefix cache
        # becomes host-RAM-sized (docs/OBSERVABILITY.md "KV host tier")
        if self.paged and self._config.prefix_caching:
            host_pages = int(getattr(self._config, "kv_host_tier_pages", 0))
            self.host_store = (
                HostPageStore(host_pages, registry=self._registry)
                if host_pages > 0 else None)
            self.prefix_cache = PrefixCache(
                self.pool, registry=self._registry,
                host_store=self.host_store,
                fetch_page=(self._fetch_page_host
                            if self.host_store is not None else None))
        else:
            self.host_store = None
            self.prefix_cache = None
        # max_out is the configured LOGICAL budget — generation bounds use
        # max_out so serving stays token-identical to generate(), which
        # never sees the physical rounding
        self.max_out = int(self._config.max_out_tokens)
        # Host-side SCHEDULE view of per-slot state.  pos/active mirror the
        # device-resident carries below; for EOS rows the host view is an
        # upper bound (the device may stop a row early — the host learns
        # from the deferred drain), which only ever OVER-allocates pages.
        self._pos = np.zeros(self.num_slots, np.int32)      # cache depth
        self._active = np.zeros(self.num_slots, bool)       # decoding now
        self._limit = np.zeros(self.num_slots, np.int32)    # pos decode bound
        self._eos = np.full(self.num_slots, -1, np.int32)
        self._drained_pos = np.zeros(self.num_slots, np.int32)
        # device-resident decode state: last sampled token, per-row
        # position, per-row active mask — carried (donated) block to block
        # so neither no-EOS nor EOS scheduling ever syncs per step
        self._last_dev = jnp.zeros(self.num_slots, jnp.int32)
        self._pos_dev = jnp.zeros(self.num_slots, jnp.int32)
        self._act_dev = jnp.zeros(self.num_slots, bool)
        self._wake_fn = jax.jit(
            lambda pos, act, slot, s: (pos.at[slot].set(s),
                                       act.at[slot].set(True)),
            donate_argnums=(0, 1))
        self._park_fn = jax.jit(
            lambda pos, act, slot: (pos.at[slot].set(0),
                                    act.at[slot].set(False)),
            donate_argnums=(0, 1))
        self._setpos_fn = jax.jit(lambda pos, slot, s: pos.at[slot].set(s),
                                  donate_argnums=(0,))
        self._rng = jax.random.PRNGKey(self._config.seed + 1)
        self._block_fn = None
        self._prefill_fns = {}
        self._cow_copy = None    # compiled COW page copy (prefix cache)
        self._host_write = None  # compiled host->device page write (tier)
        # background serving loop (start_loop/stop_loop): drives step()
        # so HTTP /generate handlers can block on request completion
        self._loop_thread: Optional[threading.Thread] = None
        self._loop_stop: Optional[threading.Event] = None
        # set by the loop's crash handler BEFORE health flips: /generate
        # handlers watching a request on a crashed loop hand it back for
        # router re-dispatch instead of stranding it until client timeout
        self._loop_crashed = False
        # idempotent dispatch (docs/RESILIENCE.md "Serving fleet"): a
        # router retry after an ambiguous socket death carries the same
        # idempotency_key, JOINS the original in-flight request here, and
        # cannot double-generate.  Bounded insertion-order map; entries
        # are {"req": Request|None, "ready": Event} — the reservation is
        # taken under the lock BEFORE submit so two racing duplicates
        # cannot both generate.
        self._idem = {}
        self._idem_order = deque()
        self._idem_cap = 4096
        self._idem_lock = threading.Lock()
        # cross-thread abort requests (abort()): consumed at the top of
        # step() so slot/page teardown always runs on the engine thread
        self._aborts = deque()
        # cross-thread KV-handoff work (/kv_offer, /kv_adopt HTTP
        # handlers): prefix-trie mutation and page writes must run on the
        # engine thread, so handlers enqueue {kind, payload, result,
        # done-Event} items consumed right after aborts at the top of
        # step(); the handler blocks on the Event for its answer
        self._handoffs = deque()
        # deferred token blocks: device [K, B] arrays kept un-fetched until
        # scheduling needs their values.  No-EOS requests hold refcounted
        # (idx, n) refs resolved at finish; EOS requests are drain
        # PARTICIPANTS — their share is appended when the block's
        # (toks, valid) pair is drained, one block behind dispatch.
        self._blocks = {}        # idx -> device toks [K, B]
        self._block_valid = {}   # idx -> device valid [K, B] (drain blocks)
        self._block_np = {}      # idx -> (toks np, valid np | None)
        self._block_refs = {}    # idx -> pending consumers (refs + drains)
        self._outstanding = deque()   # [(idx, [eos Request, ...])]
        self._drain_lag = 1
        self._next_block = 0
        self.steps = 0
        self.metrics_server = None   # attached by init_serving(metrics_port=)
        # /profilez: windowed capture over scheduler iterations (decode
        # blocks), claimed from the process-global broker — one attribute
        # load per step while nothing is requested
        from deepspeed_tpu.profiling.device_trace import get_profile_broker

        self._pz_broker = get_profile_broker()
        self._pz = None              # [TraceCapture, ProfileRequest, done]
        # per-request span tracing (compute-side edges/spans; the
        # scheduler owns the queue-side ones) + flight-recorder request
        # events — both disabled-by-default one-branch no-ops
        self._tracer = get_request_tracer()
        self._flight = get_flight_recorder()
        # continuous profiler (docs/OBSERVABILITY.md "Continuous
        # profiling"): scheduled low-duty-cycle capture windows over
        # scheduler iterations, sharing the /profilez decompose + registry
        # paths.  Default OFF — self._cprof stays None and the steady-state
        # cost is one attribute load + branch per iteration (PR 3 contract).
        self._cprof = None
        cpc = dict(getattr(self._config, "continuous_profiler", None) or {})
        if cpc.get("enabled"):
            from deepspeed_tpu.profiling.continuous import (
                ContinuousProfiler, ensure_registered)

            get_registry().enable()
            ensure_registered(get_registry())
            self._cprof = ContinuousProfiler(
                engine="serving",
                every_steps=int(cpc.get("every_steps", 200)),
                every_seconds=float(cpc.get("every_seconds", 120.0)),
                capture_steps=int(cpc.get("capture_steps", 2)),
                max_duty_cycle=float(cpc.get("max_duty_cycle", 0.01)),
                history_dir=cpc.get("history_dir", "profile_history"),
                max_windows=int(cpc.get("max_windows", 64)),
                max_bytes=int(cpc.get("max_bytes", 4 << 20)),
                regression_tolerance=float(
                    cpc.get("regression_tolerance", 0.25)),
                min_scope_seconds=float(
                    cpc.get("min_scope_seconds", 5e-5)),
                flight=self._flight)
            log_dist("continuous profiler armed (serving): every "
                     f"{self._cprof.every_steps} steps / "
                     f"{self._cprof.every_seconds}s, duty cycle <= "
                     f"{self._cprof.max_duty_cycle:.2%}", ranks=[0])
        # run-level goodput ledger (docs/OBSERVABILITY.md "Goodput
        # ledger"): serving shares the same process-global run clock.
        # Enabled by the DSTPU_RUNLEDGER env (serve_supervisor's channel)
        # or an ``slo``/``goodput`` block in the serving config.
        self._goodput = get_goodput_ledger()
        slo_rules = dict(getattr(self._config, "slo", None) or {})
        gp_cfg = dict(getattr(self._config, "goodput", None) or {})
        if (os.environ.get("DSTPU_RUNLEDGER") or slo_rules
                or gp_cfg.get("enabled")):
            # role-split fleets attribute prefill-side and decode-side
            # wall clock to distinct ledger roles so the run ledger's
            # per-role aggregation keeps the two pools' goodput apart
            self._goodput.enable(
                path=gp_cfg.get("path"),
                role="serve" if self.role == "both" else f"serve-{self.role}",
                min_tick_interval_s=gp_cfg.get("min_tick_interval_s"),
                slo_rules=slo_rules or None)
        # compute-side lifecycle metrics (queue-side spans live in the
        # scheduler; all are one-branch no-ops while the registry is
        # disabled — see docs/OBSERVABILITY.md for the schema)
        reg = self._registry
        self._m_ttft = reg.histogram(
            "ds_serve_ttft_seconds", "submit -> first-token dispatch")
        self._m_tpot = reg.histogram(
            "ds_serve_tpot_seconds",
            "per-output-token latency (first token -> finish)")
        self._m_prefill_s = reg.histogram(
            "ds_serve_prefill_chunk_seconds", "one chunked-prefill dispatch")
        self._m_decode_s = reg.histogram(
            "ds_serve_decode_block_seconds",
            "one compiled decode-block dispatch (host side)")
        self._m_prefill_chunks = reg.counter(
            "ds_serve_prefill_chunks_total", "prefill chunks dispatched")
        self._m_prefill_toks = reg.counter(
            "ds_serve_prefill_tokens_total", "prompt tokens prefilled")
        self._m_decode_toks = reg.counter(
            "ds_serve_decode_tokens_total", "decode tokens scheduled")
        self._m_steps = reg.counter(
            "ds_serve_steps_total", "scheduler iterations")
        self._m_compiles = reg.counter(
            "ds_serve_compiles_total",
            "serving programs compiled (prefill buckets + decode block)")
        self._m_active = reg.gauge(
            "ds_serve_active_slots", "slots decoding right now")
        self._m_occupancy = reg.histogram(
            "ds_serve_occupancy_ratio",
            "per-step occupied-slot fraction (mean = avg occupancy)",
            buckets=tuple(i / 16 for i in range(1, 17)))
        self._m_step_finished = reg.gauge(
            "ds_serve_step_finished", "requests drained by the last step")
        # graceful drain (docs/RESILIENCE.md): 1 for the whole drain()
        # window — the same signal /healthz serves as 503
        self._draining = False
        self._m_draining = reg.gauge(
            "ds_serve_draining",
            "1 while drain() runs (admission stopped, in-flight requests "
            "finishing); 0 otherwise")
        # paged-KV pool health (registered unconditionally so the metrics
        # namespace guard covers them; zero-valued on fixed-slot engines)
        self._m_pages_used = reg.gauge(
            "ds_serve_kv_pages_used", "KV pool pages allocated to slots")
        self._m_pages_free = reg.gauge(
            "ds_serve_kv_pages_free", "KV pool pages on the free list")
        self._m_preempted = reg.counter(
            "ds_serve_preempted_total",
            "requests preempted (pages reclaimed, requeued at queue head)")
        self._m_kv_util = reg.histogram(
            "ds_serve_kv_cache_util_ratio",
            "per-step live-tokens / allocated-page-tokens (paged pool)",
            buckets=tuple(i / 16 for i in range(1, 17)))
        # prefix-cache effectiveness (registered unconditionally for the
        # namespace guard; the hit/miss counters only move while a
        # PrefixCache is attached).  hit = prompt tokens whose prefill
        # was SKIPPED (served from cached pages), miss = tokens actually
        # computed — hit / (hit + miss) is the prefix hit ratio
        self._m_prefix_hit = reg.counter(
            "ds_serve_prefix_hit_tokens_total",
            "prefix tokens served from the cache (prefill skipped)")
        self._m_prefix_miss = reg.counter(
            "ds_serve_prefix_miss_tokens_total",
            "prefix tokens computed by prefill (cache miss or cache off)")
        self._m_idem_hits = reg.counter(
            "ds_serve_idem_hits_total",
            "/generate dispatches that joined an existing request via "
            "their idempotency key (router retry de-duplicated)")
        self._m_crash_requeues = reg.counter(
            "ds_serve_crash_requeued_total",
            "in-flight requests handed back (503) because the serving "
            "loop crashed under them")
        # disaggregated prefill/decode serving (docs/RESILIENCE.md):
        # handoff byte/page accounting on the SENDER (wire = what crossed
        # the socket, dense = the same pages at the engine compute
        # dtype), adoption counts on the RECEIVER, and the streaming
        # front's resume counter.  Registered unconditionally for the
        # metric-namespace guard; only a role-split fleet moves them.
        self._m_handoff_bytes = {
            dt: reg.counter(
                "ds_serve_kv_handoff_bytes_total",
                "KV handoff bytes by encoding: wire encodings (int8/raw) "
                "vs the dense twin the same pages would cost at the "
                "compute dtype", labels={"dtype": dt})
            for dt in ("int8", "raw", "dense")}
        self._m_handoff_pages = reg.counter(
            "ds_serve_kv_handoff_pages_total",
            "KV pages shipped to a decode replica (sender side)")
        self._m_adopted_pages = reg.counter(
            "ds_serve_kv_adopted_pages_total",
            "handed-off KV pages adopted into the local prefix cache "
            "(receiver side; offered-but-already-held pages not counted)")
        self._m_stream_resumes = reg.counter(
            "ds_serve_stream_resumes_total",
            "streaming /generate dispatches that entered with "
            "resume_from > 0 (router resumed a broken stream here)")
        self._m_role = reg.gauge(
            "ds_serve_role_info",
            "1 for this replica's serving role (prefill|decode|both)",
            labels={"role": self.role})
        self._m_role.set(1)
        from deepspeed_tpu.models.fused_decode import supports_fused_decode
        fused_ok = (self._config.use_fused_decode is not False
                    and supports_fused_decode(
                        cfg, quantized_kv=self._config.quantize_kv_cache,
                        tp=engine.mesh.shape.get("tp", 1)))
        if self.paged:
            layout = (f"paged pool: {self.pool.num_pages - 1} x "
                      f"{self.pool.page}-token pages, "
                      f"{self.num_slots} slots x {self.cache_len} window")
        else:
            layout = f"{self.num_slots} slots x {self.cache_len} tokens"
        log_dist(f"serving engine: {layout}, prefill_chunk="
                 f"{self.prefill_chunk}, decode_block={self._K}, "
                 f"{'fused' if fused_ok else 'unfused'} decode", ranks=[0])

    # ------------------------------------------------------------------
    def set_params(self, params: Any) -> None:
        self.engine.set_params(params)
        self._block_fn = None
        self._prefill_fns = {}

    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 128,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               traceparent: Optional[str] = None,
               stream: bool = False,
               prefill_only: bool = False) -> Request:
        """Enqueue one request; returns the live Request handle (its
        ``output_tokens`` fill in as the scheduler serves it).

        ``deadline_s`` (or the config default ``request_deadline_s``)
        sets the request's service deadline: still QUEUED past it, the
        scheduler cancels it with finish reason ``deadline`` instead of
        burning a slot on an answer nobody is waiting for.  Raises
        :class:`~deepspeed_tpu.serving.scheduler.QueueFull` when the
        bounded admission queue (``max_queue_depth``) is at its
        watermark — the overload shed the HTTP surface maps to 429."""
        if self._draining or self.scheduler.admission_paused:
            raise RuntimeError(
                "engine is draining/drained: not admitting new requests "
                "(the router should have stopped sending — /healthz is "
                "503; resume_admission() re-opens)")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size > self.max_out:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the per-slot cache "
                f"budget max_out_tokens={self.max_out}")
        if deadline_s is None:
            cfg_dl = float(self._config.request_deadline_s)
            deadline_s = cfg_dl if cfg_dl > 0 else None
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      eos_token_id=(-1 if eos_token_id is None
                                    else int(eos_token_id)),
                      stream=bool(stream), prefill_only=bool(prefill_only))
        if traceparent:
            # W3C shape "00-<32hex trace>-<16hex span>-01": the 32-hex
            # trace-id is the cross-process join key; a non-conforming
            # header is kept verbatim (still a usable correlation key)
            parts = str(traceparent).split("-")
            req.trace_id = parts[1] if len(parts) == 4 and parts[1] \
                else str(traceparent)
        if deadline_s is not None:
            req.deadline = time.perf_counter() + float(deadline_s)
        return self.scheduler.submit(req)

    # ------------------------------------------------------------------
    def step(self) -> List[Request]:
        """One scheduler iteration: admit → prefill chunk(s) → decode
        block → drain deferred finish events.  Returns the requests that
        finished during this iteration."""
        if self.engine._params is None:
            raise RuntimeError("no weights: set_params() first")
        # ledger: one scheduler iteration is a `compute` region (admit +
        # prefill + decode dispatches); time between step() calls is idle
        # (or `drain` during a drain window).  Ticks ride the same seam.
        self._goodput.push("compute")
        try:
            return self._step_inner()
        finally:
            self._goodput.pop()
            self._goodput.tick()

    def _step_inner(self) -> List[Request]:
        self._profilez_begin()
        # 0. cross-thread aborts (504'd /generate handlers): tear down on
        #    THIS thread so slot parking / page release / deferred-block
        #    unref never race a dispatch
        while self._aborts:
            self._process_abort(self._aborts.popleft())
        # 0b. KV-handoff work (/kv_offer, /kv_adopt): trie walks + page
        #     writes on THIS thread — the prefix cache is engine-thread-
        #     only by contract
        while self._handoffs:
            self._process_handoff(self._handoffs.popleft())
        done_before = len(self.scheduler.finished)
        # 1. admission: freed slots pick up the oldest queued requests;
        #    a prefix-cache hit pre-populates the slot's page table with
        #    shared pages and moves the prefill frontier past them
        with annotate("ds_serve_admit"):
            for req in self.scheduler.admit():
                self._pos[req.slot] = 0
                self._active[req.slot] = False
                self._limit[req.slot] = 0
                if self.prefix_cache is not None:
                    self._admit_prefix(req)
        # 2. chunked prefill, oldest admissions first (bounded per
        #    iteration so running slots' decode latency stays bounded)
        with annotate("ds_serve_prefill"):
            for req in self.scheduler.prefilling()[: self.max_prefill_chunks]:
                self._prefill_one_chunk(req)
        # 3. decode one block for every active slot
        if self._active.any():
            with annotate("ds_serve_decode"):
                self._decode_block()
        elif self._outstanding:
            # nothing left to dispatch: flush pending finish events so the
            # final EOS slots free and the loop can drain
            self._flush_outstanding()
        self.steps += 1
        self._m_steps.inc()
        self._m_active.set(int(self._active.sum()))
        self._m_occupancy.record(self.scheduler.num_occupied / self.num_slots)
        # cache utilization = live tokens / ALLOCATED tokens: pages actually
        # granted on the paged pool, the full per-slot reservation on the
        # fixed layout — the bench's paged-vs-fixed attribution series
        if self.paged:
            if self.pool.pages_used:
                self._m_kv_util.record(
                    self.pool.utilization(int(self._pos.sum())))
        elif self.scheduler.num_occupied:
            self._m_kv_util.record(
                int(self._pos.sum()) / (self.num_slots * self.cache_len))
        finished = self.scheduler.finished[done_before:]
        self._m_step_finished.set(len(finished))
        self._profilez_end()
        self._cprof_tick()
        return finished

    def run(self) -> List[Request]:
        """Serve to empty: iterate until queue and slots are empty; returns
        finished requests in completion order.  With admission paused (the
        state ``drain()`` leaves behind) and only queued work remaining,
        returns instead of spinning — queued requests cannot be admitted
        until :meth:`resume_admission`."""
        while self.scheduler.has_work:
            if (self.scheduler.admission_paused
                    and self.scheduler.num_occupied == 0
                    and not self._outstanding):
                break
            self.step()
        return self.scheduler.finished

    # ------------------------------------------------------------------
    # graceful drain (docs/RESILIENCE.md; the router drain signal of
    # ROADMAP item 3)
    # ------------------------------------------------------------------
    def drain(self, timeout: Optional[float] = None) -> List[Request]:
        """Stop admission and finish every in-flight request.

        For the whole drain window: ``submit()`` raises, the scheduler
        hands out no new slots, ``/healthz`` reports not-ready (503), and
        ``ds_serve_draining`` reads 1.  Already-admitted requests
        (prefilling or decoding) run to completion TOKEN-IDENTICALLY —
        the per-slot decode path is untouched, admission is the only
        thing gated.  Requests still queued (never admitted) stay in the
        queue for the caller/router to re-dispatch.

        Readiness stays ``not ready`` after the drain completes (the
        process is about to go away); call :meth:`resume_admission` to
        take traffic again.  Returns the requests that finished during
        the drain; with ``timeout`` (seconds) the loop stops early and
        returns what finished, leaving the rest in flight.

        With a background serving loop attached (:meth:`start_loop`) the
        loop keeps stepping and this call only WAITS for occupancy to
        reach zero (two threads must not both dispatch); the loop also
        drains the finished list continuously, so the return value is []
        in that mode — callers watching a loop-driven drain observe
        ``/healthz`` and their own request handles instead."""
        if self._draining:
            return []
        self._draining = True
        self.scheduler.pause_admission()
        self._m_draining.set(1)
        self.health.set_not_ready("draining")
        inflight = self.scheduler.running() + self.scheduler.prefilling()
        if self._flight.enabled:
            self._flight.record("serve_drain_start",
                                occupied=self.scheduler.num_occupied,
                                queued=self.scheduler.num_queued,
                                rids=[r.request_id for r in inflight][:32])
        done_before = len(self.scheduler.finished)
        t0 = time.perf_counter()
        timed_out = False
        loop_is_stepping = self._loop_alive()
        # ledger: the drain window is its own category; step()'s nested
        # `compute` regions carve their time out, so `drain` accumulates
        # only the non-compute remainder (waiting on occupancy).
        self._goodput.push("drain")
        try:
            while self.scheduler.num_occupied > 0:
                if timeout is not None and time.perf_counter() - t0 > timeout:
                    timed_out = True
                    break
                if loop_is_stepping and not self._loop_alive():
                    # the loop thread died mid-drain (stop_loop or a
                    # crash): join so its in-flight step fully retires,
                    # then take over stepping instead of sleeping forever
                    if self._loop_thread is not None:
                        self._loop_thread.join(timeout=30)
                    loop_is_stepping = False
                    if self._loop_crashed:
                        # drain racing a KILL: the loop crashed under the
                        # drain — stepping a crashed engine would only
                        # re-raise, and the in-flight requests are being
                        # handed back (503) to the router by their own
                        # /generate handlers.  Return what finished; the
                        # replica is dead, not draining.
                        timed_out = True
                        break
                if loop_is_stepping:
                    time.sleep(0.002)     # the loop thread dispatches
                else:
                    self.step()
        finally:
            self._goodput.pop()
            self._m_draining.set(0)
            self._draining = False
            finished = self.scheduler.finished[done_before:]
            if self._flight.enabled:
                self._flight.record(
                    "serve_drain_done", finished=len(finished),
                    timed_out=timed_out,
                    queued=self.scheduler.num_queued,
                    seconds=time.perf_counter() - t0,
                    rids=[r.request_id for r in finished][:32])
            log_dist(f"serving drain: {len(finished)} request(s) finished"
                     + (", TIMED OUT with slots still occupied"
                        if timed_out else "")
                     + f"; {self.scheduler.num_queued} left queued "
                     f"(admission stays paused; /healthz not-ready)",
                     ranks=[0])
        return finished

    def resume_admission(self) -> None:
        """Undo :meth:`drain`: admission resumes and ``/healthz`` reports
        ready again (a drained-but-not-terminated replica rejoining the
        router pool)."""
        self.scheduler.resume_admission()
        self.health.set_ready()

    @property
    def draining(self) -> bool:
        return self._draining

    # ------------------------------------------------------------------
    # background serving loop + HTTP /generate handler (the replica side
    # of serving/router.py — docs/OBSERVABILITY.md "Router")
    # ------------------------------------------------------------------
    def _loop_alive(self) -> bool:
        return self._loop_thread is not None and self._loop_thread.is_alive()

    def start_loop(self, idle_sleep: float = 0.002) -> "ServingEngine":
        """Drive :meth:`step` on a daemon thread so requests submitted
        from other threads (the ``POST /generate`` HTTP handler) make
        progress without a caller-owned serving loop.  The loop drains
        ``scheduler.finished`` every iteration (long-lived processes must
        not accumulate history); handlers keep their own Request
        references.  Idempotent; :meth:`stop_loop` stops it."""
        if self._loop_alive():
            return self
        self._loop_crashed = False       # a restart clears the crash latch
        stop = self._loop_stop = threading.Event()

        def loop():
            try:
                while not stop.is_set():
                    idle = True
                    # KV handoffs must progress on an IDLE replica too —
                    # a decode replica with no live requests still
                    # answers /kv_offer + /kv_adopt (the handler blocks
                    # on this drain; without it every handoff to a quiet
                    # replica stalls to the enqueue timeout)
                    while self._handoffs:
                        self._process_handoff(self._handoffs.popleft())
                        idle = False
                    if self.scheduler.has_work and not (
                            self.scheduler.admission_paused
                            and self.scheduler.num_occupied == 0
                            and not self._outstanding):
                        self.step()
                        self.scheduler.drain_finished()
                        idle = False
                    if idle:
                        time.sleep(idle_sleep)
            except Exception as exc:    # noqa: BLE001 - must not die silent
                # a crashed loop is a DEAD replica, not a busy one: flip
                # readiness so the router stops sending (a 200 /healthz
                # over a thread that no longer steps would strand every
                # dispatch in the requeue-grace path forever).  The crash
                # flag goes first: /generate handlers watching admitted
                # requests hand them back (503 requeue) the moment they
                # see it — a dead loop must not strand in-flight work
                # until client timeout (chaos-harness class)
                self._loop_crashed = True
                self.health.set_not_ready(f"serving loop crashed: {exc!r}")
                log_dist(f"serving loop crashed (replica marked not-ready;"
                         f" /healthz 503): {exc!r}", ranks=[0])
                raise

        self._loop_thread = threading.Thread(
            target=loop, name="ds-serving-loop", daemon=True)
        self._loop_thread.start()
        return self

    def stop_loop(self, timeout: float = 30.0) -> None:
        if self._loop_stop is not None:
            self._loop_stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=timeout)
        self._loop_thread = None
        self._loop_stop = None

    def abort(self, req: Request) -> None:
        """Request teardown of an abandoned request (the ``/generate``
        handler's 504 path: the client stopped waiting, so decoding to
        ``max_new_tokens`` for nobody would burn the slot).  Safe from
        any thread — the actual cancel/release runs at the next
        :meth:`step` boundary on the engine thread."""
        self._aborts.append(req)

    def _process_abort(self, req: Request) -> None:
        """Engine-thread half of :meth:`abort`: a still-queued request is
        cancelled; an admitted one is released with reason ``cancelled``
        (its deferred token blocks are materialized first so refcounted
        blocks drop; an EOS drain participant released early is already
        skipped-and-unref'd by ``_drain_one``'s state check)."""
        if req.state == QUEUED:
            self.scheduler.cancel(req)
            return
        if (req.state in (PREFILLING, RUNNING)
                and req.slot >= 0
                and self.scheduler.request_in(req.slot) is req):
            self._materialize(req)
            self._release(req, "cancelled")

    def _http_generate(self, payload: dict):
        """``POST /generate`` handler (wired by ``init_serving(
        metrics_port=...)``): submit, block this HTTP worker until the
        request finishes, return its tokens.  Returns ``(status, body)``.

        Drain-aware redistribution: while the engine drains, ``submit``
        raises (503 — the router sends elsewhere), and a request that was
        QUEUED but never admitted when the drain hit is CANCELLED and
        503'd back so the router re-dispatches it to a healthy replica —
        zero requests are dropped on a drain.

        Overload protection: a submit shed by the bounded admission
        queue returns ``429`` with ``retry_after_s`` (the server adds the
        ``Retry-After`` header); a request whose service deadline
        (``deadline_s``) expires while queued returns ``504`` with
        ``deadline_expired`` (the router does NOT retry — the deadline
        has passed everywhere).

        Idempotent dispatch: a payload ``idempotency_key`` reserves a
        slot in the engine's bounded dedup map BEFORE submitting; a
        second dispatch with the same key (the router retrying after an
        ambiguous socket death) JOINS the original request instead of
        generating again, and a key whose request already finished
        replays its tokens — one generation per key, however many times
        the network made the router ask."""
        try:
            prompt = payload["prompt"]
            max_new = int(payload.get("max_new_tokens", 128))
            eos = payload.get("eos_token_id")
            timeout = float(payload.get("timeout", 300.0))
            deadline_s = payload.get("deadline_s")
            deadline_s = None if deadline_s is None else float(deadline_s)
            idem = payload.get("idempotency_key")
            if idem is not None and not isinstance(idem, str):
                raise ValueError("idempotency_key must be a string")
            # trace context: the router's traceparent header (injected
            # into the payload by monitor/server.py do_POST) or a
            # caller-supplied payload field
            traceparent = payload.get("traceparent")
            if traceparent is not None and not isinstance(traceparent, str):
                raise ValueError("traceparent must be a string")
            # disaggregated serving: "phase": "prefill" runs admission +
            # chunked prefill only and ships the KV pages to handoff_to;
            # "stream": true returns a chunked ndjson event stream;
            # "resume_from": N streams/returns only tokens[N:] (the
            # router already delivered the first N to the client)
            phase = payload.get("phase")
            if phase not in (None, "prefill"):
                raise ValueError(f"unknown phase {phase!r}")
            prefill_only = phase == "prefill"
            stream = bool(payload.get("stream")) and not prefill_only
            resume_from = int(payload.get("resume_from") or 0)
            if resume_from < 0:
                raise ValueError("resume_from must be >= 0")
            handoff_to = payload.get("handoff_to")
            if handoff_to is not None and not isinstance(handoff_to, str):
                raise ValueError("handoff_to must be a string URL")
        except (KeyError, TypeError, ValueError) as exc:
            return 400, {"error": f"bad /generate payload: {exc!r}"}
        if stream and resume_from:
            self._m_stream_resumes.inc()
        deadline = time.monotonic() + timeout
        # the reservation loop converges: each pass either owns the key
        # (submits exactly once) or joins an existing in-flight entry; a
        # joined entry whose owner FAILED to submit re-loops to take the
        # key over.  Bounded to keep a pathological churn from spinning.
        for _attempt in range(4):
            entry = None
            owner = True
            if idem is not None:
                with self._idem_lock:
                    entry = self._idem.get(idem)
                    if entry is None:
                        entry = {"req": None, "ready": threading.Event()}
                        self._idem[idem] = entry
                        # the order deque holds (key, entry) so cap
                        # eviction can verify IDENTITY: a key that was
                        # dropped and re-reserved appears twice, and
                        # popping the stale first occurrence must not
                        # delete the LIVE entry (that would re-enable
                        # the double-generation this map exists to stop)
                        self._idem_order.append((idem, entry))
                        while len(self._idem_order) > self._idem_cap:
                            old_key, old_entry = self._idem_order.popleft()
                            if self._idem.get(old_key) is old_entry:
                                del self._idem[old_key]
                    else:
                        owner = False
            if not owner:
                self._m_idem_hits.inc()
                if not entry["ready"].wait(
                        max(0.0, deadline - time.monotonic())):
                    return 504, {"error": "timed out joining the "
                                          "in-flight idempotent request",
                                 "idempotency_key": idem}
                req = entry["req"]
                if req is None:
                    continue       # the original submit failed: take over
                if stream:
                    return 200, self._stream_request(
                        req, deadline, owns=False, idem=idem, entry=entry,
                        start=resume_from)
                return self._await_request(req, deadline, owns=False,
                                           idem=idem, entry=entry,
                                           resume_from=resume_from)
            try:
                req = self.submit(prompt, max_new_tokens=max_new,
                                  eos_token_id=eos, deadline_s=deadline_s,
                                  traceparent=traceparent, stream=stream,
                                  prefill_only=prefill_only)
            except QueueFull as exc:       # overload shed -> 429 + backoff
                self._idem_drop(idem, entry)
                return 429, {"error": str(exc), "shed": True,
                             "retry_after_s": exc.retry_after_s}
            except RuntimeError as exc:    # draining: stop-sending signal
                self._idem_drop(idem, entry)
                return 503, {"error": str(exc), "draining": True}
            except (TypeError, ValueError) as exc:
                self._idem_drop(idem, entry)
                return 400, {"error": str(exc)}
            if entry is not None:
                entry["req"] = req         # published by the event below
                entry["ready"].set()
            if stream:
                return 200, self._stream_request(
                    req, deadline, owns=True, idem=idem, entry=entry,
                    start=resume_from)
            return self._await_request(req, deadline, owns=True,
                                       idem=idem, entry=entry,
                                       resume_from=resume_from,
                                       handoff_to=handoff_to)
        return 503, {"error": "idempotency reservation kept churning "
                              "(original submits failing); try again",
                     "requeued": True}

    def _idem_drop(self, idem, entry) -> None:
        """Remove a reservation whose request failed/was torn down, and
        wake joiners (they re-loop and take the key over)."""
        if idem is None or entry is None:
            return
        with self._idem_lock:
            if self._idem.get(idem) is entry:
                del self._idem[idem]
        entry["ready"].set()

    def _await_request(self, req: Request, deadline: float, *, owns: bool,
                       idem=None, entry=None, resume_from: int = 0,
                       handoff_to=None):
        """Block one HTTP worker until ``req`` finishes; maps every
        terminal state to the router-facing status contract.  ``owns``
        is False for a joined idempotent duplicate — it must not abort a
        request another handler owns when ITS deadline passes (and it
        never re-ships a handoff the owner already performed)."""
        now = time.monotonic()
        last_steps, last_progress = self.steps, now
        while not req.done:
            now = time.monotonic()
            if self.steps != last_steps:      # SOMETHING is stepping —
                last_steps = self.steps       # background loop or a
                last_progress = now           # caller-driven step() loop
            if self._loop_crashed:
                # the serving loop DIED under this request (kill/chaos
                # class): hand it back for router re-dispatch instead of
                # stranding it until client timeout.  An admitted
                # request is aborted locally — the teardown runs when
                # the replica revives, so its pages free then.
                if req.state == QUEUED and self.scheduler.cancel(req):
                    self._m_crash_requeues.inc()
                    self._idem_drop(idem, entry)
                    return 503, {"error": "request requeued: serving "
                                          "loop crashed before admission",
                                 "requeued": True}
                if req.state in (PREFILLING, RUNNING):
                    self.abort(req)
                    self._m_crash_requeues.inc()
                    self._idem_drop(idem, entry)
                    return 503, {"error": "request requeued: serving "
                                          "loop crashed mid-request "
                                          "(aborted locally)",
                                 "requeued": True}
            # hand the request back for router re-dispatch when nothing
            # will admit it: immediately on a drain (admission paused),
            # or once no scheduler step has run for a grace second and
            # no loop thread exists — a busy caller-driven loop keeps
            # making steps and is never mistaken for a dead replica
            if req.state == QUEUED and (
                    self.scheduler.admission_paused
                    or (not self._loop_alive()
                        and now - last_progress > 1.0)):
                if self.scheduler.cancel(req):
                    self._idem_drop(idem, entry)
                    return 503, {"error": "request requeued: replica "
                                          "draining/stopped before "
                                          "admission", "requeued": True}
            if now > deadline:
                if not owns:
                    return 504, {"error": "timed out waiting on the "
                                          "in-flight idempotent request "
                                          "(not aborted: another handler "
                                          "owns it)",
                                 "request_id": req.request_id}
                # the client is gone: don't decode to max_new_tokens for
                # nobody — the engine thread tears the request down at
                # its next step boundary and the slot frees
                self.abort(req)
                return 504, {"error": "generation timed out (request "
                                      "aborted; slot reclaimed)",
                             "request_id": req.request_id}
            time.sleep(0.001)
        if req.finish_reason == "deadline":
            # expired while queued: too late everywhere — no retry
            return 504, {"error": "service deadline expired before "
                                  "admission; request cancelled",
                         "deadline_expired": True,
                         "request_id": req.request_id}
        if req.finish_reason == "cancelled":
            # torn down without an answer (abort/crash teardown): let the
            # router re-dispatch; the dropped reservation makes a retry
            # here generate fresh
            self._idem_drop(idem, entry)
            return 503, {"error": "request cancelled before completion",
                         "requeued": True, "request_id": req.request_id}
        if req.finish_reason == "prefill_done":
            # prefill-role completion: no output tokens by design — the
            # OWNER ships the captured KV pages to the decode replica
            # named by the dispatch (a joined duplicate reports success
            # without re-shipping; the transfer is idempotent anyway,
            # the decode side re-offers and takes nothing twice)
            body = {"prefill_done": True, "tokens": [],
                    "request_id": req.request_id,
                    "finish_reason": "prefill_done",
                    "prefix_hit_tokens": req.prefix_hit_tokens}
            if owns and handoff_to:
                body["handoff"] = self._ship_handoff(req, handoff_to)
            if req.trace_id:
                body["trace"] = req.trace_id
            return 200, body
        toks = [int(t) for t in req.output_tokens]
        body = {"tokens": toks[resume_from:] if resume_from else toks,
                "request_id": req.request_id,
                "finish_reason": req.finish_reason,
                "prefix_hit_tokens": req.prefix_hit_tokens}
        if resume_from:
            body["resume_from"] = int(resume_from)
            body["tokens_total"] = len(toks)
        if req.trace_id:
            body["trace"] = req.trace_id
        return 200, body

    def _stream_request(self, req: Request, deadline: float, *, owns: bool,
                        idem=None, entry=None, start: int = 0):
        """Streaming twin of :meth:`_await_request`: a generator of ndjson
        events the HTTP front relays as chunked transfer encoding.  Token
        chunks arrive as ``{"tokens": [...], "n": <cumulative>}`` the
        moment the lag-1 drain lands them in ``output_tokens`` (reading
        the list from this thread is safe: the engine thread only ever
        appends, and list reads are GIL-atomic); the terminal event is
        ``{"done": true, ...}`` with the buffered path's body fields, or
        an ``{"error": ..., "status": ...}`` event mirroring the status
        the buffered path would have returned (the transport already
        committed to 200 + chunked, so the code rides in the event — the
        router's relay turns ``requeued`` errors into a resume on another
        replica).  ``start`` is resume-from-token-N: the client already
        holds the first N tokens, so only the suffix is sent."""
        sent = max(0, int(start))
        last_steps, last_progress = self.steps, time.monotonic()
        while True:
            n = len(req.output_tokens)
            if n > sent:
                chunk = [int(t) for t in req.output_tokens[sent:n]]
                sent = n
                yield {"tokens": chunk, "n": sent}
                continue
            if req.done:
                break
            now = time.monotonic()
            if self.steps != last_steps:
                last_steps, last_progress = self.steps, now
            if self._loop_crashed:
                # same hand-back contract as _await_request: the stream
                # ends with a resumable error and the router re-dispatches
                # with resume_from = tokens already relayed
                if req.state == QUEUED and self.scheduler.cancel(req):
                    self._m_crash_requeues.inc()
                    self._idem_drop(idem, entry)
                    yield {"error": "request requeued: serving loop "
                                    "crashed before admission",
                           "requeued": True, "status": 503, "n": sent}
                    return
                if req.state in (PREFILLING, RUNNING):
                    self.abort(req)
                    self._m_crash_requeues.inc()
                    self._idem_drop(idem, entry)
                    yield {"error": "request requeued: serving loop "
                                    "crashed mid-request (aborted locally)",
                           "requeued": True, "status": 503, "n": sent}
                    return
            if req.state == QUEUED and (
                    self.scheduler.admission_paused
                    or (not self._loop_alive()
                        and now - last_progress > 1.0)):
                if self.scheduler.cancel(req):
                    self._idem_drop(idem, entry)
                    yield {"error": "request requeued: replica draining/"
                                    "stopped before admission",
                           "requeued": True, "status": 503, "n": sent}
                    return
            if now > deadline:
                if owns:
                    self.abort(req)
                yield {"error": "generation timed out"
                                + (" (request aborted; slot reclaimed)"
                                   if owns else ""),
                       "status": 504, "request_id": req.request_id,
                       "n": sent}
                return
            time.sleep(0.001)
        # the finish raced the last length check: flush the tail so the
        # stream is complete before the terminal event
        n = len(req.output_tokens)
        if n > sent:
            yield {"tokens": [int(t) for t in req.output_tokens[sent:n]],
                   "n": n}
            sent = n
        if req.finish_reason == "deadline":
            yield {"error": "service deadline expired before admission; "
                            "request cancelled",
                   "deadline_expired": True, "status": 504,
                   "request_id": req.request_id, "n": sent}
            return
        if req.finish_reason == "cancelled":
            self._idem_drop(idem, entry)
            yield {"error": "request cancelled before completion",
                   "requeued": True, "status": 503,
                   "request_id": req.request_id, "n": sent}
            return
        final = {"done": True, "request_id": req.request_id,
                 "finish_reason": req.finish_reason, "n": sent,
                 "prefix_hit_tokens": req.prefix_hit_tokens}
        if req.trace_id:
            final["trace"] = req.trace_id
        yield final

    # ------------------------------------------------------------------
    # KV-page handoff (disaggregated prefill/decode serving —
    # docs/RESILIENCE.md "Disaggregated serving")
    # ------------------------------------------------------------------
    def _capture_handoff(self, req: Request) -> None:
        """Engine-thread half of the prefill->decode handoff: read the
        request's FULL prompt pages device->host and stash (chunk tokens,
        page payload) pairs on the request — BEFORE release returns the
        pages to the pool (the payloads are host copies, so the release
        is safe).  Fixed-slot engines have no pages to ship; the decode
        side simply re-prefills (degraded mode)."""
        req.handoff = []
        if not self.paged:
            return
        page = self.pool.page
        resident = min(req.prefill_pos, req.prompt_len)
        full = resident // page
        if not full:
            return
        # ledger: handoff IO is its own category so prefill-role wall
        # clock splits into compute vs handoff in the run ledger
        self._goodput.push("handoff")
        try:
            pages = self.pool.owned(req.slot)[:full]
            for i, pid in enumerate(pages):
                toks = [int(t) for t in req.prompt[i * page:(i + 1) * page]]
                req.handoff.append((toks, self._fetch_page_host(int(pid))))
        finally:
            self._goodput.pop()

    def _ship_handoff(self, req: Request, target: str) -> dict:
        """HTTP-handler half (network IO off the engine thread): offer
        the captured chunk manifest to the decode replica at ``target``,
        ship ONLY the pages it reports missing (shared prefixes transfer
        once, fleet-wide), and account wire vs dense-twin bytes.
        Best-effort by contract: any failure returns an ``error`` field
        and the decode replica re-prefills the prompt itself (monolithic
        fallback) — a handoff can make a request faster, never wrong."""
        import json as _json
        import urllib.request

        from deepspeed_tpu.serving import handoff as hoff

        pages = req.handoff or []
        out = {"pages_offered": len(pages), "pages_shipped": 0,
               "wire_bytes": 0, "dense_bytes": 0}
        if not pages:
            return out

        def post(path, obj):
            data = _json.dumps(obj).encode()
            r = urllib.request.Request(
                target.rstrip("/") + path, data=data,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(r, timeout=30.0) as resp:
                return _json.loads(resp.read().decode())

        try:
            offer = post("/kv_offer", {"chunks": [c for c, _ in pages]})
            need = sorted(int(i) for i in offer.get("need", []))
            dense_item = np.dtype(self.engine.dtype).itemsize
            enc_pages = {}
            for i in need:
                if not 0 <= i < len(pages):
                    continue
                enc = hoff.encode_page(pages[i][1], wire=self._handoff_wire)
                enc_pages[str(i)] = enc
                out["wire_bytes"] += hoff.wire_nbytes(enc)
                out["dense_bytes"] += hoff.dense_twin_nbytes(
                    pages[i][1], dense_item)
            if enc_pages:
                adopt = post("/kv_adopt", {"chunks": [c for c, _ in pages],
                                           "pages": enc_pages})
                out["pages_shipped"] = len(enc_pages)
                out["pages_adopted"] = int(adopt.get("adopted", 0))
                self._m_handoff_pages.inc(len(enc_pages))
                self._m_handoff_bytes[self._handoff_wire].inc(
                    out["wire_bytes"])
                self._m_handoff_bytes["dense"].inc(out["dense_bytes"])
        except Exception as exc:  # noqa: BLE001 - degraded mode by contract
            out["error"] = repr(exc)
        return out

    def _http_kv_offer(self, payload: dict):
        """``POST /kv_offer`` (decode-role side): which of these chunks
        do I lack?  Engine-thread work — the trie walk touches LRU."""
        return self._enqueue_handoff("offer", payload)

    def _http_kv_adopt(self, payload: dict):
        """``POST /kv_adopt`` (decode-role side): decode + write the
        shipped pages and pin them into the local prefix trie."""
        return self._enqueue_handoff("adopt", payload)

    def _enqueue_handoff(self, kind: str, payload: dict,
                         timeout: float = 30.0):
        work = {"kind": kind, "payload": payload, "result": None,
                "done": threading.Event()}
        self._handoffs.append(work)
        if not work["done"].wait(timeout):
            return 503, {"error": f"kv_{kind} timed out waiting for the "
                                  "engine thread (serving loop running?)"}
        res = work["result"]
        if "error" in res:
            return 400, res
        return 200, res

    def _process_handoff(self, work: dict) -> None:
        """Engine-thread half of the /kv_offer and /kv_adopt handlers."""
        try:
            work["result"] = self._handoff_work(work["kind"],
                                                work["payload"])
        except Exception as exc:  # noqa: BLE001 - handler needs an answer
            work["result"] = {"error": repr(exc)}
        finally:
            work["done"].set()

    def _handoff_work(self, kind: str, payload: dict) -> dict:
        chunks = [tuple(int(t) for t in c)
                  for c in (payload.get("chunks") or [])]
        if self.prefix_cache is None:
            # no trie to adopt into: claim everything is held so the
            # sender ships nothing; decode-side admission re-prefills
            return {"need": []} if kind == "offer" else {"adopted": 0}
        if any(len(c) != self.pool.page for c in chunks):
            return {"error": "handoff chunks must be exactly "
                             f"page_tokens={self.pool.page} tokens long"}
        if kind == "offer":
            flat = np.asarray([t for c in chunks for t in c], np.int32)
            m = len(self.prefix_cache.match_nodes(flat))
            return {"need": list(range(m, len(chunks)))}
        from deepspeed_tpu.serving import handoff as hoff

        want = {k for k, v in self._cache.items() if v.ndim == 5}
        self._goodput.push("handoff")
        try:
            payloads = {}
            for key, enc in (payload.get("pages") or {}).items():
                planes = hoff.decode_page(enc)
                if set(planes) != want:
                    return {"error": "KV plane-layout mismatch between "
                                     "roles (quantize_kv_cache and the "
                                     "model config must match fleet-wide)"}
                payloads[int(key)] = {
                    k: np.ascontiguousarray(
                        np.asarray(v).astype(self._cache[k].dtype))
                    for k, v in planes.items()}

            def alloc():
                pid = self.pool.alloc_page()
                while pid is None:
                    if not self.prefix_cache.evict_lru():
                        return None
                    pid = self.pool.alloc_page()
                return pid

            adopted = self.prefix_cache.adopt_chunks(
                chunks, payloads, alloc, self._write_page)
            if adopted:
                self._m_adopted_pages.inc(adopted)
                self._m_pages_used.set(self.pool.pages_used)
                self._m_pages_free.set(self.pool.pages_free)
            return {"adopted": adopted}
        finally:
            self._goodput.pop()

    # ------------------------------------------------------------------
    # /profilez: on-demand device-true capture over scheduler iterations
    # (docs/OBSERVABILITY.md "Device truth")
    # ------------------------------------------------------------------
    def _profilez_begin(self) -> None:
        if self._pz is not None or self._pz_broker.pending is None:
            return
        if self._cprof is not None and self._cprof.active:
            # the operator wins the single global jax profiler session:
            # the abandoned continuous window simply reschedules at its
            # next cadence tick
            self._cprof.close()
        req = self._pz_broker.claim()
        if req is None:
            return
        import tempfile

        from deepspeed_tpu.profiling.trace import TraceCapture

        trace_dir = req.trace_dir or tempfile.mkdtemp(prefix="ds_profilez_")
        cap = TraceCapture(trace_dir, start_step=1, num_steps=req.steps,
                           perfetto=True)
        try:
            cap.maybe_start(1)       # the window opens before this step's
        except Exception as exc:     # dispatches (prefill + decode block)
            self._pz_broker.resolve(req, error=f"trace start failed: {exc}")
            return
        self._pz = [cap, req, 0]

    def _profilez_end(self) -> None:
        if self._pz is None:
            return
        cap, req, done = self._pz
        self._pz[2] = done = done + 1
        trace_dir = cap.after_step(done)
        if trace_dir is None:
            return
        self._pz = None
        from deepspeed_tpu.profiling import device_trace as dtr

        try:
            summary = dtr.analyze_capture(trace_dir, cap.num_steps,
                                          clock=cap.clock,
                                          trigger="profilez",
                                          engine="serving")
        except Exception as exc:
            self._pz_broker.resolve(
                req, error=f"trace post-processing failed: {exc}")
            return
        self._pz_broker.resolve(req, summary=summary)

    def _cprof_tick(self) -> None:
        """End-of-iteration hook of the continuous profiler: close a
        finished window (decompose + history commit run inline here,
        between scheduler iterations), else open the next one when due —
        a window opened now covers the NEXT iteration's dispatches.
        Never opens while an operator /profilez request is pending or
        claimed (jax has one global profiler session; the operator wins).
        One attribute load + one branch when off."""
        cp = self._cprof
        if cp is None:
            return
        if cp.active:
            cp.after_step(self.steps)
            return
        if self._pz is not None or self._pz_broker.pending is not None:
            return
        cp.maybe_begin(self.steps + 1)

    # ------------------------------------------------------------------
    # prefix caching (serving/prefix_cache.py)
    # ------------------------------------------------------------------
    def _admit_prefix(self, req: Request) -> None:
        """Match the request's prefix (prompt — plus produced tokens on a
        preempt-resume) against the cache at admission: fully-matched
        DEVICE-resident pages are ADOPTED into the slot's page table
        read-only (refcounted; the kernel's page-table indirection reads
        them with zero changes), HOST-resident chunks are PROMOTED first
        (a fresh page is allocated and the demoted payload streams back
        host->device — byte-identical KV, then re-pinned and shared), and
        ``prefill_pos`` jumps to the match frontier.  A partially-matched
        boundary page — the page the request will write its first
        computed token into — is COPY-ON-WRITTEN: a private page is
        allocated and the cached KV lands in it (one compiled device page
        copy, or a host->device write when the boundary chunk lives in
        the host tier), so the shared original is never written.  At
        least one prefix token is always left to compute (the final
        chunk's logits feed first-token sampling)."""
        prefix = req.prefix
        n = req.prefix_len
        page = self.pool.page
        nodes = self.prefix_cache.match_nodes(prefix)
        cap = n - 1
        want_full = min(len(nodes), cap // page)
        adopted = 0
        for node in nodes[:want_full]:
            pid = node.page           # read LIVE per iteration: an earlier
            if pid == -2:             # promotion's eviction pressure may
                break                 # have demoted (-1) or pruned (-2,
            if pid < 0:               # tombstone) nodes in this snapshot
                pid = self._promote_node(node)
                if pid is None:       # pool/store pressure: stop here
                    break
            self.pool.append_shared(req.slot, pid)
            adopted += 1
        matched = adopted * page
        r = cap - matched if (adopted == want_full
                              and want_full < len(nodes)) else 0
        if r:
            # boundary-page COW: allocate the private copy now (under
            # light pressure, evict/demote LRU cached pages; if the pool
            # still has nothing, fall back to the page-aligned frontier
            # and recompute the boundary page instead of preempting
            # anyone at admission time)
            boundary = nodes[want_full]
            ok = True
            while not self.pool.ensure(req.slot, matched + 1):
                if not self.prefix_cache.evict_lru():
                    ok = False
                    break
            if ok:
                dst = int(self.pool.page_table[req.slot, adopted])
                if boundary.page >= 0:
                    # even if the eviction loop above just unpinned the
                    # source and handed it back as ``dst``, the copy stays
                    # correct: a freed page's KV is intact until
                    # reallocated, and dst==src copies in place.  (With a
                    # host tier the same race instead demotes the
                    # boundary, which the branch below serves.)
                    self._cache = self._cow_fn()(
                        self._cache, jnp.asarray(dst, jnp.int32),
                        jnp.asarray(boundary.page, jnp.int32))
                    matched += r
                else:
                    payload = self.prefix_cache.host_payload(boundary)
                    if payload is not None:
                        # boundary lives in the host tier: stream it into
                        # the slot's PRIVATE page (the node itself stays
                        # host-resident for future matches)
                        self._write_page(dst, payload)
                        self.host_store.m_promote.inc()
                        matched += r
        if matched <= 0:          # nothing usable survived the pressure
            self._m_prefix_miss.inc(n)
            return
        req.prefill_pos = matched
        req.prefix_hit_tokens += matched
        self._m_prefix_hit.inc(matched)
        self._m_prefix_miss.inc(n - matched)
        # mirror the frontier onto host + device pos: the decode block's
        # parked junk write for this row must land AT the frontier (junk
        # page or the private COW page, both overwritten/never-read
        # before any query attends them) — NEVER inside a shared page
        self._pos[req.slot] = matched
        self._pos_dev = self._setpos_fn(
            self._pos_dev, jnp.asarray(req.slot, jnp.int32),
            jnp.asarray(matched, jnp.int32))
        self._m_pages_used.set(self.pool.pages_used)
        self._m_pages_free.set(self.pool.pages_free)
        self._tracer.span(req.request_id, "prefix_hit", req.t_admit,
                          req.t_admit, matched)

    def _cow_fn(self):
        """One compiled device-side page copy: every 5-dim cache array
        (K/V payloads and, quantized, their scales) copies physical page
        ``src`` over page ``dst``; scalars pass through."""
        if self._cow_copy is None:
            self._m_compiles.inc()

            @functools.partial(jax.jit, donate_argnums=(0,))
            def cow(cache, dst, src):
                return {k: (v.at[:, dst].set(v[:, src]) if v.ndim == 5
                            else v) for k, v in cache.items()}

            self._cow_copy = cow
        return self._cow_copy

    # -- KV host tier (serving/host_tier.py): demote/promote page IO ----
    def _fetch_page_host(self, page: int):
        """Device->host payload of one physical page (every 5-dim cache
        plane) — the demote reader the prefix cache calls from
        ``evict_lru`` when the host tier is attached."""
        return {k: np.asarray(v[:, page])
                for k, v in self._cache.items() if v.ndim == 5}

    def _host_write_fn(self):
        """One compiled host->device page write: the demoted payload
        (K/V planes and, quantized, their scales) lands in physical page
        ``dst``.  The payload is NOT donated — only the cache is (the
        ``_cow_fn`` pattern), so the numpy-aliased host arrays never meet
        a donated argument."""
        if self._host_write is None:
            self._m_compiles.inc()

            @functools.partial(jax.jit, donate_argnums=(0,))
            def wr(cache, dst, payload):
                return {k: (v.at[:, dst].set(payload[k]) if k in payload
                            else v) for k, v in cache.items()}

            self._host_write = wr
        return self._host_write

    def _write_page(self, dst: int, payload) -> None:
        self._cache = self._host_write_fn()(
            self._cache, jnp.asarray(dst, jnp.int32), payload)

    def _promote_node(self, node) -> Optional[int]:
        """Promote one host-resident chunk back to the device tier: pop a
        free page (demoting other LRU cached pages under pressure —
        never this one: a host node is not in the device LRU list, and
        the whole match path was just touched MRU), stream the payload
        in, and re-pin the node onto it.  None = could not promote (pool
        dry with nothing evictable, or the entry aged out of the bounded
        store) — the caller caps the match at the frontier reached."""
        payload = self.prefix_cache.host_payload(node)
        if payload is None:
            return None
        dst = self.pool.alloc_page()
        while dst is None:
            if not self.prefix_cache.evict_lru():
                return None
            if node.host_key is None or node.page != -1:
                # the eviction's demote overflowed the bounded store and
                # pushed out THIS node's entry (deterministic at
                # kv_host_tier_pages=1): the node was pruned from the
                # trie — promoting it would pin an orphan page
                return None
            dst = self.pool.alloc_page()
        self._write_page(dst, payload)
        self.prefix_cache.promote(node, dst)
        self.host_store.m_promote.inc()
        return dst

    # ------------------------------------------------------------------
    # paged-pool allocation + preemption
    # ------------------------------------------------------------------
    def _ensure_pages(self, req: Request, tokens: int) -> bool:
        """Allocate pages so ``req``'s slot covers ``tokens`` tokens.
        Under pool pressure, first drain any deferred finish events (a
        pending EOS release may free pages for free), then evict
        refcount-0 prefix-cache pages (LRU — cached history is
        reclaimed BEFORE any live request suffers), and only then preempt
        the YOUNGEST-admitted occupant (LIFO — possibly ``req`` itself,
        in which case False is returned and the caller skips this
        dispatch) and requeue it at the queue head.  The oldest request
        always keeps its pages, so progress is guaranteed and the pool
        cannot livelock."""
        while not self.pool.ensure(req.slot, tokens):
            if self._outstanding:
                self._flush_outstanding()
                continue
            if self.prefix_cache is not None and self.prefix_cache.evict_lru():
                continue
            victim = self._youngest_victim()
            if victim is None:
                # unreachable by construction: the pool holds >= one full
                # slot window, and a lone occupant owns every page it needs
                raise RuntimeError(
                    f"KV page pool exhausted with no preemptible slot "
                    f"(slot {req.slot} needs {tokens} tokens)")
            self._preempt(victim)
            if victim is req:
                return False
        self._m_pages_used.set(self.pool.pages_used)
        self._m_pages_free.set(self.pool.pages_free)
        return True

    def _youngest_victim(self) -> Optional[Request]:
        cands = self.scheduler.running() + self.scheduler.prefilling()
        return max(cands, key=lambda r: r.t_admit, default=None)

    def _preempt(self, victim: Request) -> None:
        """Reclaim every page the victim holds and send it back to the
        queue head.  Its produced tokens are materialized first (they
        become part of the resume prefix: re-prefilling prompt + outputs
        rebuilds the identical KV state, so greedy continuations are
        token-identical across the preempt-resume cycle)."""
        self._flush_outstanding()        # retire in-flight blocks first
        if victim.state == RUNNING:
            self._materialize(victim)
        b = victim.slot
        self._active[b] = False
        self._pos[b] = 0
        self._limit[b] = 0
        self._eos[b] = -1
        self._pos_dev, self._act_dev = self._park_fn(
            self._pos_dev, self._act_dev, jnp.asarray(b, jnp.int32))
        if self.prefix_cache is not None:
            # the victim's already-computed prompt pages go into the cache
            # BEFORE release reclaims them: its requeue-front resume (and
            # anyone sharing the prompt) re-prefills through the cache, so
            # LIFO preemption costs the boundary/output tokens, not the
            # whole prompt.  Under the very pressure that triggered this
            # preempt these pages are the NEWEST LRU entries — the
            # requester evicts older history first and takes these only
            # as a last resort.
            resident = min(victim.prefill_pos, victim.prompt_len)
            full = resident // self.pool.page
            if full:
                self.prefix_cache.insert(victim.prompt,
                                         self.pool.owned(b)[:full])
        freed = self.pool.release(b)
        victim.preemptions += 1
        self.scheduler.requeue_front(victim)   # records the preempt edge
        if self._flight.enabled:
            self._flight.record("serve_preempt", rid=victim.request_id,
                                pages_freed=freed,
                                tokens_reclaimed=freed * self.pool.page,
                                trace=victim.trace_id)
        self._m_preempted.inc()
        self._m_pages_used.set(self.pool.pages_used)
        self._m_pages_free.set(self.pool.pages_free)

    # ------------------------------------------------------------------
    def _prefill_one_chunk(self, req: Request) -> None:
        if req.state != PREFILLING:      # preempted mid-iteration
            return
        t0 = time.perf_counter()
        slot, off = req.slot, req.prefill_pos
        prefix = req.prefix              # prompt (+ outputs after a resume)
        n_prefix = req.prefix_len
        c = min(self.prefill_chunk, n_prefix - off)
        if self.paged and not self._ensure_pages(req, off + c):
            return                       # self-preempted: resumes later
        cb = pow2_bucket(c, lo=8, cap=self.cache_len - off)  # pow2 bucket
        chunk = np.zeros((1, cb), np.int32)
        chunk[0, :c] = prefix[off:off + c]
        self._rng, srng = jax.random.split(self._rng)
        if self.paged:
            tok_dev, self._cache = self._prefill_fn(cb)(
                self.engine._params, self._cache,
                jnp.asarray(self.pool.page_table[slot]), jnp.asarray(chunk),
                jnp.asarray(off, jnp.int32), jnp.asarray(c - 1, jnp.int32),
                srng)
        else:
            tok_dev, self._cache = self._prefill_fn(cb)(
                self.engine._params, self._cache, jnp.asarray(chunk),
                jnp.asarray(slot, jnp.int32), jnp.asarray(off, jnp.int32),
                jnp.asarray(c - 1, jnp.int32), srng)
        req.prefill_pos += c
        t1 = time.perf_counter()
        self._tracer.span(req.request_id, "prefill_chunk", t0, t1, c)
        self._m_prefill_s.record(t1 - t0)
        self._m_prefill_chunks.inc()
        self._m_prefill_toks.inc(c)
        # parked rows write junk at their own pos; keeping pos = prefill
        # progress means the NEXT chunk overwrites that row before any
        # query attends it
        self._pos[slot] = req.prefill_pos
        if req.prefill_pos < n_prefix:
            # mirror the frontier onto the DEVICE pos carry: the decode
            # block's parked junk write for this row must land at the
            # frontier (overwritten by the next chunk), not at row 0 the
            # previous chunk already filled
            self._pos_dev = self._setpos_fn(
                self._pos_dev, jnp.asarray(slot, jnp.int32),
                jnp.asarray(req.prefill_pos, jnp.int32))
            return
        # prefix fully resident: the next token came out of the final
        # chunk's program.  Its VALUE is only fetched when scheduling
        # depends on it (EOS) — otherwise it stays on device and the
        # pipeline keeps flowing.
        tpf = time.perf_counter()
        if not req.t_first_token:        # not re-recorded on a resume
            req.t_first_token = tpf
            # dispatch-time TTFT: on the sync-free path the token VALUE is
            # still device-resident, but it exists and later work is
            # ordered behind it
            self._m_ttft.record(req.t_first_token - req.t_submit)
        if req.prefill_only:
            # prefill-role finish (disaggregated serving): the prompt KV
            # is resident — capture the full prompt pages for the
            # prefill->decode handoff and finish WITHOUT decoding.  The
            # decode replica owns sampling end to end (even token 1 is
            # produced there, from byte-identical KV), so the response
            # cannot depend on which role computed the prefix.
            self._capture_handoff(req)
            self._release(req, "prefill_done")
            return
        # prefix resident + first token dispatched: the request's decode
        # phase begins here (re-entered after a preempt-resume re-prefill)
        self._tracer.decode_start(req.request_id, tpf)
        S = n_prefix
        # The position bound is ABSOLUTE, so it is invariant across
        # preempt-resume (prefix grows by exactly the tokens produced).
        # limit <= S: the cache budget is already exhausted by the prefix —
        # the prefill-sampled token is the only one left to emit.  The
        # bound is the LOGICAL max_out_tokens, not the page/block-rounded
        # physical depth, so a request emits exactly what generate() would
        req_bound = req.prompt_len + req.max_new_tokens - 1
        limit = min(req_bound, self.max_out - 1)
        req.limit_reason = "length" if limit == req_bound else "cache_budget"
        if (req.eos_token_id >= 0 or req.stream
                or len(req.output_tokens) + 1 >= req.max_new_tokens
                or limit <= S):
            # streaming requests also take the sync: the first token IS
            # the first chunk on the wire — deferring it would hold TTFT
            # hostage to the first decode block's drain
            first = int(tok_dev)         # the once-per-request EOS sync
            req.output_tokens.append(first)
            if req.eos_token_id >= 0 and first == req.eos_token_id:
                self._release(req, "eos")
                return
            if len(req.output_tokens) >= req.max_new_tokens:
                self._release(req, "length")
                return
            if limit <= S:
                self._release(req, req.limit_reason)
                return
        else:
            req.pending_blocks.append(("tok", tok_dev))
        req.state = RUNNING
        self._last_dev = self._last_dev.at[slot].set(tok_dev)
        self._pos_dev, self._act_dev = self._wake_fn(
            self._pos_dev, self._act_dev, jnp.asarray(slot, jnp.int32),
            jnp.asarray(S, jnp.int32))
        self._pos[slot] = S
        self._drained_pos[slot] = S
        self._limit[slot] = limit
        self._eos[slot] = req.eos_token_id
        self._active[slot] = True

    def _prefill_fn(self, cb: int):
        """Per-slot chunked prefill, compiled once per pow2 chunk bucket.

        Fixed layout: slice the slot's cache rows out, run the standard
        (batch-1) prefill forward at the chunk's absolute offset, write the
        slot back, and sample the next token from the last real position's
        logits — the token stays a DEVICE scalar so admission never syncs
        the host.  Paged layout: the slot's pages are GATHERED into the
        same contiguous logical view, the identical forward runs, and the
        pages scatter back (prefill is matmul-bound; the gather cost is
        one slot window per chunk, and the decode hot path never pays it).
        Pad rows in [off+c, off+cb) hold junk K/V but are only ever
        attended AFTER being overwritten by the next chunk / decode step
        (queries attend key_pos <= q_pos, and every row <= q_pos has been
        rewritten by then); junk landing past the allocated pages goes to
        the junk page."""
        if cb in self._prefill_fns:
            return self._prefill_fns[cb]
        self._m_compiles.inc()
        model = self.module
        do_sample, temperature, top_k, top_p = self._sample
        if self.paged:
            maxp, page = self.pool.slot_pages, self.pool.page

            @functools.partial(jax.jit, donate_argnums=(1,))
            def prefill(params, cache, pt_row, chunk, start, last_idx, srng):
                def gather(v):
                    g = v[:, pt_row]            # [L, maxp, Hkv, page, D]
                    L, mp, Hkv, pg, D = g.shape
                    return g.transpose(0, 2, 1, 3, 4).reshape(
                        L, 1, Hkv, mp * pg, D)

                def scatter(dst, s):
                    L, _, Hkv, _, D = s.shape
                    pages = s.reshape(L, Hkv, maxp, page, D).transpose(
                        0, 2, 1, 3, 4)
                    return dst.at[:, pt_row].set(pages)

                sub = {k: (gather(v) if v.ndim == 5 else v)
                       for k, v in cache.items()}
                logits, sub = forward_with_cache(model, params, chunk, sub,
                                                 start)
                out = {k: (scatter(cache[k], sub[k])
                           if cache[k].ndim == 5 else sub[k])
                       for k in cache}
                last = jax.lax.dynamic_index_in_dim(logits, last_idx, axis=1,
                                                    keepdims=False)
                tok = sample_token(last, srng, temperature=temperature,
                                   top_k=top_k, top_p=top_p,
                                   do_sample=do_sample)[0].astype(jnp.int32)
                return tok, out

            self._prefill_fns[cb] = prefill
            return prefill

        @functools.partial(jax.jit, donate_argnums=(1,))
        def prefill(params, cache, chunk, slot, start, last_idx, srng):
            sub = {k: (jax.lax.dynamic_slice_in_dim(v, slot, 1, axis=1)
                       if v.ndim == 5 else v) for k, v in cache.items()}
            logits, sub = forward_with_cache(model, params, chunk, sub, start)
            out = {k: (jax.lax.dynamic_update_slice_in_dim(cache[k], sub[k],
                                                           slot, axis=1)
                       if cache[k].ndim == 5 else sub[k])
                   for k in cache}
            last = jax.lax.dynamic_index_in_dim(logits, last_idx, axis=1,
                                                keepdims=False)
            tok = sample_token(last, srng, temperature=temperature,
                               top_k=top_k, top_p=top_p,
                               do_sample=do_sample)[0].astype(jnp.int32)
            return tok, out

        self._prefill_fns[cb] = prefill
        return prefill

    # ------------------------------------------------------------------
    def _decode_block(self) -> None:
        """Dispatch one compiled decode block and schedule its outputs.

        The device carries pos/active itself (EOS folded into the compiled
        step), so dispatches never wait on token values:

        - no-EOS rows: a row emits exactly min(K, limit - pos) tokens —
          the host appends a refcounted (block, n) ref and releases the
          request the moment position arithmetic says it finished (the
          deferred fetch at finish overlaps already-queued blocks);
        - EOS rows: the host registers the request as a DRAIN PARTICIPANT
          of this block and fetches the block's (toks, valid) pair only
          after the NEXT block is dispatched (lag 1) — the fetch RTT
          overlaps live device work, and the valid mask tells exactly how
          many tokens each row emitted before its EOS stopped it."""
        t0 = time.perf_counter()
        running = self.scheduler.running()
        if self.paged:
            for req in running:
                if req.state != RUNNING:     # preempted by an earlier ensure
                    continue
                b = req.slot
                n = int(min(self._K, self._limit[b] - self._pos[b]))
                if n > 0:
                    # the block writes rows [pos, pos+n); EOS rows may stop
                    # early on device — the host view only over-allocates.
                    # A False return = req itself was the youngest and
                    # self-preempted; the filter below drops it.
                    self._ensure_pages(req, int(self._pos[b]) + n)
            # a preemption above may have demoted someone mid-list
            running = [r for r in running if r.state == RUNNING]
            if not self._active.any():
                return
        args = [self._loop_params(), self._cache, self._last_dev,
                self._pos_dev, self._act_dev, jnp.asarray(self._limit),
                jnp.asarray(self._eos), self._rng]
        if self.paged:
            args.append(jnp.asarray(self.pool.page_table))
        (toks, valid, self._last_dev, self._pos_dev, self._act_dev,
         self._cache, self._rng) = self._block()(*args)
        t1 = time.perf_counter()
        self._m_decode_s.record(t1 - t0)
        idx = self._next_block
        self._next_block += 1
        refs = 0
        drainers: List[Request] = []
        for req in running:
            b = req.slot
            n = int(min(self._K, self._limit[b] - self._pos[b]))
            self._pos[b] += n
            # one span per participating row: the block's host dispatch
            # window with this request's scheduled token count
            self._tracer.span(req.request_id, "decode_block", t0, t1, n)
            self._m_decode_toks.inc(n)
            self._goodput.add_tokens(n)
            refs += 1
            if req.eos_token_id < 0 and not req.stream:
                req.pending_blocks.append((idx, n))
            else:
                # EOS rows need the drain for slot turnover; STREAMING
                # rows ride the same lag-1 drain so their tokens land in
                # output_tokens incrementally — the HTTP stream generator
                # tails the list and ships each block as it drains
                drainers.append(req)
            if self._pos[b] >= self._limit[b]:
                # stop scheduling the row; EOS rows RELEASE at their drain
                # (token values decide), no-EOS rows release below
                self._active[b] = False
        if refs:
            self._blocks[idx] = toks
            self._block_refs[idx] = refs
            if drainers:
                self._block_valid[idx] = valid
        if drainers:
            self._outstanding.append((idx, drainers))
            while len(self._outstanding) > self._drain_lag:
                self._drain_one()
        for req in running:              # finish AFTER refs registered
            if (req.eos_token_id < 0 and not req.stream
                    and not self._active[req.slot]
                    and req.state == RUNNING):
                self._materialize(req)
                self._release(req, req.limit_reason)

    # -- deferred finish-event drain -----------------------------------
    def _fetch_block(self, idx: int):
        """Device -> host fetch of one block's (toks, valid) arrays,
        memoized.  All deferred output flows through here, which is what
        the sync-free tests instrument."""
        entry = self._block_np.get(idx)
        if entry is None:
            toks = np.asarray(self._blocks[idx])  # dslint: disable=DSL002 -- THE deliberate deferred fetch: drains run >=1 block behind dispatch (lag 1), finish-fetches overlap queued blocks; pinned structurally in test_paged_kv
            valid = (np.asarray(self._block_valid[idx])  # dslint: disable=DSL002 -- same deferred-fetch seam (valid mask rides the same memoized entry)
                     if idx in self._block_valid else None)
            entry = self._block_np[idx] = (toks, valid)
        return entry

    def _unref(self, idx: int) -> None:
        self._block_refs[idx] -= 1
        if self._block_refs[idx] == 0:
            for d in (self._blocks, self._block_valid, self._block_np,
                      self._block_refs):
                d.pop(idx, None)

    def _drain_one(self) -> None:
        """Retire the oldest outstanding block: append each EOS
        participant's share (its valid prefix) and release rows whose
        finish the host could not predict."""
        idx, drainers = self._outstanding.popleft()
        t0 = time.perf_counter()
        toks, valid = self._fetch_block(idx)
        t1 = time.perf_counter()
        for req in drainers:
            b = req.slot
            if req.state != RUNNING:     # released at an earlier drain
                self._unref(idx)         # (its later blocks carry 0 tokens)
                continue
            n = int(valid[:, b].sum())   # valid is monotone within a block
            # the deferred (toks, valid) fetch this EOS participant rode —
            # memoized, so only the first drainer of a block pays the RTT
            self._tracer.span(req.request_id, "drain_fetch", t0, t1, n)
            req.output_tokens.extend(int(t) for t in toks[:n, b])
            self._drained_pos[b] += n
            self._unref(idx)
            if (n and req.eos_token_id >= 0
                    and req.output_tokens[-1] == req.eos_token_id):
                self._release(req, "eos")
            elif len(req.output_tokens) >= req.max_new_tokens:
                self._release(req, "length")
            elif self._drained_pos[b] >= self._limit[b]:
                self._release(req, req.limit_reason)

    def _flush_outstanding(self) -> None:
        while self._outstanding:
            self._drain_one()

    def _release(self, req: Request, reason: str) -> None:
        """Finish the request, park its slot at depth 0 (the parked row's
        junk writes land on row 0 / the junk page, overwritten or never
        read before any query can see them, and the slot's stale depth no
        longer inflates the flash-decode loop bound), and — paged — return
        its pages to the pool."""
        b = req.slot
        self._active[b] = False
        self._pos[b] = 0
        self._pos_dev, self._act_dev = self._park_fn(
            self._pos_dev, self._act_dev, jnp.asarray(b, jnp.int32))
        if self.paged:
            if self.prefix_cache is not None:
                # insert the request's FULL prompt pages (the pages whose
                # every row holds a prompt token — the boundary page mixes
                # in generated tokens and is not cacheable) before release
                # decrefs them; newly-inserted pages are pinned and
                # survive, already-cached chunks keep their existing page.
                # Bounded by the prefill frontier: an ABORTED mid-prefill
                # request must not cache pages it never computed (every
                # natural finish path has the whole prompt resident)
                resident = min(req.prefill_pos, req.prompt_len)
                full = resident // self.pool.page
                if full:
                    self.prefix_cache.insert(
                        req.prompt, self.pool.owned(b)[:full])
            self.pool.release(b)
            self._m_pages_used.set(self.pool.pages_used)
            self._m_pages_free.set(self.pool.pages_free)
        req.finish_reason = reason
        n = len(req.output_tokens)
        if n > 1 and req.t_first_token:
            self._m_tpot.record((time.perf_counter() - req.t_first_token)
                                / (n - 1))
        self.scheduler.finish(req)

    def _materialize(self, req: Request) -> None:
        """Fetch this request's deferred tokens (the prefill-sampled first
        token + its (block, n) refs) into output_tokens, in order.  Blocks
        are refcounted: a device block is dropped once every consumer has
        drained it.  Only no-EOS requests carry refs (EOS requests drain);
        a ref fetched here may sync on the just-dispatched block — that is
        the existing fetch-at-finish, by which time later blocks are
        already queued behind it."""
        for entry in req.pending_blocks:
            if entry[0] == "tok":                 # prefill-sampled token
                req.output_tokens.append(int(entry[1]))
                continue
            idx, n = entry
            toks, _ = self._fetch_block(idx)
            req.output_tokens.extend(int(t) for t in toks[:n, req.slot])
            self._unref(idx)
        req.pending_blocks.clear()

    def _loop_params(self):
        return (self.engine._dparams if self.engine._dparams is not None
                else self.engine._params)

    # ------------------------------------------------------------------
    def _step_fn(self):
        """One decode micro-step at per-row positions: (params, tokens
        [B, 1], cache, pos [B], page_table|None) -> (logits [B, V],
        cache)."""
        model = self.module
        if self.engine._dparams is not None:
            from deepspeed_tpu.models.fused_decode import decode_step

            def fused(params, tok, cache, pos, page_table):
                return decode_step(model.config, params, tok, cache, pos,
                                   page_table=page_table)
            return fused

        def unfused(params, tok, cache, pos, page_table):
            logits, cache = forward_with_cache(model, params, tok, cache,
                                               pos, page_table=page_table)
            return logits[:, -1], cache
        return unfused

    def _block(self):
        """ONE compiled program decoding ``decode_block_tokens`` tokens for
        all slots: lax.scan of per-row-position decode micro-steps with the
        active mask AND positions as device carries (EOS termination folded
        into the step — a row goes inactive the step its EOS is sampled,
        with no host involvement).  Parked rows keep static shapes alive at
        their frozen pos; the host reads (toks, valid) lazily."""
        if self._block_fn is not None:
            return self._block_fn
        self._m_compiles.inc()
        step_fn = self._step_fn()
        do_sample, temperature, top_k, top_p = self._sample
        K = self._K

        def body(params, cache, last, pos, active, limit, eos, rng,
                 page_table):
            def sub(carry, _):
                cache, last, pos, act, rng = carry
                valid = act & (pos < limit)
                rng, srng = jax.random.split(rng)
                logits, cache = step_fn(params, last[:, None], cache, pos,
                                        page_table)
                nxt = sample_token(logits, srng, temperature=temperature,
                                   top_k=top_k, top_p=top_p,
                                   do_sample=do_sample).astype(last.dtype)
                nxt = jnp.where(valid, nxt, last)
                hit = valid & (eos >= 0) & (nxt == eos)
                act = act & ~hit
                pos = pos + valid.astype(pos.dtype)
                return (cache, nxt, pos, act, rng), (nxt, valid)

            (cache, last, pos, act, rng), (toks, valid) = jax.lax.scan(
                sub, (cache, last, pos, active, rng), None, length=K)
            return toks, valid, last, pos, act, cache, rng

        if self.paged:
            block = jax.jit(body, donate_argnums=(1, 2, 3, 4))
        else:
            block = jax.jit(functools.partial(body, page_table=None),
                            donate_argnums=(1, 2, 3, 4))
        self._block_fn = block
        return block

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Release host-side resources: stops the background serving loop
        (if :meth:`start_loop` started one) and the attached metrics HTTP
        server (if ``init_serving(metrics_port=...)`` started one).  The
        device-side state (cache, programs) is freed by GC as usual; a
        dropped engine's server is also stopped by a GC finalizer, so
        ``close()`` is for deterministic shutdown, not a leak guard."""
        self.stop_loop()
        if self._cprof is not None:
            self._cprof.close()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None

    @property
    def config(self):
        return self._config
