"""Continuous-batching serving engine over a slot-based KV cache.

The static-batch :class:`~deepspeed_tpu.inference.engine.InferenceEngine`
decodes the whole batch in lock-step on one scalar position: no request can
join or leave until the slowest row finishes, and mixed-length traffic
burns most of the batch on padding and head-of-line blocking.  This engine
is the Orca / DeepSpeed-FastGen answer, mapped onto the existing fused
Pallas decode stack:

- a fixed pool of ``num_slots`` KV-cache slots (the batch dim of ONE
  preallocated [L, num_slots, Hkv, Smax, Dh] cache, donated through every
  jitted program so XLA updates it in place);
- PER-ROW decode positions: every slot sits at its own depth, threaded
  through ``forward_with_cache`` / ``decode_step`` / the flash-decode
  kernel (which masks and DMA-clamps per row);
- iteration-level scheduling: each :meth:`step` admits queued requests
  into freed slots, advances at most ``max_prefill_chunks`` prompt chunks
  (chunked per-slot prefill, interleaved with decode so decode latency
  stays bounded), then decodes ``decode_block_tokens`` tokens for every
  active slot in one compiled program;
- a traced active-slot mask: compiled shapes stay static while occupancy
  varies, so there is exactly ONE decode program regardless of how many
  slots are live.

Slot-reuse safety (why freed slots need no cache zeroing): a query at
position p only attends cache rows <= p, and every row <= p has been
written by the CURRENT occupant before it is first attended — prefill
writes [0, S) before the first decode, and each decode step writes its own
row before attending it.  Inactive slots are "parked": they still run in
the compiled step (static shapes) but write their junk K/V at their own
frozen position, which the next occupant's prefill/decode overwrites
before any query can see it.
"""

from __future__ import annotations

import functools
import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.inference.engine import InferenceEngine, pow2_bucket
from deepspeed_tpu.models.decoding import (forward_with_cache, init_kv_cache,
                                           sample_token)
from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.profiling.trace import annotate
from deepspeed_tpu.serving.scheduler import (RUNNING, IterationScheduler,
                                             Request)
from deepspeed_tpu.utils.logging import log_dist


class ServingEngine:
    """Continuous-batching serving over an :class:`InferenceEngine`'s
    weights (plain + kernel-injected views, dtype, mesh all reused).

    Parameters
    ----------
    model / config / params / mesh:
        As :func:`deepspeed_tpu.init_inference`; alternatively pass an
        existing ``engine=`` to share its weights.
    num_slots:
        KV-cache slots = max concurrently-decoding requests (the compiled
        batch).  Defaults to ``config.num_slots``.
    prefill_chunk:
        Max prompt tokens prefilled per scheduler iteration per slot
        (chunked prefill; bounds the decode stall a long prompt causes).
    decode_block_tokens:
        Decode steps per compiled block (per host sync) — the serving
        analog of ``decode_unroll``.
    """

    def __init__(self, model=None, config=None, *, engine: Optional[InferenceEngine] = None,
                 num_slots: int = 0, prefill_chunk: int = 0,
                 decode_block_tokens: int = 0, params: Any = None, mesh=None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0):
        if engine is None:
            if config is None:
                config = {}
            if not isinstance(config, DeepSpeedInferenceConfig):
                config = DeepSpeedInferenceConfig(**config)
            engine = InferenceEngine(model, config, params=params, mesh=mesh)
        elif any(a is not None for a in (model, config, params, mesh)):
            # silently preferring engine.config over a passed config would
            # discard the caller's settings with no indication
            raise ValueError(
                "pass EITHER engine= (its model/config/params/mesh are "
                "reused) OR model/config/params/mesh, not both")
        self.engine = engine
        self.module = engine.module
        self._config = engine.config
        self.num_slots = int(num_slots or self._config.num_slots)
        self.prefill_chunk = int(prefill_chunk or self._config.prefill_chunk)
        self._K = int(decode_block_tokens or self._config.decode_block_tokens
                      or max(1, self._config.decode_unroll))
        self.max_prefill_chunks = max(1, int(self._config.max_prefill_chunks))
        self._sample = (bool(do_sample), float(temperature), int(top_k),
                        float(top_p))
        self.scheduler = IterationScheduler(self.num_slots)

        cfg = self.module.config
        self._cache = init_kv_cache(
            cfg, self.num_slots, self._config.max_out_tokens,
            dtype=engine.dtype, quantized=self._config.quantize_kv_cache)
        # cache_len is the PHYSICAL depth (init_kv_cache rounds up to a
        # flash-decode block multiple); max_out is the configured LOGICAL
        # budget — generation bounds use max_out so serving stays
        # token-identical to generate(), which never sees the rounding
        self.cache_len = int(self._cache["k"].shape[-2])
        self.max_out = int(self._config.max_out_tokens)
        # host-owned per-slot scheduling state, passed into every compiled
        # block; the cache and the last-sampled-token vector are the only
        # device-resident state (last stays on device so the no-EOS fast
        # path never syncs per block — see _decode_block)
        self._pos = np.zeros(self.num_slots, np.int32)      # cache depth
        self._active = np.zeros(self.num_slots, bool)       # decoding now
        self._limit = np.zeros(self.num_slots, np.int32)    # pos decode bound
        self._eos = np.full(self.num_slots, -1, np.int32)
        self._last_dev = jnp.zeros(self.num_slots, jnp.int32)
        self._rng = jax.random.PRNGKey(self._config.seed + 1)
        self._block_fn = None
        self._prefill_fns = {}
        # deferred token blocks: device [K, B] arrays kept un-fetched until
        # a participating request finishes (refcounted)
        self._blocks = {}       # idx -> device toks [K, B]
        self._block_np = {}     # idx -> host copy (memoized at first fetch)
        self._block_refs = {}   # idx -> pending request references
        self._next_block = 0
        self.steps = 0
        self.metrics_server = None   # attached by init_serving(metrics_port=)
        # compute-side lifecycle metrics (queue-side spans live in the
        # scheduler; all are one-branch no-ops while the registry is
        # disabled — see docs/OBSERVABILITY.md for the schema)
        reg = get_registry()
        self._m_ttft = reg.histogram(
            "ds_serve_ttft_seconds", "submit -> first-token dispatch")
        self._m_tpot = reg.histogram(
            "ds_serve_tpot_seconds",
            "per-output-token latency (first token -> finish)")
        self._m_prefill_s = reg.histogram(
            "ds_serve_prefill_chunk_seconds", "one chunked-prefill dispatch")
        self._m_decode_s = reg.histogram(
            "ds_serve_decode_block_seconds",
            "one compiled decode-block dispatch (host side)")
        self._m_prefill_chunks = reg.counter(
            "ds_serve_prefill_chunks_total", "prefill chunks dispatched")
        self._m_prefill_toks = reg.counter(
            "ds_serve_prefill_tokens_total", "prompt tokens prefilled")
        self._m_decode_toks = reg.counter(
            "ds_serve_decode_tokens_total", "decode tokens scheduled")
        self._m_steps = reg.counter(
            "ds_serve_steps_total", "scheduler iterations")
        self._m_compiles = reg.counter(
            "ds_serve_compiles_total",
            "serving programs compiled (prefill buckets + decode block)")
        self._m_active = reg.gauge(
            "ds_serve_active_slots", "slots decoding right now")
        self._m_occupancy = reg.histogram(
            "ds_serve_occupancy_ratio",
            "per-step occupied-slot fraction (mean = avg occupancy)",
            buckets=tuple(i / 16 for i in range(1, 17)))
        self._m_step_finished = reg.gauge(
            "ds_serve_step_finished", "requests drained by the last step")
        from deepspeed_tpu.models.fused_decode import supports_fused_decode
        fused_ok = (self._config.use_fused_decode is not False
                    and supports_fused_decode(
                        cfg, quantized_kv=self._config.quantize_kv_cache,
                        tp=engine.mesh.shape.get("tp", 1)))
        log_dist(f"serving engine: {self.num_slots} slots x "
                 f"{self.cache_len} tokens, prefill_chunk="
                 f"{self.prefill_chunk}, decode_block={self._K}, "
                 f"{'fused' if fused_ok else 'unfused'} decode", ranks=[0])

    # ------------------------------------------------------------------
    def set_params(self, params: Any) -> None:
        self.engine.set_params(params)
        self._block_fn = None
        self._prefill_fns = {}

    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 128,
               eos_token_id: Optional[int] = None) -> Request:
        """Enqueue one request; returns the live Request handle (its
        ``output_tokens`` fill in as the scheduler serves it)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size > self.max_out:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the per-slot cache "
                f"budget max_out_tokens={self.max_out}")
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      eos_token_id=(-1 if eos_token_id is None
                                    else int(eos_token_id)))
        return self.scheduler.submit(req)

    # ------------------------------------------------------------------
    def step(self) -> List[Request]:
        """One scheduler iteration: admit → prefill chunk(s) → decode
        block.  Returns the requests that finished during this iteration."""
        if self.engine._params is None:
            raise RuntimeError("no weights: set_params() first")
        done_before = len(self.scheduler.finished)
        # 1. admission: freed slots pick up the oldest queued requests
        with annotate("ds_serve_admit"):
            for req in self.scheduler.admit():
                self._pos[req.slot] = 0
                self._active[req.slot] = False
                self._limit[req.slot] = 0
        # 2. chunked prefill, oldest admissions first (bounded per
        #    iteration so running slots' decode latency stays bounded)
        with annotate("ds_serve_prefill"):
            for req in self.scheduler.prefilling()[: self.max_prefill_chunks]:
                self._prefill_one_chunk(req)
        # 3. decode one block for every active slot
        if self._active.any():
            with annotate("ds_serve_decode"):
                self._decode_block()
        self.steps += 1
        self._m_steps.inc()
        self._m_active.set(int(self._active.sum()))
        self._m_occupancy.record(self.scheduler.num_occupied / self.num_slots)
        finished = self.scheduler.finished[done_before:]
        self._m_step_finished.set(len(finished))
        return finished

    def run(self) -> List[Request]:
        """Drain: iterate until queue and slots are empty; returns finished
        requests in completion order."""
        while self.scheduler.has_work:
            self.step()
        return self.scheduler.finished

    # ------------------------------------------------------------------
    def _prefill_one_chunk(self, req: Request) -> None:
        t0 = time.perf_counter()
        slot, off = req.slot, req.prefill_pos
        c = min(self.prefill_chunk, req.prompt_len - off)
        cb = pow2_bucket(c, lo=8, cap=self.cache_len - off)  # pow2 bucket
        chunk = np.zeros((1, cb), np.int32)
        chunk[0, :c] = req.prompt[off:off + c]
        self._rng, srng = jax.random.split(self._rng)
        tok_dev, self._cache = self._prefill_fn(cb)(
            self.engine._params, self._cache, jnp.asarray(chunk),
            jnp.asarray(slot, jnp.int32), jnp.asarray(off, jnp.int32),
            jnp.asarray(c - 1, jnp.int32), srng)
        req.prefill_pos += c
        self._m_prefill_s.record(time.perf_counter() - t0)
        self._m_prefill_chunks.inc()
        self._m_prefill_toks.inc(c)
        # parked rows write junk at their own pos; keeping pos = prefill
        # progress means the NEXT chunk overwrites that row before any
        # query attends it
        self._pos[slot] = req.prefill_pos
        if req.prefill_pos < req.prompt_len:
            return
        # prompt fully resident: the first generated token came out of the
        # final chunk's program.  Its VALUE is only fetched when scheduling
        # depends on it (EOS) — otherwise it stays on device and the
        # pipeline keeps flowing.
        req.t_first_token = time.perf_counter()
        # dispatch-time TTFT: on the sync-free path the token VALUE is still
        # on device, but it exists and later work is ordered behind it
        self._m_ttft.record(req.t_first_token - req.t_submit)
        S = req.prompt_len
        # limit <= S: the cache budget is already exhausted by the prompt
        # (prompt length >= max_out_tokens - 1) — the prefill-sampled token
        # is the only one this request can emit.  The bound is the LOGICAL
        # max_out_tokens, not the block-rounded physical cache depth, so a
        # request emits exactly the tokens generate() would
        req_bound = S + req.max_new_tokens - 1
        limit = min(req_bound, self.max_out - 1)
        req.limit_reason = "length" if limit == req_bound else "cache_budget"
        if req.eos_token_id >= 0 or req.max_new_tokens == 1 or limit <= S:
            first = int(tok_dev)
            req.output_tokens.append(first)
            if req.eos_token_id >= 0 and first == req.eos_token_id:
                self._release(req, "eos")
                return
            if req.max_new_tokens == 1:
                self._release(req, "length")
                return
            if limit <= S:
                self._release(req, req.limit_reason)
                return
        else:
            req.pending_blocks.append(("tok", tok_dev))
        req.state = RUNNING
        self._last_dev = self._last_dev.at[slot].set(tok_dev)
        self._pos[slot] = S
        self._limit[slot] = limit
        self._eos[slot] = req.eos_token_id
        self._active[slot] = True

    def _prefill_fn(self, cb: int):
        """Per-slot chunked prefill, compiled once per pow2 chunk bucket:
        slice the slot's cache rows out, run the standard (batch-1) prefill
        forward at the chunk's absolute offset, write the slot back, and
        sample the next token from the last real position's logits — the
        token stays a DEVICE scalar so admission never syncs the host (its
        value is only fetched when scheduling needs it: EOS requests, or
        output materialization at finish).  Pad rows in [off+c, off+cb)
        hold junk K/V but are only ever attended AFTER being overwritten by
        the next chunk / decode step (queries attend key_pos <= q_pos, and
        every row <= q_pos has been rewritten by then — same invariant as
        the engine's bucketed prefill)."""
        if cb in self._prefill_fns:
            return self._prefill_fns[cb]
        self._m_compiles.inc()
        model = self.module
        do_sample, temperature, top_k, top_p = self._sample

        @functools.partial(jax.jit, donate_argnums=(1,))
        def prefill(params, cache, chunk, slot, start, last_idx, srng):
            sub = {k: (jax.lax.dynamic_slice_in_dim(v, slot, 1, axis=1)
                       if v.ndim == 5 else v) for k, v in cache.items()}
            logits, sub = forward_with_cache(model, params, chunk, sub, start)
            out = {k: (jax.lax.dynamic_update_slice_in_dim(cache[k], sub[k],
                                                           slot, axis=1)
                       if cache[k].ndim == 5 else sub[k])
                   for k in cache}
            last = jax.lax.dynamic_index_in_dim(logits, last_idx, axis=1,
                                                keepdims=False)
            tok = sample_token(last, srng, temperature=temperature,
                               top_k=top_k, top_p=top_p,
                               do_sample=do_sample)[0].astype(jnp.int32)
            return tok, out

        self._prefill_fns[cb] = prefill
        return prefill

    # ------------------------------------------------------------------
    def _decode_block(self) -> None:
        """Dispatch one compiled decode block.

        No-EOS fast path: without EOS stops, completion is pure position
        arithmetic (a row emits exactly min(K, limit - pos) tokens), so the
        host scheduler runs AHEAD of the device — blocks are dispatched
        back-to-back with NO per-block sync, slot frees/admissions happen
        on deterministic host state, and the sampled tokens are fetched
        lazily when a request finishes (by which time later blocks are
        already queued, so the fetch RTT overlaps device work).  On a
        tunneled/remote runner this is the difference between goodput
        bounded by host RTT and goodput bounded by the chip.

        With any active EOS request, token VALUES gate scheduling, so the
        block is fetched synchronously and processed token-by-token."""
        t0 = time.perf_counter()
        running = self.scheduler.running()
        toks, valid, self._last_dev, self._cache, self._rng = self._block()(
            self._loop_params(), self._cache, self._last_dev,
            jnp.asarray(self._pos), jnp.asarray(self._active),
            jnp.asarray(self._limit), jnp.asarray(self._eos), self._rng)
        self._m_decode_s.record(time.perf_counter() - t0)
        if all(r.eos_token_id < 0 for r in running):
            idx = self._next_block
            self._next_block += 1
            refs = 0
            for req in running:
                b = req.slot
                n = int(min(self._K, self._limit[b] - self._pos[b]))
                req.pending_blocks.append((idx, n))
                refs += 1
                self._pos[b] += n
                self._m_decode_toks.inc(n)
                if self._pos[b] >= self._limit[b]:
                    self._active[b] = False
            if refs:
                self._blocks[idx] = toks
                self._block_refs[idx] = refs
            for req in running:           # finish AFTER refs registered
                if not self._active[req.slot] and req.state == RUNNING:
                    self._materialize(req)
                    self._release(req, req.limit_reason)
            return
        # synchronous path: flush any deferred output first so token order
        # is preserved, then walk the fetched block
        for req in running:
            self._materialize(req)
        toks = np.asarray(toks)    # [K, num_slots]
        valid = np.asarray(valid)
        for req in running:
            b = req.slot
            for k in range(self._K):
                if not valid[k, b]:
                    break  # valid is monotone within a block
                t = int(toks[k, b])
                req.output_tokens.append(t)
                self._pos[b] += 1
                self._m_decode_toks.inc()
                if req.eos_token_id >= 0 and t == req.eos_token_id:
                    self._release(req, "eos")
                    break
                if len(req.output_tokens) >= req.max_new_tokens:
                    self._release(req, "length")
                    break
            if req.state == RUNNING and self._pos[b] >= self._limit[b]:
                # position-limit stop (in practice the cache-budget bound:
                # a length-bound request releases in-loop at max_new)
                self._release(req, req.limit_reason)

    def _release(self, req: Request, reason: str) -> None:
        """Finish the request and park its slot at depth 0: the parked
        row's junk writes land on row 0 (overwritten by the next
        occupant's first prefill chunk before it can be attended), and —
        on the unfused path — the slot's stale depth no longer inflates
        the flash-decode block loop bound (max over q_pos) for everyone
        else."""
        self._active[req.slot] = False
        self._pos[req.slot] = 0
        req.finish_reason = reason
        n = len(req.output_tokens)
        if n > 1 and req.t_first_token:
            self._m_tpot.record((time.perf_counter() - req.t_first_token)
                                / (n - 1))
        self.scheduler.finish(req)

    def _materialize(self, req: Request) -> None:
        """Fetch this request's deferred tokens (the prefill-sampled first
        token + its share of each decode block) into output_tokens, in
        order.  Blocks are refcounted: a device block is dropped once every
        participating request has drained it."""
        for entry in req.pending_blocks:
            if entry[0] == "tok":                 # prefill-sampled token
                req.output_tokens.append(int(entry[1]))
                continue
            idx, n = entry
            arr = self._block_np.get(idx)
            if arr is None:
                arr = self._block_np[idx] = np.asarray(self._blocks[idx])
            req.output_tokens.extend(int(t) for t in arr[:n, req.slot])
            self._block_refs[idx] -= 1
            if self._block_refs[idx] == 0:
                del self._blocks[idx], self._block_np[idx], \
                    self._block_refs[idx]
        req.pending_blocks.clear()

    def _loop_params(self):
        return (self.engine._dparams if self.engine._dparams is not None
                else self.engine._params)

    # ------------------------------------------------------------------
    def _step_fn(self):
        """One decode micro-step at per-row positions: (params, tokens
        [B, 1], cache, pos [B]) -> (logits [B, V], cache)."""
        model = self.module
        if self.engine._dparams is not None:
            from deepspeed_tpu.models.fused_decode import decode_step

            def fused(params, tok, cache, pos):
                return decode_step(model.config, params, tok, cache, pos)
            return fused

        def unfused(params, tok, cache, pos):
            logits, cache = forward_with_cache(model, params, tok, cache, pos)
            return logits[:, -1], cache
        return unfused

    def _block(self):
        """ONE compiled program decoding ``decode_block_tokens`` tokens for
        all slots: lax.scan of per-row-position decode micro-steps with the
        active mask traced (static shapes at any occupancy).  Rows stop
        advancing when they hit their own EOS or position limit inside the
        block; parked rows keep static shapes alive at their frozen pos."""
        if self._block_fn is not None:
            return self._block_fn
        self._m_compiles.inc()
        step_fn = self._step_fn()
        do_sample, temperature, top_k, top_p = self._sample
        K = self._K

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def block(params, cache, last, pos, active, limit, eos, rng):
            def sub(carry, _):
                cache, last, pos, act, rng = carry
                valid = act & (pos < limit)
                rng, srng = jax.random.split(rng)
                logits, cache = step_fn(params, last[:, None], cache, pos)
                nxt = sample_token(logits, srng, temperature=temperature,
                                   top_k=top_k, top_p=top_p,
                                   do_sample=do_sample).astype(last.dtype)
                nxt = jnp.where(valid, nxt, last)
                hit = valid & (eos >= 0) & (nxt == eos)
                act = act & ~hit
                pos = pos + valid.astype(pos.dtype)
                return (cache, nxt, pos, act, rng), (nxt, valid)

            (cache, last, pos, act, rng), (toks, valid) = jax.lax.scan(
                sub, (cache, last, pos, active, rng), None, length=K)
            return toks, valid, last, cache, rng

        self._block_fn = block
        return block

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Release host-side resources: stops the attached metrics HTTP
        server (if ``init_serving(metrics_port=...)`` started one).  The
        device-side state (cache, programs) is freed by GC as usual; a
        dropped engine's server is also stopped by a GC finalizer, so
        ``close()`` is for deterministic shutdown, not a leak guard."""
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None

    @property
    def config(self):
        return self._config
