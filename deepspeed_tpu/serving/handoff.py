"""KV-page handoff codec for disaggregated prefill/decode serving.

A prefill replica finishes chunked prefill with the request's KV sitting
in its own paged pool; the decode replica needs those pages before it can
emit token 1 without re-running prefill.  This module is the WIRE FORMAT
of that transfer: page payloads (the ``{plane_name: [L, H_kv, page,
D]}`` dicts ``ServingEngine._fetch_page_host`` reads and the host tier
stores) serialized into JSON-able dicts, int8 over the wire via the
blockwise codec from ``comm/quant.py``.

Three plane encodings, chosen per plane:

- a plane that is ALREADY int8 (``quantize_kv_cache=True`` pools store
  k/v as int8 codes + fp32 scale planes) ships verbatim — the handoff is
  LOSSLESS, so decode-side outputs are bit-identical to a monolithic
  replica;
- a wide (bf16/fp32) plane under ``wire="int8"`` is blockwise-quantized
  (<= 1/254 relative error per element — the same budget every other
  int8 relay in the repo carries);
- ``wire="raw"`` ships wide planes byte-exact when the operator wants
  bit-identity on an unquantized pool and can afford the bytes.

The manifest that decides WHICH pages travel is the prefix-cache trie
key set: the offer lists page-sized token chunks, the decode side
answers with the indices it does not already hold (shared prefixes
transfer once, fleet-wide).  Every byte count the bench/metrics report
is computed here so sender and receiver agree: ``wire_nbytes`` is what
crossed the socket (pre-base64), ``dense_twin_nbytes`` what the same
page would have cost shipped dense at the engine compute dtype.

Imports ``comm.quant`` (which imports jax) — replica-side only; the
router relays handoff payloads as opaque JSON and must stay jax-free.
"""

from __future__ import annotations

import base64
from typing import Dict, List, Sequence

import numpy as np

from deepspeed_tpu.comm.quant import (DEFAULT_BLOCK, decode_blockwise_np,
                                      encode_blockwise_np)

__all__ = ["encode_page", "decode_page", "wire_nbytes",
           "dense_twin_nbytes", "page_chunks"]


def _b64(raw: bytes) -> str:
    return base64.b64encode(raw).decode("ascii")


def _unb64(s: str) -> bytes:
    return base64.b64decode(s.encode("ascii"))


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registered by jax; covers bfloat16 etc.

        return np.dtype(getattr(ml_dtypes, name))


def encode_page(payload: Dict[str, np.ndarray], wire: str = "int8",
                block: int = DEFAULT_BLOCK) -> dict:
    """One page payload -> JSON-able dict.  int8 planes (quantized pool
    codes + their fp32 scale planes ride as raw — scales are 1/page_tokens
    of the code bytes) always ship verbatim; wide planes follow ``wire``."""
    planes = {}
    for name, arr in payload.items():
        a = np.ascontiguousarray(np.asarray(arr))
        if a.dtype == np.int8 or wire == "raw" or name.endswith("_scale"):
            planes[name] = {"codec": "raw", "b": _b64(a.tobytes()),
                            "dtype": str(a.dtype),
                            "shape": [int(s) for s in a.shape],
                            "nbytes": int(a.nbytes)}
        else:
            enc = encode_blockwise_np(a, block)
            planes[name] = {"codec": "q8", "q": _b64(enc["q"]),
                            "scale": _b64(enc["scale"]),
                            "shape": [int(s) for s in enc["shape"]],
                            "block": int(enc["block"]),
                            "dtype": str(a.dtype),
                            "nbytes": len(enc["q"]) + len(enc["scale"])}
    return {"planes": planes, "wire": wire}


def decode_page(enc: dict) -> Dict[str, np.ndarray]:
    """Inverse of :func:`encode_page` -> numpy payload dict.  q8 planes
    come back fp32 in the original shape; the engine casts each plane to
    its pool storage dtype at write time."""
    out: Dict[str, np.ndarray] = {}
    for name, plane in enc["planes"].items():
        shape = tuple(plane["shape"])
        if plane["codec"] == "raw":
            out[name] = np.frombuffer(
                _unb64(plane["b"]), _np_dtype(plane["dtype"])).reshape(shape)
        else:
            out[name] = decode_blockwise_np(
                {"q": _unb64(plane["q"]), "scale": _unb64(plane["scale"]),
                 "shape": shape, "block": plane["block"]})
    return out


def wire_nbytes(enc: dict) -> int:
    """Payload bytes that crossed the socket (pre-base64 framing)."""
    return sum(int(p["nbytes"]) for p in enc["planes"].values())


def dense_twin_nbytes(payload: Dict[str, np.ndarray],
                      dense_itemsize: int) -> int:
    """What this page would cost shipped dense at the engine compute
    dtype: every k/v element at ``dense_itemsize`` bytes.  Scale planes
    have no dense twin (a dense cache does not store them)."""
    total = 0
    for name, arr in payload.items():
        if name.endswith("_scale"):
            continue
        total += int(np.asarray(arr).size) * int(dense_itemsize)
    return total


def page_chunks(tokens: Sequence[int], page: int) -> List[List[int]]:
    """The prompt's full page-sized token chunks — the handoff manifest
    (exactly the prefix-cache trie's edge labels for this prompt)."""
    toks = [int(t) for t in tokens]
    return [toks[i * page:(i + 1) * page] for i in range(len(toks) // page)]
