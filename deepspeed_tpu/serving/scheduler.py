"""Request queue + iteration-level scheduler (Orca / DeepSpeed-FastGen
dynamic-batching role).

The scheduler is pure host bookkeeping — no jax.  It owns the FIFO wait
queue and the slot table; the :class:`~deepspeed_tpu.serving.engine.
ServingEngine` drives it one *iteration* at a time (admit → prefill chunk →
decode block), so requests join and leave the running batch at token
granularity instead of batch granularity:

- a finished sequence frees its slot at the end of the iteration that
  finished it (early EOS included — no head-of-line blocking on the
  slowest row);
- a queued request is admitted the moment a slot frees, and its prompt is
  prefilled in chunks interleaved with everyone else's decode steps.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

import numpy as np

from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.monitor.request_trace import get_request_tracer

# process-global request id sequence: ids must be unique ACROSS engines in
# one process — the request tracer and flight recorder key per-request
# state/events by id, and two schedulers both starting at 0 would corrupt
# open timelines.  FIFO admission order per scheduler is preserved (ids
# are still assigned in submit order).
_REQUEST_IDS = itertools.count()

QUEUED = "queued"          # waiting for a slot
PREFILLING = "prefilling"  # owns a slot; prompt partially in the KV cache
RUNNING = "running"        # decoding
FINISHED = "finished"


class QueueFull(RuntimeError):
    """Admission-control shed: the bounded wait queue is at its
    watermark, so this submit is REFUSED instead of queued (graceful
    degradation — an unbounded queue turns overload into unbounded
    latency for everyone, docs/RESILIENCE.md "Serving fleet").  The HTTP
    surface maps it to ``429 Too Many Requests`` with a ``Retry-After``;
    the router backs off and tries another replica."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


@dataclass
class Request:
    """One generation request and its lifecycle bookkeeping."""

    prompt: np.ndarray                  # 1-D int token ids
    max_new_tokens: int
    request_id: int = -1
    eos_token_id: int = -1              # -1 = no EOS stop
    state: str = QUEUED
    slot: int = -1
    prefill_pos: int = 0                # prompt tokens already in the cache
    output_tokens: List[int] = field(default_factory=list)
    # deferred-output refs [(block_idx, n_tokens), ...]: on the no-EOS fast
    # path the engine defers fetching sampled tokens until finish; these
    # name the device token blocks (in order) this request's output spans
    pending_blocks: List = field(default_factory=list)
    t_submit: float = 0.0
    t_admit: float = 0.0                # slot assignment (queue wait ends)
    t_first_token: float = 0.0
    t_finish: float = 0.0
    # absolute service deadline (perf_counter clock; 0 = none): a request
    # still QUEUED past it is cancelled with reason "deadline" instead of
    # burning a slot on an answer nobody is waiting for
    deadline: float = 0.0
    finish_reason: str = ""             # "eos" | "length" | "cache_budget"
    # which bound produced the engine's position limit (min of request
    # budget and cache budget) — recorded WHERE the limit is computed so
    # finish attribution can't drift from the limit formula
    limit_reason: str = ""
    # paged-KV preempt-and-requeue (engine._preempt): times this request
    # lost its pages to pool pressure and went back to the queue head
    preemptions: int = 0
    # prefix-cache hits (engine._admit_prefix): prefix tokens whose
    # prefill was SKIPPED because their KV pages were adopted from the
    # cache — accumulated across admissions (a preempt-resume that
    # re-prefills through the cache adds its resume hit here too)
    prefix_hit_tokens: int = 0
    # propagated distributed-trace id (the 32-hex trace-id parsed from
    # the router's traceparent header; "" for direct submits): keys this
    # replica's tracer timeline and flight-recorder serve events to the
    # router's hop spans across the process boundary
    trace_id: str = ""
    # streaming front (disaggregated serving): a streaming request joins
    # the lag-1 drain path even without an EOS id so its tokens land in
    # ``output_tokens`` incrementally — the HTTP generator tails the list
    # and ships chunks as they appear (TTFT = first chunk on the wire)
    stream: bool = False
    # prefill-role request: finish at prefill completion (reason
    # "prefill_done") instead of decoding; the engine captures the
    # prompt's KV pages into ``handoff`` for the prefill->decode transfer
    prefill_only: bool = False
    # captured handoff: [(chunk_token_list, page_payload_dict), ...] for
    # every full prompt page, read device->host on the engine thread at
    # release time (set only for prefill_only requests)
    handoff: Optional[List] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def prefix(self) -> np.ndarray:
        """Tokens that must be cache-resident before decoding (re)starts:
        the prompt, plus — after a preempt-resume — every output token
        already produced (re-prefilling them regenerates the SAME KV state
        the slot held before preemption, so the continuation is
        token-identical under greedy decoding)."""
        if not self.output_tokens:
            return self.prompt
        return np.concatenate([self.prompt,
                               np.asarray(self.output_tokens, np.int32)])

    @property
    def prefix_len(self) -> int:
        return self.prompt_len + len(self.output_tokens)

    @property
    def done(self) -> bool:
        return self.state == FINISHED

    @property
    def latency(self) -> float:
        """Submit -> finish wall seconds (0 until finished)."""
        return (self.t_finish - self.t_submit) if self.done else 0.0


class IterationScheduler:
    """FIFO admission over a fixed pool of KV-cache slots.

    ``submit`` enqueues; ``admit`` assigns every free slot to the oldest
    queued requests (called once per engine iteration); ``finish`` frees
    the slot immediately so the next ``admit`` can reuse it.  Completion
    order is recorded in ``finished`` (drain ordering is by finish time,
    not submit time — early-EOS rows drain first).
    """

    def __init__(self, num_slots: int, registry=None,
                 max_queue_depth: int = 0,
                 shed_retry_after_s: float = 1.0):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = num_slots
        # admission control (0 = unbounded, the pre-overload-protection
        # behavior): submits past the watermark shed with QueueFull
        self.max_queue_depth = int(max_queue_depth)
        self.shed_retry_after_s = float(shed_retry_after_s)
        self._queue: Deque[Request] = deque()
        self._slots: List[Optional[Request]] = [None] * num_slots
        self.finished: List[Request] = []
        # drain support (ServingEngine.drain): while paused, admit() hands
        # out no slots — queued requests wait, occupied slots run dry
        self.admission_paused = False
        self._ids = _REQUEST_IDS
        # per-request span tracing + flight-recorder request events (both
        # disabled-by-default one-branch no-ops; the scheduler owns the
        # queue-side edges, the engine the compute-side ones)
        self._tracer = get_request_tracer()
        self._flight = get_flight_recorder()
        # lifecycle metrics (no-ops while the registry is disabled; the
        # scheduler owns the queue-side spans, the engine owns the
        # compute-side ones — see docs/OBSERVABILITY.md).  A replica-
        # scoped registry may be passed so N engines in one process keep
        # per-replica series (the router's least-loaded signal).
        reg = registry if registry is not None else get_registry()
        self._m_submitted = reg.counter(
            "ds_serve_submitted_total", "requests enqueued")
        self._m_admitted = reg.counter(
            "ds_serve_admitted_total", "requests assigned a KV slot")
        self._m_queue_wait = reg.histogram(
            "ds_serve_queue_wait_seconds", "submit -> slot admission wait")
        self._m_latency = reg.histogram(
            "ds_serve_request_latency_seconds", "submit -> finish wall time")
        self._m_queue_depth = reg.gauge(
            "ds_serve_queue_depth", "requests waiting for a slot")
        self._m_finished: Dict[str, object] = {
            r: reg.counter("ds_serve_finished_total",
                           "finished requests by reason",
                           labels={"reason": r})
            for r in ("eos", "length", "cache_budget", "cancelled",
                      "deadline", "prefill_done", "unknown")}
        self._m_shed = reg.counter(
            "ds_serve_shed_total",
            "submits refused by the bounded admission queue (429)")
        self._m_deadline = reg.counter(
            "ds_serve_deadline_expired_total",
            "queued requests cancelled past their service deadline")

    # -- admission -----------------------------------------------------
    def submit(self, req: Request) -> Request:
        if self.max_queue_depth > 0 \
                and len(self._queue) >= self.max_queue_depth:
            # shed at the watermark: refusing NOW (the caller 429s and
            # the router goes elsewhere) beats queueing work this replica
            # cannot start before everyone's latency blows out
            self._m_shed.inc()
            raise QueueFull(
                f"admission queue full ({len(self._queue)} >= "
                f"max_queue_depth={self.max_queue_depth}); shedding",
                retry_after_s=self.shed_retry_after_s)
        if req.request_id < 0:
            req.request_id = next(self._ids)
        req.state = QUEUED
        req.t_submit = time.perf_counter()
        self._queue.append(req)
        self._tracer.submit(req.request_id, req.t_submit, req.prompt_len,
                            req.max_new_tokens, trace=req.trace_id)
        self._m_submitted.inc()
        self._m_queue_depth.set(len(self._queue))
        return req

    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self._slots) if r is None]

    def pause_admission(self) -> None:
        """Stop handing out slots (drain): queued requests stay queued,
        running slots finish naturally.  Reversible via
        :meth:`resume_admission`."""
        self.admission_paused = True

    def resume_admission(self) -> None:
        self.admission_paused = False

    def expire_deadlines(self, now: Optional[float] = None) -> List[Request]:
        """Cancel every QUEUED request whose service deadline has passed
        (reason ``deadline``) — starting work nobody is still waiting for
        wastes the slot AND delays requests that can still make theirs.
        Runs at the top of every :meth:`admit`; bounded by the queue
        depth (itself bounded by ``max_queue_depth`` when shedding is
        on).  Thread-safe against concurrent HTTP-thread ``submit``/
        ``cancel``: the scan walks a GIL-atomic ``list()`` snapshot
        (iterating the live deque raises on concurrent appends), and
        each removal goes through ``deque.remove`` (raising = lost
        race, same as cancel)."""
        now = time.perf_counter() if now is None else now
        expired = [r for r in list(self._queue) if 0 < r.deadline < now]
        out = []
        for req in expired:
            try:
                self._queue.remove(req)
            except ValueError:
                continue                 # admitted/cancelled concurrently
            req.state = FINISHED
            req.finish_reason = "deadline"
            req.t_finish = now
            self._tracer.finish(req.request_id, now, "deadline", 0)
            if self._flight.enabled:
                self._flight.record("serve_deadline", rid=req.request_id,
                                    trace=req.trace_id)
            self._m_finished["deadline"].inc()
            self._m_deadline.inc()
            out.append(req)
        if out:
            self._m_queue_depth.set(len(self._queue))
        return out

    def admit(self) -> List[Request]:
        """Assign free slots to the oldest queued requests (FIFO); returns
        the newly-admitted requests, now in PREFILLING state.  Queued
        requests past their deadline are expired first — they never take
        a slot."""
        self.expire_deadlines()
        if self.admission_paused:
            return []
        admitted = []
        for slot in self.free_slots():
            try:
                req = self._queue.popleft()
            except IndexError:
                # empty — including the race where a cancel() from an
                # HTTP /generate worker removed the last queued request
                # between our emptiness check and the pop
                break
            req.slot = slot
            req.state = PREFILLING
            req.prefill_pos = 0
            req.t_admit = time.perf_counter()
            self._slots[slot] = req
            admitted.append(req)
            self._tracer.admit(req.request_id, slot, req.t_admit)
            if self._flight.enabled:
                self._flight.record("serve_admit", rid=req.request_id,
                                    slot=slot, trace=req.trace_id)
            self._m_admitted.inc()
            # queue wait is submit -> FIRST admission only: a re-admission
            # after a paged-KV preempt would otherwise record the whole
            # first run as "queue" time (that wait is the preempted_wait
            # phase, per docs/OBSERVABILITY.md)
            if req.preemptions == 0:
                self._m_queue_wait.record(req.t_admit - req.t_submit)
        if admitted:
            self._m_queue_depth.set(len(self._queue))
        return admitted

    # -- lifecycle -----------------------------------------------------
    def request_in(self, slot: int) -> Optional[Request]:
        return self._slots[slot]

    def prefilling(self) -> List[Request]:
        """Prefilling requests in ADMISSION order (request ids are
        assigned FIFO at submit) — the engine advances a bounded number of
        chunks per iteration, and slot-index order would starve
        high-index slots under churn."""
        return sorted((r for r in self._slots
                       if r is not None and r.state == PREFILLING),
                      key=lambda r: r.request_id)

    def running(self) -> List[Request]:
        return [r for r in self._slots if r is not None and r.state == RUNNING]

    def finish(self, req: Request) -> None:
        """Mark finished and free the slot NOW (iteration-level release:
        the next admit() hands this slot to the head of the queue)."""
        if req.state == FINISHED:
            return
        req.state = FINISHED
        req.t_finish = time.perf_counter()
        if req.slot >= 0 and self._slots[req.slot] is req:
            self._slots[req.slot] = None
        self.finished.append(req)
        # terminal edge: closes the request's span timeline with the SAME
        # timestamp the latency histogram records, so the per-request
        # phase partition reconciles with ds_serve_request_latency exactly
        self._tracer.finish(req.request_id, req.t_finish,
                            req.finish_reason or "unknown",
                            len(req.output_tokens))
        if self._flight.enabled:
            self._flight.record("serve_finish", rid=req.request_id,
                                reason=req.finish_reason or "unknown",
                                trace=req.trace_id)
        self._m_latency.record(req.t_finish - req.t_submit)
        # an unset/novel reason lands in the explicit "unknown" series —
        # a nonzero count there means a release path forgot to attribute,
        # which silent folding into "length" would hide
        self._m_finished.get(req.finish_reason,
                             self._m_finished["unknown"]).inc()

    def cancel(self, req: Request) -> bool:
        """Withdraw a still-QUEUED request (it never ran; no slot, no
        pages, no output).  The router's drain-redistribution path: a
        request parked in a draining replica's queue is cancelled here
        and re-dispatched to a healthy replica, so a drain drops nothing.
        Thread-safe against a concurrent ``admit``: once admit pops the
        request the ``deque.remove`` below raises and this returns False
        (the request runs where it is).  Cancelled requests close their
        trace timeline with reason ``cancelled`` and are NOT appended to
        ``finished`` (they were never served here)."""
        if req.state != QUEUED:
            return False
        try:
            self._queue.remove(req)
        except ValueError:
            return False
        req.state = FINISHED
        req.finish_reason = "cancelled"
        req.t_finish = time.perf_counter()
        self._tracer.finish(req.request_id, req.t_finish, "cancelled", 0)
        if self._flight.enabled:
            self._flight.record("serve_cancel", rid=req.request_id,
                                trace=req.trace_id)
        self._m_finished["cancelled"].inc()
        self._m_queue_depth.set(len(self._queue))
        return True

    def requeue_front(self, req: Request) -> None:
        """Preempt-and-requeue (paged KV pool pressure): the request loses
        its slot and goes back to the HEAD of the wait queue — it resumes
        (re-prefilling its prompt + produced tokens) as soon as capacity
        frees, ahead of requests that never ran.  The engine preempts the
        YOUNGEST-admitted slot, so the oldest request always keeps its
        pages and the pool cannot livelock."""
        if req.slot >= 0 and self._slots[req.slot] is req:
            self._slots[req.slot] = None
        req.slot = -1
        req.state = QUEUED
        req.prefill_pos = 0
        self._queue.appendleft(req)
        self._tracer.preempt(req.request_id, time.perf_counter())
        self._m_queue_depth.set(len(self._queue))

    def drain_finished(self) -> List[Request]:
        """Return-and-clear the finished list.  Long-lived serving loops
        MUST call this (or process the slice ``ServingEngine.step``
        returns and drain between steps): ``finished`` retains every
        completed request — prompt and output included — and grows without
        bound otherwise.  Call between engine iterations, not mid-step."""
        out = self.finished
        self.finished = []
        return out

    # -- introspection -------------------------------------------------
    @property
    def num_queued(self) -> int:
        return len(self._queue)

    @property
    def num_occupied(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def has_work(self) -> bool:
        return bool(self._queue) or self.num_occupied > 0
