"""Multi-replica serving router: least-loaded dispatch over ServingEngine
replicas (the front-end ABOVE one engine — ROADMAP item 3).

One ServingEngine serves one process's slots; millions-of-users traffic
needs N replicas and something to spread load across them.  This module
is that something, built from signals the replicas already export:

- **membership / drain** — ``GET /healthz`` per replica (200 = ready,
  503 = draining or otherwise not accepting work; unreachable = down).
  A replica that stops being ready simply stops receiving dispatches —
  ``ServingEngine.drain()`` needs no router-side coordination.
- **least-loaded dispatch** — each replica's live ``/statz`` gauges
  (``ds_serve_queue_depth``, ``ds_serve_active_slots``,
  ``ds_serve_kv_pages_used/free``) plus the router's own in-flight count
  (polls are eventually-consistent; the in-flight term keeps a burst
  between polls from piling onto one replica).  Score = requests in the
  system (queue + active + in-flight) with KV-pool pressure as the
  fractional tie-break.
- **session affinity** — a ``session`` key in the request pins follow-up
  turns to the same replica while it stays healthy (TTL-bounded), so a
  conversation's prefix-cache pages (serving/prefix_cache.py) are HIT
  instead of recomputed on a cold replica.
- **no dropped requests** — a failed dispatch (connection error, 503
  while draining, or the replica handing back a request that was still
  queued when its drain hit) is retried on another replica; the request
  is only failed back to the client after every round is exhausted.

The router dispatches ``POST /generate`` (the endpoint
``init_serving(metrics_port=...)`` attaches to the replica's metrics
server) and is itself a stdlib HTTP front-end (:class:`RouterServer`)
exposing the same ``/generate`` + ``/healthz`` + ``/statz`` shapes, so
routers can be health-checked and scraped exactly like replicas.

jax-free by construction: the metrics module is resolved through the
package only when it is already importable, else loaded by file path
(the ``tools/fleet_dump.py`` idiom) — ``tools/router.py`` runs this file
standalone on an operator box with no jax installed.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs


def _load_metrics():
    """The repo's stdlib-only metrics module: via the package when it is
    importable in this process (so the router and any in-process engines
    share ONE registry), else exec'd by file path (operator box, no
    jax)."""
    if "deepspeed_tpu" in sys.modules:
        from deepspeed_tpu.monitor import metrics

        return metrics
    mod = sys.modules.get("_ds_router_metrics")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "monitor", "metrics.py")
    spec = importlib.util.spec_from_file_location("_ds_router_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_ds_router_metrics"] = mod
    spec.loader.exec_module(mod)
    return mod


_metrics = _load_metrics()

__all__ = ["Replica", "Router", "RouterServer"]


class Replica:
    """One backend ServingEngine endpoint and the router's view of it."""

    def __init__(self, name: str, base_url: str):
        self.name = name
        self.base = base_url.rstrip("/")
        if not self.base.startswith("http"):
            self.base = "http://" + self.base
        self.ready = False
        self.reason: Optional[str] = "unpolled"
        self.queue_depth = 0.0
        self.active_slots = 0.0
        self.kv_busy = 0.0           # pages_used / (used + free), in [0, 1]
        self.inflight = 0            # router-side: dispatches awaiting reply
        self.last_poll = 0.0

    def score(self) -> float:
        """Lower = less loaded.  Whole requests in the system dominate;
        KV-pool pressure (always < 1) breaks ties between otherwise-equal
        replicas."""
        return (self.queue_depth + self.active_slots + self.inflight
                + min(self.kv_busy, 0.99))

    def snapshot(self) -> Dict[str, object]:
        return {"name": self.name, "base": self.base, "ready": self.ready,
                "reason": self.reason, "queue_depth": self.queue_depth,
                "active_slots": self.active_slots,
                "kv_busy": round(self.kv_busy, 4),
                "inflight": self.inflight, "score": round(self.score(), 4)}


class Router:
    """Least-loaded, drain-aware dispatch across N replicas.

    ``replicas`` is a list of URLs (or ``name=url`` pairs) pointing at
    replica metrics servers (``init_serving(metrics_port=...)``).
    ``dispatch`` POSTs ``/generate`` to the best ready replica and
    retries elsewhere on failure; ``refresh`` polls ``/healthz`` +
    ``/statz``; ``start()`` polls on a background thread.
    """

    def __init__(self, replicas: List[str], *, poll_interval: float = 0.25,
                 poll_timeout: float = 2.0, affinity_ttl: float = 300.0,
                 max_sessions: int = 65536, dispatch_rounds: int = 8,
                 retry_backoff: float = 0.05,
                 request_timeout: float = 300.0, registry=None):
        self.replicas: List[Replica] = []
        for i, spec in enumerate(replicas):
            name, sep, rest = spec.partition("=")
            if sep and not name.startswith("http") and "/" not in name:
                self.replicas.append(Replica(name, rest))
            else:
                self.replicas.append(Replica(f"r{i}", spec))
        if not self.replicas:
            raise ValueError("router needs at least one replica URL")
        self._by_name = {r.name: r for r in self.replicas}
        if len(self._by_name) != len(self.replicas):
            raise ValueError("duplicate replica names")
        self.poll_interval = float(poll_interval)
        self.poll_timeout = float(poll_timeout)
        self.affinity_ttl = float(affinity_ttl)
        self.max_sessions = int(max_sessions)
        self.dispatch_rounds = int(dispatch_rounds)
        self.retry_backoff = float(retry_backoff)
        self.request_timeout = float(request_timeout)
        self._affinity: Dict[str, Tuple[str, float]] = {}
        self._lock = threading.Lock()
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_stop: Optional[threading.Event] = None
        self.registry = (registry if registry is not None
                         else _metrics.get_registry())
        self._m_retries = self.registry.counter(
            "ds_router_retries_total",
            "dispatches retried on another replica (connection failure, "
            "drain 503, or drain-requeue)")
        self._m_dispatch = {
            r.name: self.registry.counter(
                "ds_router_dispatch_total",
                "requests dispatched, by replica",
                labels={"replica": r.name})
            for r in self.replicas}
        self._m_depth = {
            r.name: self.registry.gauge(
                "ds_router_replica_queue_depth",
                "last-polled ds_serve_queue_depth, by replica",
                labels={"replica": r.name})
            for r in self.replicas}

    # -- membership + load polling -------------------------------------
    def poll_one(self, rep: Replica) -> None:
        """One replica's ``/healthz`` + ``/statz`` poll; failures mark it
        not-ready (it rejoins on the next successful poll)."""
        import urllib.error
        import urllib.request

        try:
            # readiness: the status code IS the signal (503 raises)
            with urllib.request.urlopen(rep.base + "/healthz",
                                        timeout=self.poll_timeout):
                pass
            rep.ready, rep.reason = True, None
        except urllib.error.HTTPError as exc:
            body = {}
            try:
                body = json.load(exc)
            except Exception:
                pass
            rep.ready = False
            rep.reason = body.get("reason") or f"healthz {exc.code}"
        except OSError as exc:
            rep.ready, rep.reason = False, f"unreachable: {exc}"
        rep.last_poll = time.monotonic()
        if not rep.ready:
            return
        try:
            with urllib.request.urlopen(rep.base + "/statz",
                                        timeout=self.poll_timeout) as resp:
                m = json.load(resp).get("metrics", {})
        except (OSError, ValueError):
            return                       # keep the last load view
        rep.queue_depth = float(m.get("ds_serve_queue_depth") or 0)
        rep.active_slots = float(m.get("ds_serve_active_slots") or 0)
        used = float(m.get("ds_serve_kv_pages_used") or 0)
        free = float(m.get("ds_serve_kv_pages_free") or 0)
        rep.kv_busy = used / (used + free) if used + free else 0.0
        self._m_depth[rep.name].set(rep.queue_depth)

    def refresh(self) -> None:
        for rep in self.replicas:
            self.poll_one(rep)

    def start(self) -> "Router":
        """Poll membership/load on a background daemon thread."""
        if self._poll_thread is not None and self._poll_thread.is_alive():
            return self
        self.refresh()                   # synchronous first poll
        stop = self._poll_stop = threading.Event()

        def poll():
            while not stop.wait(self.poll_interval):
                self.refresh()

        self._poll_thread = threading.Thread(target=poll, daemon=True,
                                             name="ds-router-poll")
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        if self._poll_stop is not None:
            self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10)
        self._poll_thread = None
        self._poll_stop = None

    # -- dispatch ------------------------------------------------------
    def ready_replicas(self) -> List[Replica]:
        return [r for r in self.replicas if r.ready]

    def pick(self, session: Optional[str] = None,
             exclude: Tuple[str, ...] = ()) -> Optional[Replica]:
        """Session-affine when possible (prefix-cache locality), else the
        lowest-score ready replica (name as the deterministic final
        tie-break)."""
        now = time.monotonic()
        ready = [r for r in self.replicas
                 if r.ready and r.name not in exclude]
        if not ready:
            return None
        if session is not None:
            with self._lock:
                ent = self._affinity.get(session)
            if ent is not None and now - ent[1] < self.affinity_ttl:
                rep = self._by_name.get(ent[0])
                if rep is not None and rep.ready and rep.name not in exclude:
                    return rep
        return min(ready, key=lambda r: (r.score(), r.name))

    def _post(self, rep: Replica, payload: dict) -> Tuple[int, dict]:
        import urllib.error
        import urllib.request

        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            rep.base + "/generate", data=body,
            headers={"Content-Type": "application/json"})
        # the socket deadline must OUTLAST the replica's own generation
        # deadline (the payload's "timeout", which the engine honors with
        # its 504-and-abort path) — a router that times out first would
        # mistake a still-generating replica for a dead one and
        # double-generate the prompt elsewhere
        deadline = self.request_timeout
        try:
            deadline = max(deadline, float(payload.get("timeout")) + 30.0)
        except (TypeError, ValueError):
            pass
        try:
            with urllib.request.urlopen(req, timeout=deadline) as resp:
                return resp.status, json.load(resp)
        except urllib.error.HTTPError as exc:
            try:
                return exc.code, json.load(exc)
            except Exception:
                return exc.code, {"error": f"replica returned {exc.code}"}

    def dispatch(self, payload: dict) -> Tuple[int, dict]:
        """Route one ``/generate`` payload: pick → POST → retry elsewhere
        on failure.  Returns ``(status, body)``; 200 bodies carry the
        serving replica's name under ``"replica"``.  A request is only
        failed (503) after ``dispatch_rounds`` picks found no replica
        that would take it — drain-aware redistribution means a replica
        draining mid-request hands its queued-never-admitted requests
        back as 503s, and they land here for a second life elsewhere."""
        session = payload.get("session")
        last_err: Optional[dict] = None
        tried: set = set()
        for attempt in range(self.dispatch_rounds):
            rep = self.pick(session=session, exclude=tuple(tried))
            if rep is None and tried:
                # every ready replica already refused this request this
                # round; start a fresh round over re-polled membership
                tried.clear()
                rep = self.pick(session=session)
            if rep is None:
                self.refresh()
                time.sleep(self.retry_backoff * (attempt + 1))
                continue
            with self._lock:
                rep.inflight += 1
            try:
                try:
                    code, body = self._post(rep, payload)
                except OSError as exc:
                    # a TIMEOUT is not "unreachable": the replica may
                    # still be mid-generation, and re-dispatching would
                    # double-generate the prompt — surface it like the
                    # replica's own 504 (no retry); genuine connection
                    # failures fall through to retry-elsewhere
                    reason = getattr(exc, "reason", exc)
                    if isinstance(exc, TimeoutError) or isinstance(
                            reason, TimeoutError):
                        return 504, {"error": "router-side timeout; the "
                                              "replica may still be "
                                              "generating (not retried)",
                                     "replica": rep.name}
                    code, body = -1, {"error": f"unreachable: {exc}"}
            finally:
                with self._lock:
                    rep.inflight -= 1
            if code == 200:
                self._m_dispatch[rep.name].inc()
                if session is not None:
                    with self._lock:
                        self._affinity[session] = (rep.name,
                                                   time.monotonic())
                    if len(self._affinity) > self.max_sessions:
                        self._expire_affinity()
                body["replica"] = rep.name
                return 200, body
            if code == 400:
                # the payload itself is bad — no replica will differ
                return 400, body
            if code == 504:
                # the replica timed out mid-generation: re-dispatching
                # could double-generate; surface it
                body["replica"] = rep.name
                return 504, body
            # -1 (unreachable) / 503 (draining or requeued): take the
            # replica out until the next healthz poll and retry elsewhere
            rep.ready = False
            rep.reason = body.get("error") or f"generate -> {code}"
            if session is not None:
                with self._lock:
                    self._affinity.pop(session, None)
            self._m_retries.inc()
            tried.add(rep.name)
            last_err = body
        return 503, {"error": "no replica accepted the request after "
                              f"{self.dispatch_rounds} rounds",
                     "last": last_err}

    def _expire_affinity(self) -> None:
        """Enforce the session-map bound: drop TTL-expired entries, then
        — if live sessions alone exceed the cap — evict oldest-touched
        down to 7/8 of ``max_sessions``, so the scan amortizes instead of
        re-running on every over-bound dispatch while the dict grows."""
        now = time.monotonic()
        with self._lock:
            dead = [s for s, (_, t) in self._affinity.items()
                    if now - t >= self.affinity_ttl]
            for s in dead:
                del self._affinity[s]
            over = len(self._affinity) - (self.max_sessions * 7) // 8
            if over > 0:
                oldest = sorted(self._affinity.items(),
                                key=lambda kv: kv[1][1])[:over]
                for s, _ in oldest:
                    del self._affinity[s]

    def snapshot(self) -> Dict[str, object]:
        return {"replicas": [r.snapshot() for r in self.replicas],
                "ready": sum(1 for r in self.replicas if r.ready),
                "sessions": len(self._affinity)}


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------


class _RouterHandler(BaseHTTPRequestHandler):
    router: Router   # set by the server subclass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload, sort_keys=True).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802 - http.server API
        path, _, _ = self.path.partition("?")
        if path not in ("/generate", "/generate/"):
            self.send_error(404)
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as exc:
            self._send(400, {"error": f"bad JSON body: {exc}"})
            return
        code, body = self.router.dispatch(payload)
        self._send(code, body)

    def do_GET(self):  # noqa: N802 - http.server API
        path, _, query = self.path.partition("?")
        if path in ("/healthz", "/healthz/"):
            # the router is ready while ANY replica is (same 200/503
            # shape as a replica's /healthz, so routers stack/chain)
            snap = self.router.snapshot()
            ready = snap["ready"] > 0
            self._send(200 if ready else 503,
                       {"ready": ready, "replicas": snap["replicas"]})
        elif path in ("/replicaz", "/replicaz/"):
            self._send(200, self.router.snapshot())
        elif path in ("/statz", "/statz/"):
            qs = parse_qs(query)
            reg = self.router.registry
            payload = {"enabled": reg.enabled, "metrics": reg.snapshot()}
            if "kinds" in qs:
                payload["kinds"] = {name: kind for (name, _), (kind, _) in
                                    reg.typed_snapshot().items()}
            self._send(200, payload)
        elif path == "/":
            self._send(200, {"endpoints": ["/generate", "/healthz",
                                           "/replicaz", "/statz"]})
        else:
            self.send_error(404)

    def log_message(self, fmt, *args):   # dispatches are not log lines
        pass


class RouterServer:
    """Serve the router over HTTP on a daemon thread (the ``MetricsServer``
    shape: ``port=0`` binds an ephemeral port, read it back from
    ``server.port``)."""

    def __init__(self, router: Router, port: int = 0,
                 host: str = "127.0.0.1"):
        self.router = router
        self._requested_port = port
        self.host = host
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else \
            self._requested_port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "RouterServer":
        if self._httpd is not None:
            return self
        handler = type("Handler", (_RouterHandler,), {"router": self.router})
        self._httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                          handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="ds-router-http", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        self._thread = None
