"""Multi-replica serving router: least-loaded dispatch over ServingEngine
replicas (the front-end ABOVE one engine — ROADMAP item 3).

One ServingEngine serves one process's slots; millions-of-users traffic
needs N replicas and something to spread load across them.  This module
is that something, built from signals the replicas already export:

- **membership / drain** — ``GET /healthz`` per replica (200 = ready,
  503 = draining or otherwise not accepting work; unreachable = down).
  A replica that stops being ready simply stops receiving dispatches —
  ``ServingEngine.drain()`` needs no router-side coordination.
- **least-loaded dispatch** — each replica's live ``/statz`` gauges
  (``ds_serve_queue_depth``, ``ds_serve_active_slots``,
  ``ds_serve_kv_pages_used/free``) plus the router's own in-flight count
  (polls are eventually-consistent; the in-flight term keeps a burst
  between polls from piling onto one replica).  Score = requests in the
  system (queue + active + in-flight) with KV-pool pressure as the
  fractional tie-break.
- **session affinity** — a ``session`` key in the request pins follow-up
  turns to the same replica while it stays healthy (TTL-bounded), so a
  conversation's prefix-cache pages (serving/prefix_cache.py) are HIT
  instead of recomputed on a cold replica.
- **no dropped requests** — a failed dispatch (connection error, 503
  while draining, or the replica handing back a request that was still
  queued when its drain hit) is retried on another replica; the request
  is only failed back to the client after every round is exhausted.
- **circuit breaking** — a replica that keeps failing dispatches
  (``breaker_threshold`` consecutive) trips its breaker and is skipped
  even while its ``/healthz`` still answers 200 (the sick-but-alive
  case: 500s out of a live process).  After ``breaker_cooldown`` the
  breaker goes HALF-OPEN: exactly one probe dispatch is allowed through;
  success closes it, failure re-opens with the cooldown doubled (capped).
- **retry budget** — retries draw from a token bucket refilled by
  first-attempt traffic (``retry_budget_ratio`` per dispatch, capped at
  ``retry_budget_cap``): when the whole fleet is failing, the router
  stops amplifying load instead of DDoS'ing its own sick replicas.
- **429-aware backoff** — an overloaded replica's shed (HTTP 429 from
  the bounded admission queue) is NOT a failure: the replica stays in
  membership and its breaker untouched; the router tries the others and,
  if every ready replica is shedding, surfaces 429 with the largest
  ``Retry-After`` — clients slow down, the fleet degrades gracefully.
- **idempotent dispatch** — every dispatch carries an
  ``idempotency_key`` (caller-supplied or router-generated).  The
  replica de-duplicates on it, so a retry after an AMBIGUOUS failure —
  a socket that died after the request may have been delivered, or a
  router-side timeout on a wedged replica — can join the original
  in-flight generation instead of producing a second one.  This is what
  makes timeouts retry-elsewhere-safe (previously they had to surface
  as 504 precisely because a retry could double-generate).
- **role-split (disaggregated) fleets** — a replica spec may carry a
  role (``name@prefill=url`` / ``name@decode=url``; default ``both``).
  When the fleet has dedicated prefill replicas, ``dispatch`` runs a
  TWO-PHASE request: phase 1 posts ``{"phase": "prefill"}`` to a
  prefill replica, which runs admission + chunked prefill and ships the
  computed KV pages to the chosen decode replica (``handoff_to``, int8
  on the wire — docs/RESILIENCE.md "Disaggregated serving"); phase 2
  dispatches the full generate to the decode pool, preferring the
  replica the pages landed on.  The phase NEVER fails the request: a
  sick prefill pool degrades to monolithic serving (the decode replica
  recomputes the prefix itself).  Session affinity and breaker state
  are role-scoped — a drained prefill replica cannot absorb decode
  pins.
- **token streaming with resume-from-token-N** — ``{"stream": true}``
  payloads relay the replica's NDJSON chunk stream through the router
  (TTFT becomes user-visible).  A replica that dies MID-STREAM is
  retried on a survivor with ``resume_from=<tokens already relayed>``
  and the same idempotency key, so the client sees one contiguous
  token stream with no duplicated and no dropped tokens.

The router dispatches ``POST /generate`` (the endpoint
``init_serving(metrics_port=...)`` attaches to the replica's metrics
server) and is itself a stdlib HTTP front-end (:class:`RouterServer`)
exposing the same ``/generate`` + ``/healthz`` + ``/statz`` shapes, so
routers can be health-checked and scraped exactly like replicas.

jax-free by construction: the metrics module is resolved through the
package only when it is already importable, else loaded by file path
(the ``tools/fleet_dump.py`` idiom) — ``tools/router.py`` runs this file
standalone on an operator box with no jax installed.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs


def _load_metrics():
    """The repo's stdlib-only metrics module: via the package when it is
    importable in this process (so the router and any in-process engines
    share ONE registry), else exec'd by file path (operator box, no
    jax)."""
    if "deepspeed_tpu" in sys.modules:
        from deepspeed_tpu.monitor import metrics

        return metrics
    mod = sys.modules.get("_ds_router_metrics")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "monitor", "metrics.py")
    spec = importlib.util.spec_from_file_location("_ds_router_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_ds_router_metrics"] = mod
    spec.loader.exec_module(mod)
    return mod


_metrics = _load_metrics()

__all__ = ["Replica", "Router", "RouterServer"]

# trace-context propagation (docs/OBSERVABILITY.md, "Distributed
# tracing"): every dispatch carries a W3C-traceparent-shaped id —
# ``00-<32 hex trace-id>-<16 hex span-id>-01`` — minted here when the
# client didn't send one, forwarded to the replica as the
# ``traceparent`` HTTP header, and echoed in 200 bodies as ``trace``
# (the bare 32-hex trace-id) so a client can find its spans later.
_TRACEPARENT_VERSION = "00"


def _mint_traceparent() -> str:
    return (f"{_TRACEPARENT_VERSION}-{os.urandom(16).hex()}"
            f"-{os.urandom(8).hex()}-01")


def _trace_id(traceparent: str) -> str:
    """The 32-hex trace-id half of a traceparent; a malformed value is
    used whole (better an ugly join key than a dropped correlation)."""
    parts = str(traceparent).split("-")
    return parts[1] if len(parts) == 4 and parts[1] else str(traceparent)


class _HopLog:
    """Ring of completed dispatch records — the ROUTER side of the
    distributed trace: one record per ``dispatch()`` carrying the trace
    id and its hop spans (pick, attempt N, retry, breaker-skip, shed,
    idempotency-join).

    Owns this process's clock anchor (the ``set_trace_clock_anchor``
    contract from monitor/request_trace.py, restated here because the
    router must stay jax-free and cannot import the package): exported
    timestamps are microseconds since ``anchor["perf"]``, and
    ``anchor["unix"]`` is the wall time that instant corresponds to —
    ``fleet_dump --trace`` shifts one process's export onto another's
    clock by the difference of their unix halves."""

    DEFAULT_RING = 256

    # dispatch threads append finished records (the record dict is never
    # mutated after append) and /requestz snapshots read; deque append
    # is GIL-atomic (dslint DSL006, docs/LINT.md)
    _dslint_shared = {"_ring": "atomic",
                      "dispatches_total": "lock:_lock"}

    def __init__(self, ring: int = DEFAULT_RING):
        self._ring: collections.deque = collections.deque(maxlen=int(ring))
        self._lock = threading.Lock()
        self.dispatches_total = 0
        self.anchor = {"perf": time.perf_counter(), "unix": time.time(),
                       "source": "router_process"}

    def record(self, trace: str, t0: float, t1: float, status: int,
               hops: List[dict]) -> None:
        self._ring.append({"trace": trace, "t0": t0, "t1": t1,
                           "status": int(status), "hops": list(hops)})
        with self._lock:
            self.dispatches_total += 1

    def _rel_us(self, t: float) -> float:
        return (t - self.anchor["perf"]) * 1e6

    def snapshot(self, limit: int = 32) -> Dict[str, object]:
        recs = list(self._ring)
        if limit >= 0:
            recs = recs[-limit:] if limit else []
        out = []
        for rec in recs:
            hops = []
            for h in rec["hops"]:
                ho = {"kind": h["kind"],
                      "t0_us": round(self._rel_us(h["t0"]), 1)}
                if "t1" in h:
                    ho["dur_us"] = round((h["t1"] - h["t0"]) * 1e6, 1)
                if h.get("args"):
                    ho["args"] = h["args"]
                hops.append(ho)
            out.append({"trace": rec["trace"], "status": rec["status"],
                        "t0_us": round(self._rel_us(rec["t0"]), 1),
                        "dur_us": round((rec["t1"] - rec["t0"]) * 1e6, 1),
                        "hops": hops})
        with self._lock:
            total = self.dispatches_total
        return {"kind": "router_hops", "dispatches_total": total,
                "retained": len(self._ring), "clock": dict(self.anchor),
                "dispatches": out}

    def perfetto_trace(self) -> Dict[str, object]:
        """Chrome trace-event JSON of the retained dispatches: one
        synthetic thread per dispatch (a dispatch's hops overlap other
        dispatches but never each other), the dispatch itself as the
        enclosing slice, span hops as ``X`` slices, point hops as
        instants — every event's args carry the trace id, which is the
        join key against the replicas' ``/requestz`` exports."""
        events: List[dict] = [{"ph": "M", "pid": 1, "name": "process_name",
                               "args": {"name": "ds_router"}}]
        for tid, rec in enumerate(list(self._ring), start=1):
            events.append({"ph": "M", "pid": 1, "tid": tid,
                           "name": "thread_name",
                           "args": {"name":
                                    f"dispatch {rec['trace'][:8]}"}})
            events.append({"ph": "X", "pid": 1, "tid": tid,
                           "ts": self._rel_us(rec["t0"]),
                           "dur": (rec["t1"] - rec["t0"]) * 1e6,
                           "name": f"dispatch ({rec['status']})",
                           "args": {"trace": rec["trace"],
                                    "status": rec["status"]}})
            for h in rec["hops"]:
                args = dict(h.get("args") or {})
                args["trace"] = rec["trace"]
                if "t1" in h:
                    events.append({"ph": "X", "pid": 1, "tid": tid,
                                   "ts": self._rel_us(h["t0"]),
                                   "dur": (h["t1"] - h["t0"]) * 1e6,
                                   "name": h["kind"], "args": args})
                else:
                    events.append({"ph": "i", "pid": 1, "tid": tid,
                                   "ts": self._rel_us(h["t0"]), "s": "t",
                                   "name": h["kind"], "args": args})
        return {"displayTimeUnit": "ns", "traceEvents": events,
                "otherData": {"clock_anchor_unix": self.anchor["unix"],
                              "clock_source": self.anchor["source"],
                              "domain": "microseconds since the last "
                                        "profiler-session start"}}


class Replica:
    """One backend ServingEngine endpoint and the router's view of it:
    membership/load from the polls, plus a per-replica CIRCUIT BREAKER
    over dispatch outcomes (closed -> open on consecutive failures ->
    half-open single probe after the cooldown -> closed on success /
    re-open with doubled cooldown on failure)."""

    # breaker state is written from every dispatch thread AND the poll
    # thread; all transitions hold the replica's own lock (dslint
    # DSL006, docs/LINT.md)
    _dslint_shared = {"fail_streak": "lock:_lock",
                      "breaker_open_until": "lock:_lock",
                      "breaker_trips": "lock:_lock",
                      "_cooldown": "lock:_lock",
                      "_probe_inflight": "lock:_lock"}

    ROLES = ("both", "prefill", "decode")

    def __init__(self, name: str, base_url: str, role: str = "both"):
        self.name = name
        if role not in self.ROLES:
            raise ValueError(f"unknown replica role {role!r} "
                             f"(one of {self.ROLES})")
        self.role = role
        self.base = base_url.rstrip("/")
        if not self.base.startswith("http"):
            self.base = "http://" + self.base
        self.ready = False
        self.reason: Optional[str] = "unpolled"
        self.queue_depth = 0.0
        self.active_slots = 0.0
        self.kv_busy = 0.0           # pages_used / (used + free), in [0, 1]
        self.inflight = 0            # router-side: dispatches awaiting reply
        self.last_poll = 0.0
        self._lock = threading.Lock()
        self.fail_streak = 0         # consecutive dispatch failures
        self.breaker_open_until = 0.0    # monotonic; 0 = closed
        self.breaker_trips = 0
        self._cooldown = 0.0         # current trip's cooldown (doubles)
        self._probe_inflight = False     # half-open: one probe at a time

    def score(self) -> float:
        """Lower = less loaded.  Whole requests in the system dominate;
        KV-pool pressure (always < 1) breaks ties between otherwise-equal
        replicas."""
        return (self.queue_depth + self.active_slots + self.inflight
                + min(self.kv_busy, 0.99))

    # -- circuit breaker ------------------------------------------------
    def breaker_state(self, now: float) -> str:
        until = self.breaker_open_until
        if until <= 0:
            return "closed"
        return "open" if now < until else "half-open"

    def try_probe(self, now: float) -> bool:
        """Half-open admission: exactly ONE probe dispatch may pass per
        half-open window; its outcome closes or re-opens the breaker."""
        with self._lock:
            if self.breaker_open_until <= 0:
                return True              # closed: not a probe
            if now < self.breaker_open_until or self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def note_success(self) -> None:
        with self._lock:
            self.fail_streak = 0
            self.breaker_open_until = 0.0
            self._cooldown = 0.0
            self._probe_inflight = False

    def release_probe(self) -> None:
        """Give back a half-open probe reservation whose dispatch ended
        INCONCLUSIVELY (429 shed, 400, replica 504, retry budget dry):
        neither success nor failure, so the breaker state is untouched —
        but the reservation must free or no probe can ever run again."""
        with self._lock:
            self._probe_inflight = False

    def note_failure(self, now: float, threshold: int,
                     cooldown_base: float, cooldown_max: float) -> bool:
        """One dispatch failure; returns True when it TRIPS the breaker
        (first trip at ``threshold`` consecutive failures; a failed
        half-open probe re-trips immediately with the cooldown doubled,
        capped at ``cooldown_max``)."""
        with self._lock:
            self.fail_streak += 1
            probe_failed = self._probe_inflight
            self._probe_inflight = False
            if probe_failed or (self.fail_streak >= threshold
                                and self.breaker_open_until <= 0):
                self._cooldown = (cooldown_base if self._cooldown <= 0
                                  else min(cooldown_max,
                                           self._cooldown * 2))
                self.breaker_open_until = now + self._cooldown
                self.breaker_trips += 1
                return True
            return False

    def snapshot(self) -> Dict[str, object]:
        now = time.monotonic()
        return {"name": self.name, "base": self.base, "role": self.role,
                "ready": self.ready,
                "reason": self.reason, "queue_depth": self.queue_depth,
                "active_slots": self.active_slots,
                "kv_busy": round(self.kv_busy, 4),
                "inflight": self.inflight, "score": round(self.score(), 4),
                "breaker": self.breaker_state(now),
                "breaker_trips": self.breaker_trips,
                "fail_streak": self.fail_streak}


class Router:
    """Least-loaded, drain-aware dispatch across N replicas.

    ``replicas`` is a list of URLs (or ``name=url`` pairs) pointing at
    replica metrics servers (``init_serving(metrics_port=...)``).
    ``dispatch`` POSTs ``/generate`` to the best ready replica and
    retries elsewhere on failure; ``refresh`` polls ``/healthz`` +
    ``/statz``; ``start()`` polls on a background thread.
    """

    # the retry-budget token bucket is drawn on by every dispatch
    # thread: all writes hold the router lock (dslint DSL006)
    _dslint_shared = {"_retry_tokens": "lock:_lock"}

    def __init__(self, replicas: List[str], *, poll_interval: float = 0.25,
                 poll_timeout: float = 2.0, affinity_ttl: float = 300.0,
                 max_sessions: int = 65536, dispatch_rounds: int = 8,
                 retry_backoff: float = 0.05,
                 request_timeout: float = 300.0,
                 breaker_threshold: int = 3, breaker_cooldown: float = 2.0,
                 breaker_cooldown_max: float = 30.0,
                 retry_budget_ratio: float = 0.25,
                 retry_budget_cap: float = 16.0, registry=None):
        self.replicas: List[Replica] = []
        for i, spec in enumerate(replicas):
            name, sep, rest = spec.partition("=")
            if sep and not name.startswith("http") and "/" not in name:
                # "name=url" or role-split "name@prefill=url"
                name, _, role = name.partition("@")
                self.replicas.append(Replica(name, rest,
                                             role=role or "both"))
            else:
                self.replicas.append(Replica(f"r{i}", spec))
        if not self.replicas:
            raise ValueError("router needs at least one replica URL")
        # a fleet with ANY dedicated role dispatches role-aware; an
        # all-"both" fleet keeps the legacy single-phase path bit-for-bit
        self._has_roles = any(r.role != "both" for r in self.replicas)
        self._has_prefill = any(r.role == "prefill" for r in self.replicas)
        self._by_name = {r.name: r for r in self.replicas}
        if len(self._by_name) != len(self.replicas):
            raise ValueError("duplicate replica names")
        self.poll_interval = float(poll_interval)
        self.poll_timeout = float(poll_timeout)
        self.affinity_ttl = float(affinity_ttl)
        self.max_sessions = int(max_sessions)
        self.dispatch_rounds = int(dispatch_rounds)
        self.retry_backoff = float(retry_backoff)
        self.request_timeout = float(request_timeout)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown = float(breaker_cooldown)
        self.breaker_cooldown_max = float(breaker_cooldown_max)
        # gRPC-style retry budget: first-attempt traffic refills the
        # bucket at retry_budget_ratio per dispatch; each re-POST costs
        # one token — a fleet-wide outage throttles the router's own
        # retry amplification to ~ratio x offered load
        self.retry_budget_ratio = float(retry_budget_ratio)
        self.retry_budget_cap = float(retry_budget_cap)
        self._retry_tokens = self.retry_budget_cap
        # idempotency keys: unique per logical dispatch across router
        # restarts (pid + start-stamp prefix, counter suffix)
        self._idem_prefix = f"rt-{os.getpid():x}-{int(time.time() * 1e3):x}"
        self._idem_seq = itertools.count()
        self._affinity: Dict[str, Tuple[str, float]] = {}
        self._lock = threading.Lock()
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_stop: Optional[threading.Event] = None
        self.registry = (registry if registry is not None
                         else _metrics.get_registry())
        self._m_retries = self.registry.counter(
            "ds_router_retries_total",
            "dispatches retried on another replica (connection failure, "
            "drain 503, or drain-requeue)")
        self._m_dispatch = {
            r.name: self.registry.counter(
                "ds_router_dispatch_total",
                "requests dispatched, by replica",
                labels={"replica": r.name})
            for r in self.replicas}
        self._m_depth = {
            r.name: self.registry.gauge(
                "ds_router_replica_queue_depth",
                "last-polled ds_serve_queue_depth, by replica",
                labels={"replica": r.name})
            for r in self.replicas}
        self._m_breaker_trips = self.registry.counter(
            "ds_router_breaker_trips_total",
            "circuit-breaker trips (open/re-open) across replicas")
        self._m_breaker_open = {
            r.name: self.registry.gauge(
                "ds_router_breaker_open",
                "1 while the replica's circuit breaker is open or "
                "half-open, by replica",
                labels={"replica": r.name})
            for r in self.replicas}
        self._m_budget_exhausted = self.registry.counter(
            "ds_router_retry_budget_exhausted_total",
            "retries suppressed because the retry-budget token bucket "
            "was empty (sick-fleet retry-amplification guard)")
        self._m_shed_429 = self.registry.counter(
            "ds_router_shed_429_total",
            "dispatches answered 429 by an overloaded replica's "
            "admission shed (not a failure: membership/breaker "
            "untouched, backoff honored)")
        # distributed tracing: ring of per-dispatch hop records served
        # by the router's own /requestz, with hop-kind counters and the
        # attempt-latency histogram alongside
        self.hops = _HopLog()
        self._m_hops = {
            kind: self.registry.counter(
                "ds_router_hops_total",
                "trace hop events recorded on the dispatch path, "
                "by kind",
                labels={"kind": kind})
            for kind in ("pick", "attempt", "retry", "breaker_skip",
                         "shed", "idem_join", "handoff", "resume")}
        self._m_hop_seconds = self.registry.histogram(
            "ds_router_hop_seconds",
            "wall seconds per dispatch attempt (the POST to a replica, "
            "connect through the replica's full generation)")

    # -- membership + load polling -------------------------------------
    def poll_one(self, rep: Replica) -> None:
        """One replica's ``/healthz`` + ``/statz`` poll; failures mark it
        not-ready (it rejoins on the next successful poll)."""
        import urllib.error
        import urllib.request

        try:
            # readiness: the status code IS the signal (503 raises)
            with urllib.request.urlopen(rep.base + "/healthz",
                                        timeout=self.poll_timeout):
                pass
            rep.ready, rep.reason = True, None
        except urllib.error.HTTPError as exc:
            body = {}
            try:
                body = json.load(exc)
            except Exception:
                pass
            rep.ready = False
            rep.reason = body.get("reason") or f"healthz {exc.code}"
        except OSError as exc:
            rep.ready, rep.reason = False, f"unreachable: {exc}"
        rep.last_poll = time.monotonic()
        if not rep.ready:
            return
        try:
            with urllib.request.urlopen(rep.base + "/statz",
                                        timeout=self.poll_timeout) as resp:
                m = json.load(resp).get("metrics", {})
        except (OSError, ValueError):
            return                       # keep the last load view
        rep.queue_depth = float(m.get("ds_serve_queue_depth") or 0)
        rep.active_slots = float(m.get("ds_serve_active_slots") or 0)
        used = float(m.get("ds_serve_kv_pages_used") or 0)
        free = float(m.get("ds_serve_kv_pages_free") or 0)
        rep.kv_busy = used / (used + free) if used + free else 0.0
        self._m_depth[rep.name].set(rep.queue_depth)

    def refresh(self) -> None:
        for rep in self.replicas:
            self.poll_one(rep)

    def start(self) -> "Router":
        """Poll membership/load on a background daemon thread."""
        if self._poll_thread is not None and self._poll_thread.is_alive():
            return self
        self.refresh()                   # synchronous first poll
        stop = self._poll_stop = threading.Event()

        def poll():
            while not stop.wait(self.poll_interval):
                self.refresh()

        self._poll_thread = threading.Thread(target=poll, daemon=True,
                                             name="ds-router-poll")
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        if self._poll_stop is not None:
            self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10)
        self._poll_thread = None
        self._poll_stop = None

    # -- dispatch ------------------------------------------------------
    def ready_replicas(self) -> List[Replica]:
        return [r for r in self.replicas if r.ready]

    @staticmethod
    def _role_ok(rep: Replica, role: Optional[str]) -> bool:
        """Role gate for dispatch targets.  Decode work may land on a
        dedicated decode replica or a monolithic "both"; prefill-phase
        work ONLY on a dedicated prefill replica (a "both" replica
        prefills inline during its own decode dispatch — phase-splitting
        to it would add a handoff without saving any work)."""
        if role is None:
            return True
        if role == "decode":
            return rep.role in ("decode", "both")
        return rep.role == role

    @staticmethod
    def _akey(role: Optional[str], session: str):
        """Affinity-map key: role-SCOPED in role-split fleets, so a
        session's prefill pin and decode pin live independently and a
        drained prefill replica can never absorb (or shadow) the
        session's decode pin.  Legacy role-less dispatch keeps the bare
        session string."""
        return session if role is None else (role, session)

    def pick(self, session: Optional[str] = None,
             exclude: Tuple[str, ...] = (),
             role: Optional[str] = None) -> Optional[Replica]:
        """Session-affine when possible (prefix-cache locality), else the
        lowest-score ready replica (name as the deterministic final
        tie-break), restricted to ``role``-compatible replicas (see
        :meth:`_role_ok`).  Breaker-open replicas are skipped; when only
        half-open replicas remain, the best-scored one admits a single
        probe.  A session pinned to a replica that LEFT membership
        (crash — a clean drain pops the pin at dispatch) falls back to
        least-loaded immediately AND drops the pin, so the conversation
        re-pins to the fallback replica — its prefix pages warm THERE,
        and the session must not bounce back to the cold original when
        it rejoins inside the affinity TTL.  A pin whose replica no
        longer passes the role gate (fleet re-rolled) is dropped the
        same way."""
        now = time.monotonic()
        ready = [r for r in self.replicas
                 if r.ready and r.name not in exclude
                 and self._role_ok(r, role)]
        if session is not None:
            akey = self._akey(role, session)
            with self._lock:
                ent = self._affinity.get(akey)
            if ent is not None and now - ent[1] < self.affinity_ttl:
                rep = self._by_name.get(ent[0])
                usable = (rep is not None and rep.ready
                          and rep.breaker_state(now) == "closed"
                          and self._role_ok(rep, role))
                if usable and rep.name not in exclude:
                    return rep
                if not usable:
                    # pinned replica crashed / tripped its breaker / lost
                    # its role: unpin so the dispatch below re-pins to
                    # where it actually lands.  A pin that is merely
                    # EXCLUDED this round (e.g. it answered one transient
                    # 429) is kept — the session returns to its warm
                    # prefix pages next time
                    with self._lock:
                        if self._affinity.get(akey) is ent:
                            del self._affinity[akey]
        # least-loaded over closed replicas AND half-open probes: a
        # cooled-down replica re-enters the ordering by score (it has no
        # inflight, so it naturally reaches the front) and admits ONE
        # probe — whose outcome closes or re-opens its breaker; open /
        # probe-busy replicas are skipped
        for rep in sorted(ready, key=lambda r: (r.score(), r.name)):
            state = rep.breaker_state(now)
            if state == "closed":
                return rep
            if state == "half-open" and rep.try_probe(now):
                return rep
        return None

    def _post(self, rep: Replica, payload: dict) -> Tuple[int, dict]:
        import urllib.error
        import urllib.request

        # trace context rides the traceparent HEADER (the W3C channel;
        # monitor/server.py extracts it back into the payload for the
        # engine), not the forwarded body
        tp = payload.get("traceparent")
        payload = {k: v for k, v in payload.items() if k != "traceparent"}
        headers = {"Content-Type": "application/json"}
        if isinstance(tp, str) and tp:
            headers["traceparent"] = tp
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            rep.base + "/generate", data=body, headers=headers)
        # the socket deadline must OUTLAST the replica's own generation
        # deadline (the payload's "timeout", which the engine honors with
        # its 504-and-abort path) — a router that times out first would
        # mistake a still-generating replica for a dead one and
        # double-generate the prompt elsewhere
        deadline = self.request_timeout
        try:
            deadline = max(deadline, float(payload.get("timeout")) + 30.0)
        except (TypeError, ValueError):
            pass
        try:
            with urllib.request.urlopen(req, timeout=deadline) as resp:
                return resp.status, json.load(resp)
        except urllib.error.HTTPError as exc:
            try:
                return exc.code, json.load(exc)
            except Exception:
                return exc.code, {"error": f"replica returned {exc.code}"}

    def _post_stream(self, rep: Replica, payload: dict):
        """:meth:`_post` for streaming dispatches: returns ``(200,
        live-response)`` so the relay can read NDJSON events
        incrementally, or ``(code, body-dict)`` for any non-200 answer
        (which the replica sends as plain JSON — streaming only starts
        once the request is admitted)."""
        import urllib.error
        import urllib.request

        tp = payload.get("traceparent")
        payload = {k: v for k, v in payload.items() if k != "traceparent"}
        headers = {"Content-Type": "application/json"}
        if isinstance(tp, str) and tp:
            headers["traceparent"] = tp
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            rep.base + "/generate", data=body, headers=headers)
        deadline = self.request_timeout
        try:
            deadline = max(deadline, float(payload.get("timeout")) + 30.0)
        except (TypeError, ValueError):
            pass
        try:
            resp = urllib.request.urlopen(req, timeout=deadline)
        except urllib.error.HTTPError as exc:
            try:
                return exc.code, json.load(exc)
            except Exception:
                return exc.code, {"error": f"replica returned {exc.code}"}
        return resp.status, resp

    def _hop(self, hops: List[dict], kind: str,
             t0: Optional[float] = None, t1: Optional[float] = None,
             **args) -> None:
        """Append one trace-hop record (the shape :class:`_HopLog`
        snapshots) to ``hops``."""
        h: Dict[str, object] = {
            "kind": kind,
            "t0": t0 if t0 is not None else time.perf_counter()}
        if t1 is not None:
            h["t1"] = t1
        if args:
            h["args"] = args
        hops.append(h)

    def _file_hops(self, trace: str, t0: float, code: int,
                   hops: List[dict]) -> None:
        """Bump the per-kind hop counters + attempt-latency histogram and
        file the finished dispatch record in the /requestz ring."""
        for h in hops:
            m = self._m_hops.get(h["kind"])
            if m is not None:
                m.inc()
            if h["kind"] == "attempt" and "t1" in h:
                self._m_hop_seconds.record(h["t1"] - h["t0"])
        self.hops.record(trace, t0, time.perf_counter(), code, hops)

    def _take_retry_token(self) -> bool:
        """One retry's withdrawal from the budget bucket; False = the
        bucket is dry and the retry must be suppressed (a fleet where
        everything fails must not be hammered at rounds x offered
        load by its own router)."""
        with self._lock:
            if self._retry_tokens >= 1.0:
                self._retry_tokens -= 1.0
                return True
        self._m_budget_exhausted.inc()
        return False

    def _prepare(self, payload: dict) -> Tuple[dict, str]:
        """Shared dispatch preamble: copy the payload, ensure a trace
        context (the caller's ``traceparent`` or one minted here) and an
        ``idempotency_key`` (minted BEFORE the prefill phase so phase 1
        and phase 2 derive from one logical key)."""
        payload = dict(payload)
        tp = payload.get("traceparent")
        if not (isinstance(tp, str) and tp):
            tp = _mint_traceparent()
            payload["traceparent"] = tp
        if not payload.get("idempotency_key"):
            payload["idempotency_key"] = \
                f"{self._idem_prefix}-{next(self._idem_seq)}"
        return payload, _trace_id(tp)

    def _route_roles(self, payload: dict,
                     hops: List[dict]) -> Tuple[Optional[str],
                                                Optional[Replica]]:
        """Pre-dispatch role decision: ``(role, preferred replica)``.
        Legacy all-``both`` fleets keep ``role=None`` (no behavior
        change).  A payload that IS a prefill-phase request routes
        strictly to prefill replicas; everything else runs the prefill
        phase (when the fleet has dedicated prefill replicas) and then
        dispatches to the decode pool, preferring the replica the KV
        pages were shipped to."""
        if not self._has_roles:
            return None, None
        if payload.get("phase") == "prefill":
            return "prefill", None
        prefer = (self._prefill_phase(payload, hops)
                  if self._has_prefill else None)
        return "decode", prefer

    def dispatch(self, payload: dict) -> Tuple[int, dict]:
        """Route one ``/generate`` payload: :meth:`_prepare` the trace +
        idempotency context, run the disaggregated prefill phase when the
        fleet is role-split (:meth:`_route_roles`), then the retry loop
        in :meth:`_dispatch` recording a hop span per decision point, and
        file the finished record in :attr:`hops` (the router's
        ``/requestz`` ring).  200 bodies additionally carry the 32-hex
        trace id under ``"trace"``."""
        payload, trace = self._prepare(payload)
        hops: List[dict] = []
        t0 = time.perf_counter()
        role, prefer = self._route_roles(payload, hops)
        code, body = self._dispatch(payload, hops, role=role,
                                    prefer=prefer)
        self._file_hops(trace, t0, code, hops)
        if code == 200 and isinstance(body, dict):
            body.setdefault("trace", trace)
        return code, body

    def _prefill_phase(self, payload: dict,
                       hops: List[dict]) -> Optional[Replica]:
        """Disaggregated phase 1: run admission + chunked prefill on a
        dedicated prefill replica, which ships the matched/computed KV
        pages to the chosen decode replica (``handoff_to``) before its
        ``prefill_done`` answer lands here.  Returns the decode replica
        the pages landed on — the preferred phase-2 target — or None
        when the phase was skipped (no ready prefill/decode replica).
        The phase NEVER fails the request: any phase error degrades to
        monolithic serving (the decode replica recomputes the prefix
        itself), while the breaker still learns about the sick prefill
        replica."""
        session = payload.get("session")
        dec = self.pick(session=session, role="decode")
        pre = self.pick(session=session, role="prefill")
        if dec is not None:
            # the phase never POSTs to dec itself — hand back a half-open
            # probe reservation pick() may have made; phase 2 re-probes
            dec.release_probe()
        if dec is None or pre is None:
            if pre is not None:
                pre.release_probe()   # picked as a probe but never POSTed
            return dec
        pf = {k: v for k, v in payload.items()
              if k not in ("stream", "resume_from", "phase", "handoff_to")}
        pf["phase"] = "prefill"
        pf["handoff_to"] = dec.base
        pf["idempotency_key"] = f"{payload['idempotency_key']}-pf"
        with self._lock:
            pre.inflight += 1
        t0 = time.perf_counter()
        try:
            try:
                code, body = self._post(pre, pf)
            except OSError as exc:
                code, body = -1, {"error": f"unreachable: {exc}"}
        finally:
            with self._lock:
                pre.inflight -= 1
        args: Dict[str, object] = {"prefill": pre.name, "decode": dec.name,
                                   "status": code}
        ship = body.get("handoff") if isinstance(body, dict) else None
        if isinstance(ship, dict):
            for k in ("pages_shipped", "wire_bytes", "error"):
                if ship.get(k) is not None:
                    args[k] = ship[k]
        self._hop(hops, "handoff", t0=t0, t1=time.perf_counter(), **args)
        now = time.monotonic()
        if code == 200:
            pre.note_success()
            self._m_breaker_open[pre.name].set(0)
            self._m_dispatch[pre.name].inc()
            if session is not None:
                with self._lock:
                    self._affinity[self._akey("prefill", session)] = \
                        (pre.name, now)
        elif code in (400, 429, 504):
            # inconclusive for the breaker (bad payload / shed / deadline)
            pre.release_probe()
        else:
            if pre.note_failure(now, self.breaker_threshold,
                                self.breaker_cooldown,
                                self.breaker_cooldown_max):
                self._m_breaker_trips.inc()
            self._m_breaker_open[pre.name].set(
                0 if pre.breaker_state(now) == "closed" else 1)
            if code in (-1, 503):
                pre.ready = False
                pre.reason = ((body or {}).get("error")
                              or f"prefill -> {code}")
        return dec

    def _dispatch(self, payload: dict, hops: List[dict],
                  role: Optional[str] = None,
                  prefer: Optional[Replica] = None) -> Tuple[int, dict]:
        """The retry loop behind :meth:`dispatch`: pick → POST → retry
        elsewhere on failure, appending one hop dict per decision point
        to ``hops``.  ``role`` restricts targets to role-compatible
        replicas; ``prefer`` (the replica the prefill phase shipped KV
        pages to) is tried FIRST when it is ready with a closed breaker
        — a miss just falls back to the normal pick, the pages were an
        optimization.  Returns ``(status, body)``; 200 bodies carry the
        serving replica's name under ``"replica"``.

        Every dispatch carries an ``idempotency_key`` (the caller's, or
        one minted here): replicas de-duplicate on it, so retries after
        AMBIGUOUS failures — a socket death after the request may have
        been delivered, a router-side timeout on a wedged replica —
        cannot double-generate (they join the original in-flight
        request).  Failure handling per status:

        - ``-1`` unreachable / socket timeout: membership drop + breaker
          count + retry elsewhere (timeouts are retry-safe now — the
          historical 504-no-retry existed exactly because a retry could
          double-generate);
        - ``5xx``: breaker count + retry elsewhere (a 500-ing replica
          whose /healthz still answers 200 trips its breaker and is
          skipped until the half-open probe heals it);
        - ``429`` shed: NOT a failure — membership and breaker untouched,
          retry the others; when every ready replica is shedding, 429
          surfaces to the client with the largest ``Retry-After``;
        - ``504`` from the replica itself (client/service deadline):
          authoritative, surfaced, never retried.

        Retries draw from the budget bucket; an empty bucket fails the
        request with what the last replica said instead of amplifying."""
        session = payload.get("session")
        akey = self._akey(role, session) if session is not None else None
        payload = dict(payload)

        def hop(kind: str, t0: Optional[float] = None,
                t1: Optional[float] = None, **args) -> None:
            self._hop(hops, kind, t0=t0, t1=t1, **args)

        if not payload.get("idempotency_key"):
            payload["idempotency_key"] = \
                f"{self._idem_prefix}-{next(self._idem_seq)}"
        with self._lock:
            # first-attempt traffic refills the retry budget
            self._retry_tokens = min(self.retry_budget_cap,
                                     self._retry_tokens
                                     + self.retry_budget_ratio)
        last_err: Optional[dict] = None
        shed_backoffs: List[float] = []
        non_shed_failures = 0
        budget_dry = False
        tried: set = set()
        posts = 0
        for attempt in range(self.dispatch_rounds):
            t_pick = time.perf_counter()
            rep = None
            if prefer is not None:
                # the prefill phase already shipped this request's KV
                # pages to `prefer` — land the decode there while it is
                # healthy (first attempt only; a dead/tripped prefer
                # falls back to the normal pick and the decode replica
                # recomputes the prefix)
                if (prefer.ready and prefer.name not in tried
                        and prefer.breaker_state(time.monotonic())
                        == "closed"):
                    rep = prefer
                prefer = None
            if rep is None:
                rep = self.pick(session=session, exclude=tuple(tried),
                                role=role)
            if rep is None and tried:
                # every ready replica already refused this request this
                # round; start a fresh round over re-polled membership
                tried.clear()
                rep = self.pick(session=session, role=role)
            hop("pick", t0=t_pick, t1=time.perf_counter(),
                attempt=attempt + 1,
                replica=rep.name if rep is not None else None)
            now_skip = time.monotonic()
            skipped = [r.name for r in self.replicas
                       if r.ready and r is not rep
                       and r.breaker_state(now_skip) != "closed"]
            if skipped:
                hop("breaker_skip", replicas=skipped)
            if rep is None:
                self.refresh()
                time.sleep(self.retry_backoff * (attempt + 1))
                continue
            if posts >= 1 and not self._take_retry_token():
                # a pick() may have reserved this replica's half-open
                # probe: hand it back, the probe never ran
                rep.release_probe()
                budget_dry = True
                break
            posts += 1
            if posts >= 2:
                # this POST re-presents the idempotency key minted
                # above: a replica holding the original in-flight
                # generation JOINS it instead of generating twice
                hop("idem_join", replica=rep.name,
                    key=payload["idempotency_key"])
            with self._lock:
                rep.inflight += 1
            t_att = time.perf_counter()
            try:
                try:
                    code, body = self._post(rep, payload)
                except OSError as exc:
                    reason = getattr(exc, "reason", exc)
                    if isinstance(exc, TimeoutError) or isinstance(
                            reason, TimeoutError):
                        # ambiguous — the replica may be wedged holding
                        # our request; the idempotency key makes the
                        # retry elsewhere safe, and the breaker keeps us
                        # from feeding the wedged replica more work
                        code, body = -1, {
                            "error": "router-side socket timeout "
                                     "(replica wedged?); retrying "
                                     "idempotently"}
                    else:
                        code, body = -1, {"error": f"unreachable: {exc}"}
            finally:
                with self._lock:
                    rep.inflight -= 1
            hop("attempt", t0=t_att, t1=time.perf_counter(),
                replica=rep.name, n=posts, status=code)
            now = time.monotonic()
            if code == 200:
                rep.note_success()
                self._m_breaker_open[rep.name].set(0)
                self._m_dispatch[rep.name].inc()
                if session is not None:
                    with self._lock:
                        self._affinity[akey] = (rep.name, now)
                    if len(self._affinity) > self.max_sessions:
                        self._expire_affinity()
                body["replica"] = rep.name
                return 200, body
            if code == 400:
                # the payload itself is bad — no replica will differ
                rep.release_probe()
                return 400, body
            if code == 429:
                # overload shed: graceful degradation, not a failure —
                # the replica stays in membership with its breaker
                # untouched (a half-open probe reservation is released,
                # not resolved); try the rest of the fleet
                rep.release_probe()
                self._m_shed_429.inc()
                try:
                    shed_backoffs.append(
                        float(body.get("retry_after_s", 1.0)))
                except (TypeError, ValueError):
                    shed_backoffs.append(1.0)
                hop("shed", replica=rep.name,
                    retry_after_s=shed_backoffs[-1])
                tried.add(rep.name)
                last_err = body
                continue
            if code == 504:
                # the replica's own deadline verdict (client timeout
                # abort or service-deadline expiry): too late everywhere
                rep.release_probe()
                body["replica"] = rep.name
                return 504, body
            # -1 (unreachable/timeout) / 5xx / 503 (draining, requeued,
            # crash-requeued): count it on the breaker and retry
            non_shed_failures += 1
            if rep.note_failure(now, self.breaker_threshold,
                                self.breaker_cooldown,
                                self.breaker_cooldown_max):
                self._m_breaker_trips.inc()
            self._m_breaker_open[rep.name].set(
                0 if rep.breaker_state(now) == "closed" else 1)
            if code in (-1, 503):
                # gone or draining: out of membership until the next
                # healthz poll; 500-class replicas stay (healthz is the
                # membership truth — the breaker is what skips them)
                rep.ready = False
                rep.reason = body.get("error") or f"generate -> {code}"
            if session is not None:
                with self._lock:
                    self._affinity.pop(akey, None)
            self._m_retries.inc()
            hop("retry", replica=rep.name, status=code)
            tried.add(rep.name)
            last_err = body
        if shed_backoffs and non_shed_failures == 0:
            # the whole ready fleet is load-shedding: tell the client to
            # back off (RouterServer forwards Retry-After), don't call
            # an overloaded fleet an outage
            return 429, {"error": "every ready replica is shedding "
                                  "(admission queues at their "
                                  "watermark); back off and retry",
                         "shed": True,
                         "retry_after_s": max(shed_backoffs)}
        if budget_dry:
            return 503, {"error": "retry budget exhausted (fleet-wide "
                                  "failures; not amplifying)",
                         "last": last_err}
        return 503, {"error": "no replica accepted the request after "
                              f"{self.dispatch_rounds} rounds",
                     "last": last_err}

    # -- streaming dispatch --------------------------------------------
    def dispatch_stream(self, payload: dict):
        """Route one STREAMING ``/generate`` payload.  Returns ``(200,
        iterator)`` where the iterator yields the replica's NDJSON
        events (token chunks, then one terminal event) — or ``(code,
        dict)`` when no stream could be established, same shapes as
        :meth:`dispatch` errors.

        A replica that dies MID-STREAM (socket death, or an in-stream
        error event marked ``requeued``) is retried on a survivor with
        ``resume_from=<tokens already relayed to the client>`` and the
        SAME idempotency key: a live original joins its in-flight
        generation (no double-generation), a fresh replica regenerates
        deterministically and streams only the unsent suffix — either
        way the client sees one contiguous token stream."""
        payload, trace = self._prepare(payload)
        payload["stream"] = True
        hops: List[dict] = []
        t0 = time.perf_counter()
        role, prefer = self._route_roles(payload, hops)
        sent0 = 0
        try:
            sent0 = max(0, int(payload.get("resume_from") or 0))
        except (TypeError, ValueError):
            pass
        code, rep, resp, body = self._acquire_stream(
            payload, hops, role, prefer, ())
        if code != 200:
            self._file_hops(trace, t0, code, hops)
            return code, body
        return 200, self._relay_stream(rep, resp, payload, hops, trace,
                                       t0, role, sent0)

    def _acquire_stream(self, payload: dict, hops: List[dict],
                        role: Optional[str], prefer: Optional[Replica],
                        exclude: Tuple[str, ...]):
        """Establish ONE live streaming connection: the pick/retry loop
        of :meth:`_dispatch`, slimmed to the streaming cases.  Returns
        ``(200, replica, live-response, None)`` or ``(code, None, None,
        error-body)``."""
        session = payload.get("session")
        akey = self._akey(role, session) if session is not None else None
        tried = set(exclude)
        last_err: Optional[dict] = None
        posts = 0
        for attempt in range(self.dispatch_rounds):
            t_pick = time.perf_counter()
            rep = None
            if prefer is not None:
                if (prefer.ready and prefer.name not in tried
                        and prefer.breaker_state(time.monotonic())
                        == "closed"):
                    rep = prefer
                prefer = None
            if rep is None:
                rep = self.pick(session=session, exclude=tuple(tried),
                                role=role)
            self._hop(hops, "pick", t0=t_pick, t1=time.perf_counter(),
                      attempt=attempt + 1,
                      replica=rep.name if rep is not None else None)
            if rep is None:
                self.refresh()
                time.sleep(self.retry_backoff * (attempt + 1))
                continue
            if posts >= 1 and not self._take_retry_token():
                rep.release_probe()
                return 503, None, None, {
                    "error": "retry budget exhausted (fleet-wide "
                             "failures; not amplifying)",
                    "last": last_err}
            posts += 1
            if posts >= 2:
                self._hop(hops, "idem_join", replica=rep.name,
                          key=payload["idempotency_key"])
            with self._lock:
                rep.inflight += 1
            t_att = time.perf_counter()
            try:
                try:
                    code, out = self._post_stream(rep, payload)
                except OSError as exc:
                    code, out = -1, {"error": f"unreachable: {exc}"}
            finally:
                with self._lock:
                    rep.inflight -= 1
            self._hop(hops, "attempt", t0=t_att, t1=time.perf_counter(),
                      replica=rep.name, n=posts, status=code)
            now = time.monotonic()
            if code == 200:
                rep.note_success()
                self._m_breaker_open[rep.name].set(0)
                self._m_dispatch[rep.name].inc()
                if session is not None:
                    with self._lock:
                        self._affinity[akey] = (rep.name, now)
                    if len(self._affinity) > self.max_sessions:
                        self._expire_affinity()
                return 200, rep, out, None
            if code in (400, 504):
                rep.release_probe()
                if isinstance(out, dict):
                    out["replica"] = rep.name
                return code, None, None, out
            if code == 429:
                rep.release_probe()
                self._m_shed_429.inc()
                self._hop(hops, "shed", replica=rep.name)
                tried.add(rep.name)
                last_err = out
                continue
            if rep.note_failure(now, self.breaker_threshold,
                                self.breaker_cooldown,
                                self.breaker_cooldown_max):
                self._m_breaker_trips.inc()
            self._m_breaker_open[rep.name].set(
                0 if rep.breaker_state(now) == "closed" else 1)
            if code in (-1, 503):
                rep.ready = False
                rep.reason = ((out if isinstance(out, dict) else {})
                              .get("error") or f"generate -> {code}")
            if session is not None:
                with self._lock:
                    self._affinity.pop(akey, None)
            self._m_retries.inc()
            self._hop(hops, "retry", replica=rep.name, status=code)
            tried.add(rep.name)
            last_err = out if isinstance(out, dict) else None
        return 503, None, None, {
            "error": "no replica accepted the stream after "
                     f"{self.dispatch_rounds} rounds",
            "last": last_err}

    def _relay_stream(self, rep: Replica, resp, payload: dict,
                      hops: List[dict], trace: str, t0: float,
                      role: Optional[str], sent: int):
        """The relay generator behind :meth:`dispatch_stream`: forward
        the replica's NDJSON events, counting tokens relayed; when the
        stream dies mid-generation, re-acquire on a survivor with
        ``resume_from=sent`` (hop kind ``resume``) and keep going.  The
        finished hop record files from the ``finally`` — a client that
        hangs up mid-stream still lands a /requestz record."""
        status = 200
        try:
            while True:
                died = False
                while True:
                    try:
                        line = resp.readline()
                    except OSError:
                        died = True
                        break
                    if not line:
                        died = True       # EOF before the terminal event
                        break
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        died = True
                        break
                    if not isinstance(ev, dict):
                        continue
                    if "tokens" in ev:
                        sent += len(ev["tokens"])
                        yield ev
                        continue
                    if ev.get("done"):
                        ev.setdefault("trace", trace)
                        ev.setdefault("replica", rep.name)
                        yield ev
                        return
                    # in-stream error event from the replica
                    st = 503
                    try:
                        st = int(ev.get("status") or 503)
                    except (TypeError, ValueError):
                        pass
                    if st == 504 or not ev.get("requeued"):
                        # authoritative (deadline) or non-retryable
                        status = st
                        ev.setdefault("replica", rep.name)
                        yield ev
                        return
                    died = True           # requeued: resume elsewhere
                    break
                if not died:
                    return
                try:
                    resp.close()
                except OSError:
                    pass
                now = time.monotonic()
                if rep.note_failure(now, self.breaker_threshold,
                                    self.breaker_cooldown,
                                    self.breaker_cooldown_max):
                    self._m_breaker_trips.inc()
                self._m_breaker_open[rep.name].set(
                    0 if rep.breaker_state(now) == "closed" else 1)
                rep.ready = False
                rep.reason = "stream died mid-generation"
                self._m_retries.inc()
                self._hop(hops, "resume", replica=rep.name,
                          resume_from=sent)
                retry = dict(payload)
                retry["resume_from"] = sent
                code, rep2, resp2, body = self._acquire_stream(
                    retry, hops, role, None, (rep.name,))
                if code != 200:
                    status = code
                    err = {"error": (body or {}).get(
                        "error", "stream resume failed"),
                        "status": code, "n": sent}
                    yield err
                    return
                rep, resp = rep2, resp2
        finally:
            try:
                resp.close()
            except OSError:
                pass
            self._file_hops(trace, t0, status, hops)

    def _expire_affinity(self) -> None:
        """Enforce the session-map bound: drop TTL-expired entries, then
        — if live sessions alone exceed the cap — evict oldest-touched
        down to 7/8 of ``max_sessions``, so the scan amortizes instead of
        re-running on every over-bound dispatch while the dict grows."""
        now = time.monotonic()
        with self._lock:
            dead = [s for s, (_, t) in self._affinity.items()
                    if now - t >= self.affinity_ttl]
            for s in dead:
                del self._affinity[s]
            over = len(self._affinity) - (self.max_sessions * 7) // 8
            if over > 0:
                oldest = sorted(self._affinity.items(),
                                key=lambda kv: kv[1][1])[:over]
                for s, _ in oldest:
                    del self._affinity[s]

    def snapshot(self) -> Dict[str, object]:
        return {"replicas": [r.snapshot() for r in self.replicas],
                "ready": sum(1 for r in self.replicas if r.ready),
                "sessions": len(self._affinity),
                "retry_tokens": round(self._retry_tokens, 2)}


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------


class _RouterHandler(BaseHTTPRequestHandler):
    router: Router   # set by the server subclass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload, sort_keys=True).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if code == 429 and isinstance(payload, dict) \
                and payload.get("retry_after_s") is not None:
            # the shed contract end to end: replicas 429 the router, the
            # router 429s the client, both with a Retry-After
            self.send_header("Retry-After",
                             str(max(1, int(payload["retry_after_s"]))))
        self.end_headers()
        self.wfile.write(body)

    def _stream(self, code: int, events) -> None:
        """Relay an event iterator as chunked NDJSON (the same wire
        shape a replica's streaming /generate answers with, so clients
        need one parser whether they talk to a replica or the
        router)."""
        self.send_response(code)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            for event in events:
                data = json.dumps(event, sort_keys=True).encode() + b"\n"
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass                 # client hung up mid-stream
        finally:
            close = getattr(events, "close", None)
            if close is not None:
                close()          # generator finally files the hop record

    def do_POST(self):  # noqa: N802 - http.server API
        path, _, _ = self.path.partition("?")
        if path not in ("/generate", "/generate/"):
            self.send_error(404)
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as exc:
            self._send(400, {"error": f"bad JSON body: {exc}"})
            return
        if payload.get("stream"):
            code, out = self.router.dispatch_stream(payload)
            if isinstance(out, dict):
                self._send(code, out)
            else:
                self._stream(code, out)
            return
        code, body = self.router.dispatch(payload)
        self._send(code, body)

    def do_GET(self):  # noqa: N802 - http.server API
        path, _, query = self.path.partition("?")
        if path in ("/healthz", "/healthz/"):
            # the router is ready while ANY replica is (same 200/503
            # shape as a replica's /healthz, so routers stack/chain)
            snap = self.router.snapshot()
            ready = snap["ready"] > 0
            self._send(200 if ready else 503,
                       {"ready": ready, "replicas": snap["replicas"]})
        elif path in ("/replicaz", "/replicaz/"):
            self._send(200, self.router.snapshot())
        elif path in ("/statz", "/statz/"):
            qs = parse_qs(query)
            reg = self.router.registry
            payload = {"enabled": reg.enabled, "metrics": reg.snapshot()}
            if "kinds" in qs:
                payload["kinds"] = {name: kind for (name, _), (kind, _) in
                                    reg.typed_snapshot().items()}
            self._send(200, payload)
        elif path in ("/requestz", "/requestz/"):
            # the router's half of the distributed trace, same endpoint
            # shape as a replica's /requestz so fleet_dump --trace can
            # scrape router and replicas with one code path
            qs = parse_qs(query)
            if qs.get("format", [""])[0] == "perfetto":
                self._send(200, self.router.hops.perfetto_trace())
                return
            try:
                limit = int(qs.get("n", ["32"])[0])
            except ValueError:
                self.send_error(400, "n must be an integer")
                return
            self._send(200, self.router.hops.snapshot(limit))
        elif path == "/":
            self._send(200, {"endpoints": ["/generate", "/healthz",
                                           "/replicaz", "/requestz",
                                           "/statz"]})
        else:
            self.send_error(404)

    def log_message(self, fmt, *args):   # dispatches are not log lines
        pass


class RouterServer:
    """Serve the router over HTTP on a daemon thread (the ``MetricsServer``
    shape: ``port=0`` binds an ephemeral port, read it back from
    ``server.port``)."""

    def __init__(self, router: Router, port: int = 0,
                 host: str = "127.0.0.1"):
        self.router = router
        self._requested_port = port
        self.host = host
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else \
            self._requested_port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "RouterServer":
        if self._httpd is not None:
            return self
        handler = type("Handler", (_RouterHandler,), {"router": self.router})
        self._httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                          handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="ds-router-http", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        self._thread = None
