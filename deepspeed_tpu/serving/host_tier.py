"""Host tier for KV pages: a bounded LRU store of demoted page payloads.

The prefix cache (``serving/prefix_cache.py``) pins finished prompts' KV
pages in the device pool; under pool pressure those pins are the first
thing evicted — and before this tier existed, eviction DROPPED the KV, so
the effective prefix cache was HBM-sized and a re-admission re-prefilled
from scratch.  This module is the ZeRO-Infinity move applied to serving
(ROADMAP item 3): an evicted page's payload is copied device->host into
this store ("demote") instead of being discarded, and a later admission
that matches the chunk streams it back host->device into a freshly
allocated page ("promote") — byte-identical KV, so greedy outputs cannot
change.  The effective prefix cache becomes host-RAM-sized, and a
preempt-resume re-adopts instead of re-prefilling.

The store holds opaque payloads (dicts of numpy arrays — K/V planes and,
quantized, their scales; the ENGINE owns the device<->host copies) keyed
by a monotone handle.  Capacity is page-count-bounded; inserting past the
bound evicts the least-recently-used entries and returns their keys so
the owner (the prefix-cache trie) can invalidate the nodes that pointed
at them.  Host-side bookkeeping only — no jax.

Metrics (docs/OBSERVABILITY.md "Serving — KV host tier"):
``ds_serve_kv_host_pages`` (gauge), ``ds_serve_kv_demote_total`` /
``ds_serve_kv_promote_total`` (counters — promote is counted by the
engine at the moment the payload lands back in a device page).
"""

from __future__ import annotations

import itertools
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["HostPageStore"]


class HostPageStore:
    """Bounded LRU {key -> page payload} host store."""

    def __init__(self, max_pages: int, registry=None):
        if max_pages < 1:
            raise ValueError(f"kv host tier needs >= 1 page, got {max_pages}")
        self.max_pages = int(max_pages)
        self._data: "OrderedDict[int, Dict[str, np.ndarray]]" = OrderedDict()
        self._next = itertools.count(1)
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry

            registry = get_registry()
        self._m_pages = registry.gauge(
            "ds_serve_kv_host_pages",
            "KV pages resident in the host tier (demoted, promotable)")
        self.m_demote = registry.counter(
            "ds_serve_kv_demote_total",
            "KV pages demoted device->host instead of dropped")
        self.m_promote = registry.counter(
            "ds_serve_kv_promote_total",
            "KV pages promoted host->device on a prefix re-admission")

    def __len__(self) -> int:
        return len(self._data)

    def put(self, payload: Dict[str, np.ndarray]
            ) -> Tuple[int, List[int]]:
        """Insert a demoted page; returns ``(key, evicted_keys)`` — the
        keys this insert pushed out of the bounded store (oldest first),
        which the owner must invalidate."""
        key = next(self._next)
        self._data[key] = payload
        evicted: List[int] = []
        while len(self._data) > self.max_pages:
            old, _ = self._data.popitem(last=False)
            evicted.append(old)
        self.m_demote.inc()
        self._m_pages.set(len(self._data))
        return key, evicted

    def get(self, key: int) -> Optional[Dict[str, np.ndarray]]:
        """The payload for ``key`` (LRU-touched), or None if it aged out."""
        payload = self._data.get(key)
        if payload is not None:
            self._data.move_to_end(key)
        return payload

    def touch(self, key: Optional[int]) -> bool:
        """LRU-touch without fetching; False when the entry aged out."""
        if key not in self._data:
            return False
        self._data.move_to_end(key)
        return True

    def drop(self, key: int) -> None:
        """Remove ``key`` (promotion re-homed it to a device page, or the
        owning trie node was cleared)."""
        self._data.pop(key, None)
        self._m_pages.set(len(self._data))

    def keys(self) -> List[int]:
        return list(self._data)

    def clear(self) -> int:
        n = len(self._data)
        self._data.clear()
        self._m_pages.set(0)
        return n
