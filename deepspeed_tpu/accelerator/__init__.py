from deepspeed_tpu.accelerator.real_accelerator import get_accelerator, set_accelerator

__all__ = ["get_accelerator", "set_accelerator"]
