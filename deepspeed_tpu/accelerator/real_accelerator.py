"""Accelerator auto-detection (reference: ``deepspeed/accelerator/real_accelerator.py``).

``get_accelerator()`` returns the process-wide accelerator, honoring the
``DS_ACCELERATOR`` env override exactly like the reference (SURVEY.md §5.6).
"""

from __future__ import annotations

import os
from typing import Optional

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.accelerator.tpu_accelerator import CPU_Accelerator, TPU_Accelerator
from deepspeed_tpu.utils.logging import logger

_ACCELERATOR: Optional[DeepSpeedAccelerator] = None


def _detect() -> DeepSpeedAccelerator:
    override = os.environ.get("DS_ACCELERATOR")
    if override:
        if override == "cpu":
            return CPU_Accelerator()
        if override in ("tpu", "axon"):
            return TPU_Accelerator(platform=override)
        raise ValueError(f"DS_ACCELERATOR={override!r} not supported (tpu, cpu)")
    import jax

    backend = jax.default_backend()
    if backend == "cpu":
        return CPU_Accelerator()
    return TPU_Accelerator(platform=backend)


def get_accelerator() -> DeepSpeedAccelerator:
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = _detect()
        logger.info("accelerator: %s (%d devices)", _ACCELERATOR.name(), _ACCELERATOR.device_count())
    return _ACCELERATOR


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _ACCELERATOR
    _ACCELERATOR = accel


_HOST_MEMORY_KIND: Optional[str] = None
_HOST_MEMORY_PROBED = False


def host_memory_kind() -> Optional[str]:
    """The memory kind host-tiered state (ZeRO-Infinity param offload)
    should be committed to on this backend, probed ONCE per process:

    - ``"pinned_host"`` where the client advertises it (TPU; the real
      tiered memory space — device programs DMA from it);
    - the backend's host-side kind otherwise (this jax's CPU client
      advertises only ``"unpinned_host"``, which IS its default memory —
      placements become no-ops and the offload machinery still runs);
    - ``None`` when the client exposes no memory-kind API at all
      (callers must then skip memory-space placement entirely).
    """
    global _HOST_MEMORY_KIND, _HOST_MEMORY_PROBED
    if _HOST_MEMORY_PROBED:
        return _HOST_MEMORY_KIND
    import jax

    kind = None
    try:
        kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
        if "pinned_host" in kinds:
            kind = "pinned_host"
        elif "unpinned_host" in kinds:
            kind = "unpinned_host"
    except Exception:  # pragma: no cover - clients without the memories API
        kind = None
    _HOST_MEMORY_KIND = kind
    _HOST_MEMORY_PROBED = True
    if kind != "pinned_host":
        logger.info("backend advertises no pinned_host memory kind "
                    "(got %s); host-tiered params use the fallback placement",
                    kind)
    return kind


def supports_pinned_host() -> bool:
    """Whether the ZeRO-Infinity tiering path gets a REAL second memory
    space (pinned host) on this backend; False = the gated fallback is in
    effect (params stay in the backend's one memory space)."""
    return host_memory_kind() == "pinned_host"
