"""Accelerator auto-detection (reference: ``deepspeed/accelerator/real_accelerator.py``).

``get_accelerator()`` returns the process-wide accelerator, honoring the
``DS_ACCELERATOR`` env override exactly like the reference (SURVEY.md §5.6).
"""

from __future__ import annotations

import os
from typing import Optional

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.accelerator.tpu_accelerator import CPU_Accelerator, TPU_Accelerator
from deepspeed_tpu.utils.logging import logger

_ACCELERATOR: Optional[DeepSpeedAccelerator] = None


def _detect() -> DeepSpeedAccelerator:
    override = os.environ.get("DS_ACCELERATOR")
    if override:
        if override == "cpu":
            return CPU_Accelerator()
        if override in ("tpu", "axon"):
            return TPU_Accelerator(platform=override)
        raise ValueError(f"DS_ACCELERATOR={override!r} not supported (tpu, cpu)")
    import jax

    backend = jax.default_backend()
    if backend == "cpu":
        return CPU_Accelerator()
    return TPU_Accelerator(platform=backend)


def get_accelerator() -> DeepSpeedAccelerator:
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = _detect()
        logger.info("accelerator: %s (%d devices)", _ACCELERATOR.name(), _ACCELERATOR.device_count())
    return _ACCELERATOR


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _ACCELERATOR
    _ACCELERATOR = accel
