"""The TPU accelerator — the north star's ``TPU_Accelerator`` (SURVEY.md §2.1).

Reference parity target: ``deepspeed/accelerator/cuda_accelerator.py``'s role,
reimplemented over jax.devices()/memory_stats instead of torch.cuda.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):
    _name = "tpu"
    _communication_backend_name = "xla"

    def __init__(self, platform: str = "tpu"):
        self._platform = platform

    def _devices(self):
        try:
            return jax.devices(self._platform)
        except RuntimeError:
            return jax.devices()

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._platform
        return f"{self._platform}:{device_index}"

    def device(self, device_index: Optional[int] = None) -> Any:
        return self._devices()[device_index or 0]

    def device_count(self) -> int:
        return len(self._devices())

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        stats = self._memory_stats(device_index)
        return stats.get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        stats = self._memory_stats(device_index)
        return stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))

    def total_memory(self, device_index: Optional[int] = None) -> int:
        stats = self._memory_stats(device_index)
        return stats.get("bytes_limit", 0)

    def _memory_stats(self, device_index: Optional[int] = None) -> dict:
        try:
            dev = self.device(device_index)
            return dev.memory_stats() or {}
        except Exception:
            return {}

    def is_fp16_supported(self) -> bool:
        return True  # storage/compute supported; matmuls prefer bf16 on MXU

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16


class CPU_Accelerator(TPU_Accelerator):
    """CPU fallback (reference: ``cpu_accelerator.py``); used in tests via
    ``DS_ACCELERATOR=cpu`` + ``JAX_PLATFORMS=cpu`` with a virtual device mesh."""

    _name = "cpu"
    _communication_backend_name = "xla"

    def __init__(self):
        super().__init__(platform="cpu")

    def total_memory(self, device_index: Optional[int] = None) -> int:
        try:
            with open("/proc/meminfo") as fh:
                for line in fh:
                    if line.startswith("MemTotal"):
                        return int(line.split()[1]) * 1024
        except Exception:
            pass
        return 0

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return 0

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return 0

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.float32
