"""Accelerator abstraction.

TPU-native analog of the reference's ``deepspeed/accelerator/abstract_accelerator.py``
(SURVEY.md §2.1 "Accelerator abstraction"): the seam the north star says to
swap — device management, memory stats, dtype support probes,
``communication_backend_name()``, and op-builder lookup.  The reference ABC
has ~90 methods because torch exposes streams/events/allocator knobs; under
XLA many of those are meaningless (no user-visible streams — the compiler
schedules; no caching allocator — buffers are XLA-managed), so those methods
exist for API parity and are documented no-ops.
"""

from __future__ import annotations

import abc
from typing import Any, Optional


class DeepSpeedAccelerator(abc.ABC):
    _name: str = "abstract"
    _communication_backend_name: str = "xla"

    # -- device queries -----------------------------------------------------
    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str: ...

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None) -> Any: ...

    @abc.abstractmethod
    def device_count(self) -> int: ...

    def current_device(self) -> int:
        return 0

    def current_device_name(self) -> str:
        return self.device_name(self.current_device())

    def set_device(self, device_index: int) -> None:  # no-op: XLA places buffers
        pass

    def is_available(self) -> bool:
        return self.device_count() > 0

    # -- synchronization ----------------------------------------------------
    def synchronize(self, device_index: Optional[int] = None) -> None:
        import jax
        import jax.numpy as jnp

        jax.device_get(jnp.zeros(()))

    # Streams/events: XLA has no user streams; parity no-ops.
    def Stream(self, *args, **kwargs):
        return None

    def stream(self, stream):
        import contextlib

        return contextlib.nullcontext()

    def current_stream(self, device_index: Optional[int] = None):
        return None

    def default_stream(self, device_index: Optional[int] = None):
        return None

    def Event(self, *args, **kwargs):
        return None

    # -- RNG ----------------------------------------------------------------
    def manual_seed(self, seed: int) -> None:
        self._seed = seed

    def initial_seed(self) -> int:
        return getattr(self, "_seed", 0)

    # -- memory -------------------------------------------------------------
    @abc.abstractmethod
    def memory_allocated(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def total_memory(self, device_index: Optional[int] = None) -> int: ...

    def available_memory(self, device_index: Optional[int] = None) -> int:
        return self.total_memory(device_index) - self.memory_allocated(device_index)

    def empty_cache(self) -> None:  # XLA manages buffers; parity no-op
        pass

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        pass

    def memory_stats(self, device_index: Optional[int] = None) -> dict:
        return {
            "allocated_bytes.all.current": self.memory_allocated(device_index),
            "allocated_bytes.all.peak": self.max_memory_allocated(device_index),
        }

    # -- dtype support ------------------------------------------------------
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def supported_dtypes(self):
        import jax.numpy as jnp

        out = [jnp.float32]
        if self.is_bf16_supported():
            out.append(jnp.bfloat16)
        if self.is_fp16_supported():
            out.append(jnp.float16)
        return out

    # -- misc ---------------------------------------------------------------
    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    def pin_memory(self, tensor, align_bytes: int = 1):
        return tensor  # host numpy arrays are already directly DMA-able

    def is_pinned(self, tensor) -> bool:
        return True

    def name(self) -> str:
        return self._name

    def create_op_builder(self, op_name: str):
        from deepspeed_tpu.ops.op_builder import get_op_builder

        builder = get_op_builder(op_name)
        return builder() if builder is not None else None

    def get_op_builder(self, op_name: str):
        from deepspeed_tpu.ops.op_builder import get_op_builder

        return get_op_builder(op_name)
