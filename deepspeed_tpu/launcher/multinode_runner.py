"""Multi-node transports for the launcher CLI.

Reference: ``deepspeed/launcher/multinode_runner.py`` (SURVEY.md §2.1
"Multinode runners") — each runner converts (hostfile resources, agent
command) into remote launch processes.  The ssh/pdsh runners start the
per-host agent (``launch.py``) with the right ``--node_rank``; mpirun/srun
delegate process placement to the scheduler and launch the user script
directly (ranks discovered from the scheduler env by
``comm.init_distributed``).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
from typing import Callable, Dict, List

from deepspeed_tpu.utils.logging import logger


class MultiNodeRunner:
    name = "base"

    def __init__(self, args, exports: Dict[str, str]):
        self.args = args
        self.exports = exports

    def backend_exists(self) -> bool:
        return True

    def export_cmd(self) -> List[str]:
        out = []
        for k, v in sorted(self.exports.items()):
            out.append(f"export {k}={shlex.quote(v)};")
        return out

    def launch(self, active_resources, build_launch_command: Callable
               ) -> List[subprocess.Popen]:
        raise NotImplementedError


class SSHRunner(MultiNodeRunner):
    """One ssh session per host running the launch agent (default transport;
    the reference's PDSH runner without the pdsh dependency)."""

    name = "ssh"

    def backend_exists(self) -> bool:
        return _which("ssh")

    def launch(self, active_resources, build_launch_command):
        procs = []
        for node_rank, host in enumerate(active_resources):
            agent_cmd = build_launch_command(self.args, active_resources, node_rank)
            remote = " ".join(self.export_cmd()
                              + [f"cd {shlex.quote(os.getcwd())};"]
                              + [shlex.quote(c) for c in agent_cmd])
            if host in ("localhost", "127.0.0.1"):
                cmd = ["bash", "-c", remote]
            else:
                cmd = ["ssh", "-o", "StrictHostKeyChecking=no", host, remote]
            logger.info("ssh launch [%s]: %s", host, remote)
            procs.append(subprocess.Popen(cmd))
        return procs


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out (reference default): ONE pdsh invocation covering every
    host.  The agent command uses ``--node_rank=-1``, which makes
    ``launch.py`` resolve its own rank from the local hostname against the
    world_info mapping (every host runs the identical command line)."""

    name = "pdsh"

    def backend_exists(self) -> bool:
        return _which("pdsh")

    def launch(self, active_resources, build_launch_command):
        env = {**os.environ, "PDSH_RCMD_TYPE": "ssh"}
        agent_cmd = build_launch_command(self.args, active_resources, node_rank=-1)
        remote = " ".join(self.export_cmd()
                          + [f"cd {shlex.quote(os.getcwd())};"]
                          + [shlex.quote(c) for c in agent_cmd])
        cmd = ["pdsh", "-S", "-w", ",".join(active_resources)] + (
            shlex.split(self.args.launcher_args) if self.args.launcher_args else []
        ) + [remote]
        logger.info("pdsh launch: %s", " ".join(cmd[:5]))
        return [subprocess.Popen(cmd, env=env)]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun placement: one rank per slot; ranks read OMPI_COMM_WORLD_RANK /
    OMPI_COMM_WORLD_SIZE (honored by ``comm.init_distributed``)."""

    name = "openmpi"

    def backend_exists(self) -> bool:
        return _which("mpirun")

    MPI_BIN = "mpirun"

    def launch(self, active_resources, build_launch_command):
        total = sum(len(s) for s in active_resources.values())
        hostlist = ",".join(f"{h}:{len(s)}" for h, s in active_resources.items())
        cmd = [self.MPI_BIN, "-n", str(total), "--host", hostlist,
               "--allow-run-as-root"]
        for k, v in sorted(self.exports.items()):
            cmd += ["-x", f"{k}={v}"]
        cmd += ["-x", f"MASTER_ADDR={self.args.master_addr}",
                "-x", f"MASTER_PORT={self.args.master_port}",
                "-x", "DS_AUTO_MPI_DISCOVERY=1"]
        if self.args.launcher_args:
            cmd += shlex.split(self.args.launcher_args)
        cmd += [sys.executable, "-u", self.args.user_script] + self.args.user_args
        logger.info("mpirun launch: %s", " ".join(cmd))
        return [subprocess.Popen(cmd)]


class SlurmRunner(MultiNodeRunner):
    """srun placement: ranks read SLURM_PROCID / SLURM_NTASKS."""

    name = "slurm"

    def backend_exists(self) -> bool:
        return _which("srun")

    def launch(self, active_resources, build_launch_command):
        total = sum(len(s) for s in active_resources.values())
        # the include/exclude filters were already applied to
        # active_resources; srun gets the resulting nodelist
        cmd = ["srun", "-n", str(total), "-w", ",".join(active_resources)]
        if self.args.launcher_args:
            cmd += shlex.split(self.args.launcher_args)
        env = {**os.environ, **self.exports,
               "MASTER_ADDR": self.args.master_addr,
               "MASTER_PORT": str(self.args.master_port),
               "DS_AUTO_MPI_DISCOVERY": "1"}
        cmd += [sys.executable, "-u", self.args.user_script] + self.args.user_args
        logger.info("srun launch: %s", " ".join(cmd))
        return [subprocess.Popen(cmd, env=env)]


class IMPIRunner(OpenMPIRunner):
    name = "impi"
    MPI_BIN = "mpiexec"

    def backend_exists(self) -> bool:
        return _which("mpiexec")


_RUNNERS = {r.name: r for r in
            (SSHRunner, PDSHRunner, OpenMPIRunner, SlurmRunner, IMPIRunner)}


def get_runner(name: str, args, exports: Dict[str, str]) -> MultiNodeRunner:
    cls = _RUNNERS.get(name)
    if cls is None:
        raise ValueError(f"unknown launcher {name!r}; choices: {sorted(_RUNNERS)}")
    runner = cls(args, exports)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend {name!r} not found on PATH")
    return runner


def _which(prog: str) -> bool:
    from shutil import which

    return which(prog) is not None
