"""Launcher stack: ``deepspeed`` CLI, per-host agent, multinode transports.

Reference: ``deepspeed/launcher/`` (SURVEY.md §2.1 rows "Launcher CLI",
"Node launcher", "Multinode runners"; §3.1 call stack).
"""

from deepspeed_tpu.launcher.runner import (fetch_hostfile, main,  # noqa: F401
                                           parse_args, parse_inclusion_exclusion)
