"""Per-host launcher agent.

TPU-native analog of the reference's ``deepspeed/launcher/launch.py``
(SURVEY.md §2.1 "Node launcher", §3.1): spawns one subprocess per local slot,
exports the env contract ``comm.init_distributed`` consumes —
``COORDINATOR_ADDRESS`` (host:port), ``RANK`` (global process id),
``LOCAL_RANK``, ``WORLD_SIZE`` (total process count) — and supervises the
children: any child dying propagates SIGTERM to the rest and the agent exits
with the failing child's code (fail-fast, SURVEY.md §5.3).

On a real TPU pod each process drives its host's chips and jax derives device
counts itself; WORLD_SIZE here is the *process* world, matching
``jax.distributed.initialize(num_processes=...)``.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import subprocess
import sys
import time
from collections import OrderedDict
from typing import List

from deepspeed_tpu.utils.logging import logger

PROCESS_POLL_INTERVAL_S = 0.25


def parse_args(args=None):
    parser = argparse.ArgumentParser(prog="deepspeed_tpu.launcher.launch")
    parser.add_argument("--world_info", type=str, required=True,
                        help="base64-encoded {host: [slot ids]} dict")
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--no_local_rank", action="store_true")
    parser.add_argument("--enable_each_rank_log", type=str, default=None)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def decode_world_info(encoded: str) -> "OrderedDict[str, List[int]]":
    return OrderedDict(json.loads(base64.urlsafe_b64decode(encoded.encode())))


def main(args=None) -> int:
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    hosts = list(world_info)
    if args.node_rank < 0:
        # pdsh mode: every host runs the same command line; resolve our rank
        # from the local hostname against the world_info mapping
        import socket

        hostname = socket.gethostname()
        short = hostname.split(".")[0]
        exact = [i for i, h in enumerate(hosts) if h in (hostname, short)]
        if exact:
            candidates = exact
        else:  # prefix fallback for clusters with decorated hostnames
            candidates = [i for i, h in enumerate(hosts)
                          if hostname.startswith(h)]
        if not candidates:
            raise ValueError(f"cannot resolve node_rank: hostname {hostname!r} "
                             f"not in world_info hosts {hosts}")
        if len(candidates) > 1:
            raise ValueError(f"ambiguous node_rank: hostname {hostname!r} "
                             f"prefix-matches hosts "
                             f"{[hosts[i] for i in candidates]}")
        args.node_rank = candidates[0]
        logger.info("resolved node_rank=%d from hostname %s", args.node_rank,
                    hostname)
    if not (0 <= args.node_rank < len(hosts)):
        raise ValueError(f"node_rank {args.node_rank} out of range for {hosts}")
    local_slots = world_info[hosts[args.node_rank]]
    global_rank_offset = sum(len(world_info[h]) for h in hosts[: args.node_rank])
    world_size = sum(len(s) for s in world_info.values())

    log_dir = args.enable_each_rank_log
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)

    children: List[subprocess.Popen] = []

    def terminate_all(sig=signal.SIGTERM):
        for p in children:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except ProcessLookupError:
                    pass

    def handle_signal(signum, frame):
        logger.info("launch agent received signal %d; terminating children", signum)
        terminate_all()
        sys.exit(128 + signum)

    # save the previous handlers: main() is also called in-process (tests,
    # embedding callers), where leaking this handler would hijack SIGTERM
    # for the rest of the host process
    prev_term = signal.signal(signal.SIGTERM, handle_signal)
    prev_int = signal.signal(signal.SIGINT, handle_signal)
    try:
        return _spawn_and_supervise(args, local_slots, global_rank_offset,
                                    world_size, log_dir, children,
                                    terminate_all)
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)


def _spawn_and_supervise(args, local_slots, global_rank_offset, world_size,
                         log_dir, children, terminate_all) -> int:
    for local_rank, _slot in enumerate(local_slots):
        global_rank = global_rank_offset + local_rank
        env = dict(os.environ)
        env["COORDINATOR_ADDRESS"] = f"{args.master_addr}:{args.master_port}"
        env["MASTER_ADDR"] = args.master_addr
        env["MASTER_PORT"] = str(args.master_port)
        env["RANK"] = str(global_rank)
        env["LOCAL_RANK"] = str(local_rank)
        env["WORLD_SIZE"] = str(world_size)
        env["DS_NODE_RANK"] = str(args.node_rank)
        env["DS_LOCAL_PROCESS_COUNT"] = str(len(local_slots))
        cmd = [sys.executable, "-u", args.user_script]
        if not args.no_local_rank:
            cmd.append(f"--local_rank={local_rank}")
        cmd.extend(args.user_args)
        stdout = stderr = None
        if log_dir:
            stdout = open(os.path.join(log_dir, f"rank{global_rank}.out"), "w")
            stderr = open(os.path.join(log_dir, f"rank{global_rank}.err"), "w")
        logger.info("launching rank %d (local %d): %s", global_rank, local_rank,
                    " ".join(cmd))
        children.append(subprocess.Popen(cmd, env=env, stdout=stdout, stderr=stderr))

    # Supervise: fail-fast on the first non-zero exit (reference semantics).
    rc = 0
    alive = set(range(len(children)))
    while alive:
        time.sleep(PROCESS_POLL_INTERVAL_S)
        for i in sorted(alive):
            code = children[i].poll()
            if code is None:
                continue
            alive.discard(i)
            if code != 0:
                logger.error("rank %d exited with code %d; terminating "
                             "remaining ranks", global_rank_offset + i, code)
                terminate_all()
                for j in sorted(alive):
                    children[j].wait()
                return code
    return rc


if __name__ == "__main__":
    sys.exit(main())
