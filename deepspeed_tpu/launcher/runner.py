"""``deepspeed`` CLI — cluster entry point.

TPU-native analog of the reference's ``deepspeed/launcher/runner.py``
(SURVEY.md §2.1 "Launcher CLI", §3.1): same UX — hostfile with ``slots=N``
syntax, ``--include``/``--exclude`` resource filters, ``--num_nodes``/
``--num_procs`` limits — but the per-process env contract it produces is the
one ``deepspeed_tpu.comm.init_distributed`` consumes
(``COORDINATOR_ADDRESS``/``RANK``/``WORLD_SIZE``), feeding
``jax.distributed.initialize`` instead of a torch ProcessGroup.

Single node: spawns the per-host agent (``launch.py``) directly.  Multi node:
builds one agent command per host and dispatches via a multinode runner
(ssh/pdsh/mpirun/srun — ``multinode_runner.py``).  On real TPU pods the usual
path is one process per host launched by the platform (GKE/queued resources),
where jax self-discovers the coordinator; this CLI covers the
reference-parity manual path and CPU/dev clusters.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ("PYTHON", "PATH", "LD_LIBRARY", "JAX_", "XLA_", "TPU_", "DS_",
               "LIBTPU_", "HF_", "NCCL_")  # prefixes forwarded to remote hosts
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        prog="deepspeed",
        description="deepspeed_tpu distributed launcher (reference-parity CLI)")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile with lines '<host> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help='Resources to include, e.g. "host1@host2:0,2"')
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help='Resources to exclude, e.g. "host1:1"')
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Limit the run to the first N nodes")
    parser.add_argument("--num_gpus", "--num_procs", dest="num_procs", type=int,
                        default=-1, help="Processes per node (reference: GPUs)")
    parser.add_argument("--master_addr", type=str, default="",
                        help="Coordinator address (default: first host / localhost)")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "pdsh", "openmpi", "slurm", "impi"],
                        help="Multi-node transport")
    parser.add_argument("--launcher_args", type=str, default="",
                        help="Extra flags for the multi-node transport")
    parser.add_argument("--force_multi", action="store_true",
                        help="Treat the run as multi-node even for one host")
    parser.add_argument("--no_local_rank", action="store_true",
                        help="Do not append --local_rank to the user script")
    parser.add_argument("--save_pid", action="store_true",
                        help="Write a PID file for this launcher")
    parser.add_argument("--enable_each_rank_log", type=str, default=None,
                        help="Directory for per-rank stdout/stderr logs")
    parser.add_argument("user_script", type=str, help="User training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> "OrderedDict[str, int]":
    """Parse '<hostname> slots=<n>' lines (reference hostfile format)."""
    resources: "OrderedDict[str, int]" = OrderedDict()
    if not os.path.isfile(hostfile_path):
        return resources
    with open(hostfile_path) as fh:
        for raw in fh:
            line = raw.split("#")[0].strip()
            if not line:
                continue
            try:
                host, slots = line.split()
                key, count = slots.split("=")
                if key != "slots":
                    raise ValueError(f"expected 'slots=<n>', got {slots!r}")
                resources[host] = int(count)
            except ValueError as exc:
                raise ValueError(f"Hostfile ({hostfile_path}) has a malformed "
                                 f"line: {raw!r}") from exc
    return resources


def _parse_filter(spec: str) -> Dict[str, Optional[List[int]]]:
    """Parse 'host1@host2:0,2' → {host1: None, host2: [0, 2]} (None = all)."""
    out: Dict[str, Optional[List[int]]] = {}
    for part in filter(None, spec.split("@")):
        if ":" in part:
            host, slot_str = part.split(":")
            out[host.strip()] = sorted(int(s) for s in slot_str.split(",") if s)
        else:
            out[part.strip()] = None
    return out


def parse_inclusion_exclusion(resource_pool: "OrderedDict[str, int]",
                              inclusion: str, exclusion: str
                              ) -> "OrderedDict[str, List[int]]":
    """Apply --include/--exclude to the hostfile pool → {host: [slot ids]}.

    Reference semantics: include and exclude are mutually exclusive; a filter
    naming a host without slots means the whole host.
    """
    if inclusion and exclusion:
        raise ValueError("--include and --exclude are mutually exclusive")
    active: "OrderedDict[str, List[int]]" = OrderedDict(
        (h, list(range(n))) for h, n in resource_pool.items())
    if inclusion:
        filt = _parse_filter(inclusion)
        picked: "OrderedDict[str, List[int]]" = OrderedDict()
        for host, slots in filt.items():
            if host not in active:
                raise ValueError(f"--include host {host} not in hostfile")
            use = active[host] if slots is None else slots
            bad = set(use) - set(active[host])
            if bad:
                raise ValueError(f"--include slots {sorted(bad)} not available on {host}")
            picked[host] = sorted(use)
        return picked
    if exclusion:
        filt = _parse_filter(exclusion)
        for host, slots in filt.items():
            if host not in active:
                raise ValueError(f"--exclude host {host} not in hostfile")
            if slots is None:
                del active[host]
            else:
                remaining = [s for s in active[host] if s not in set(slots)]
                if remaining:
                    active[host] = remaining
                else:
                    del active[host]
    return active


def encode_world_info(active_resources: "OrderedDict[str, List[int]]") -> str:
    return base64.urlsafe_b64encode(
        json.dumps(active_resources).encode()).decode()


def _load_persistent_env(path: str = DEEPSPEED_ENVIRONMENT_NAME) -> Dict[str, str]:
    """Read KEY=VALUE lines from .deepspeed_env (reference env passthrough)."""
    env: Dict[str, str] = {}
    for base in (os.getcwd(), os.path.expanduser("~")):
        p = os.path.join(base, path)
        if os.path.isfile(p):
            with open(p) as fh:
                for line in fh:
                    line = line.strip()
                    if line and not line.startswith("#") and "=" in line:
                        k, v = line.split("=", 1)
                        env[k] = v
            break
    return env


def exported_env() -> Dict[str, str]:
    """Env vars forwarded to launched processes: allow-listed prefixes +
    .deepspeed_env contents."""
    env = {k: v for k, v in os.environ.items()
           if any(k.startswith(p) for p in EXPORT_ENVS)}
    env.update(_load_persistent_env())
    return env


def build_launch_command(args, active_resources: "OrderedDict[str, List[int]]",
                         node_rank: int = 0) -> List[str]:
    """Per-host agent command (launch.py) for a given node rank."""
    world_info = encode_world_info(active_resources)
    cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
           f"--world_info={world_info}",
           f"--node_rank={node_rank}",
           f"--master_addr={args.master_addr}",
           f"--master_port={args.master_port}"]
    if args.no_local_rank:
        cmd.append("--no_local_rank")
    if args.enable_each_rank_log:
        cmd.append(f"--enable_each_rank_log={args.enable_each_rank_log}")
    cmd.append(args.user_script)
    cmd.extend(args.user_args)
    return cmd


def main(args=None) -> int:
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)
    if not resource_pool:
        # No hostfile: single-node run with the local processor count.
        nproc = args.num_procs if args.num_procs > 0 else 1
        resource_pool = OrderedDict([("localhost", nproc)])
    active = parse_inclusion_exclusion(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[: args.num_nodes])
    if args.num_procs > 0:
        active = OrderedDict((h, s[: args.num_procs]) for h, s in active.items())
    if not active:
        raise ValueError("no resources left after applying filters")
    if not args.master_addr:
        first = next(iter(active))
        args.master_addr = "127.0.0.1" if first in ("localhost", "127.0.0.1") else first
    logger.info("launcher: %d node(s), world size %d, coordinator %s:%d",
                len(active), sum(len(s) for s in active.values()),
                args.master_addr, args.master_port)

    multi_node = args.force_multi or len(active) > 1
    if args.save_pid:
        with open(f"/tmp/ds_launcher.{os.getpid()}.pid", "w") as fh:
            fh.write(str(os.getpid()))
    if not multi_node:
        cmd = build_launch_command(args, active, node_rank=0)
        logger.info("cmd = %s", " ".join(shlex.quote(c) for c in cmd))
        env = {**os.environ, **exported_env()}
        result = subprocess.run(cmd, env=env)
        return result.returncode

    from deepspeed_tpu.launcher.multinode_runner import get_runner

    runner = get_runner(args.launcher, args, exported_env())
    procs = runner.launch(active, build_launch_command)
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
