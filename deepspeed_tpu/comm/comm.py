"""The ``deepspeed_tpu.comm`` façade.

TPU-native analog of the reference's ``deepspeed/comm/comm.py`` +
``deepspeed/comm/torch.py`` (SURVEY.md §2.1 "comm API", §5.8): the same
module-level function surface (``init_distributed``, ``get_rank``,
``get_world_size``, ``all_reduce``, ``all_gather``, ``reduce_scatter``,
``all_to_all_single``, ``broadcast``, ``barrier``) but backed by XLA
collectives over the device mesh instead of torch.distributed/NCCL.

Two tiers, matching SURVEY.md §5.8's design note:

1. **In-jit named-axis collectives** — ``psum``/``all_gather``/
   ``psum_scatter``/``all_to_all``/``ppermute`` wrappers that take a mesh-axis
   name.  These are what the runtime uses on the hot path (inside
   ``jit``/``shard_map``); XLA schedules them onto ICI/DCN and overlaps them
   with compute.  Each wrapper records trace-time metadata into the
   ``CommsLogger`` (op, shape, bytes) — latency attribution comes from the
   profiler, not eager timers, because there is no eager hot path to time.

2. **Eager control-plane ops** — process-level broadcast/barrier built on
   ``jax.experimental.multihost_utils`` for config agreement, checkpoint
   coordination, etc.  These are NOT for gradients.

Rank semantics on TPU: ``get_rank()`` is the JAX *process* index (one per
host); ``get_world_size()`` is the global *device* count, which is what the
batch triad and ZeRO partitioning math need (the reference's rank==GPU model
maps to device, not process, on TPU).
"""

from __future__ import annotations

import datetime
import os
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.mesh import (MESH_AXES, build_mesh, get_global_mesh, mesh_from_config,
                                     set_global_mesh)
# Per-collective accounting (monitor/comms.py): the old in-file CommsLogger
# grew into CommMetrics — same trace-time counts/bytes/log_summary surface,
# now also feeding the ds_comm_* registry series (docs/OBSERVABILITY.md).
from deepspeed_tpu.monitor.comms import CommMetrics as CommsLogger  # noqa: F401
from deepspeed_tpu.monitor.comms import comm_metrics as comms_logger
from deepspeed_tpu.profiling.trace import scope as _scope
from deepspeed_tpu.utils.logging import logger

_INITIALIZED = False

ReduceOp = type("ReduceOp", (), {"SUM": "sum", "AVG": "avg", "MAX": "max", "MIN": "min", "PRODUCT": "prod"})


def init_distributed(dist_backend: str = "xla", auto_mpi_discovery: bool = False,
                     distributed_port: int = 29500, verbose: bool = True,
                     timeout: datetime.timedelta = datetime.timedelta(minutes=30),
                     init_method: Optional[str] = None, dist_init_required: Optional[bool] = None,
                     config: Optional[Any] = None, rank: int = -1, world_size: int = -1) -> None:
    """Bootstrap multi-host JAX and the global mesh.

    Reference parity: ``deepspeed.comm.init_distributed`` (SURVEY.md §3.2).
    On a single host this is a cheap no-op apart from mesh construction; on a
    TPU pod it calls ``jax.distributed.initialize`` (coordinator discovered
    from TPU metadata or ``COORDINATOR_ADDRESS``/``MASTER_ADDR`` env, matching
    the reference launcher's env contract).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    _discover_scheduler_env(auto_mpi_discovery)
    # IMPORTANT: decide from env only — any jax query (process_count etc.)
    # would initialize the XLA backend and make jax.distributed.initialize
    # raise.  jax auto-detects all args on TPU pods when passed None.
    multi_host = (os.environ.get("COORDINATOR_ADDRESS") or
                  (os.environ.get("MASTER_ADDR") and os.environ.get("WORLD_SIZE")))
    already_up = jax._src.distributed.global_state.client is not None
    if multi_host and not already_up:
        coord = os.environ.get("COORDINATOR_ADDRESS") or \
            f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', distributed_port)}"
        nproc = int(os.environ["WORLD_SIZE"]) if "WORLD_SIZE" in os.environ else \
            (world_size if world_size > 0 else None)
        pid = int(os.environ["RANK"]) if "RANK" in os.environ else (rank if rank >= 0 else None)
        logger.info("jax.distributed.initialize(coordinator=%s, num_processes=%s, process_id=%s)",
                    coord, nproc, pid)
        if (os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
                or os.environ.get("DS_ACCELERATOR") == "cpu"):
            # Multi-process CPU "pods" (dev clusters, the launcher e2e test)
            # need a cross-process collectives impl; harmless if unsupported.
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass
        try:
            jax.distributed.initialize(coordinator_address=coord, num_processes=nproc,
                                       process_id=pid)
        except RuntimeError as exc:
            # Backend already initialized (e.g. tests touched jax first):
            # surface loudly but keep single-process semantics usable.
            logger.error("jax.distributed.initialize failed: %s", exc)
    if config is not None and getattr(config, "mesh", None) is not None:
        set_global_mesh(mesh_from_config(config.mesh))
    _INITIALIZED = True
    if verbose:
        logger.info("init_distributed: backend=%s processes=%d devices=%d",
                    dist_backend, jax.process_count(), jax.device_count())


def _discover_scheduler_env(auto_mpi_discovery: bool = True) -> None:
    """Map mpirun/srun rank env onto the RANK/WORLD_SIZE contract the
    launcher agent exports (reference: ``auto_mpi_discovery`` /
    mpi_discovery in comm.py — here env-only, no mpi4py import).

    Gated on ``auto_mpi_discovery`` or the ``DS_AUTO_MPI_DISCOVERY`` marker
    the mpirun/srun runners export — an unrelated process running inside a
    scheduler allocation must not be dragged into a phantom world.
    """
    if not (auto_mpi_discovery or os.environ.get("DS_AUTO_MPI_DISCOVERY")):
        return
    if "RANK" in os.environ and "WORLD_SIZE" in os.environ:
        return
    for rank_key, size_key in (("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
                               ("PMI_RANK", "PMI_SIZE"),
                               ("SLURM_PROCID", "SLURM_NTASKS")):
        if rank_key in os.environ and size_key in os.environ:
            os.environ.setdefault("RANK", os.environ[rank_key])
            os.environ.setdefault("WORLD_SIZE", os.environ[size_key])
            logger.info("discovered scheduler env: RANK=%s WORLD_SIZE=%s (from %s)",
                        os.environ["RANK"], os.environ["WORLD_SIZE"], rank_key)
            return


def is_initialized() -> bool:
    return _INITIALIZED


def get_rank(group: Any = None) -> int:
    """Caller's rank; with ``group=`` a ProcessGroup, the caller's position
    in the group (reference semantics: -1 when not a member).  Group ranks
    are PROCESS indices for this query; a device-id group on a multi-host
    pod is ambiguous and raises ValueError (build the group with
    ``new_group(..., kind='process')``)."""
    if group is not None and hasattr(group, "ranks"):
        if (jax.process_count() > 1
                and getattr(group, "kind", "device") != "process"):
            # a device-id group has no process-membership meaning on a pod:
            # device 1 being in the group says nothing about process 1.
            # Returning -1 here would silently disable every
            # ``get_rank(group) == 0`` gate, so fail loudly instead.
            raise ValueError(
                f"get_rank(group=): group {group.ranks} is a device-id "
                "group; process membership is undefined on a multi-process "
                "world — build it with new_group(..., kind='process')")
        me = jax.process_index()
        return group.ranks.index(me) if me in group.ranks else -1
    return jax.process_index()


def get_local_rank() -> int:
    # LOCAL_RANK is exported by the launcher agent (launcher/launch.py); on
    # TPU pods the platform runs one process per host, so 0 is correct there.
    return int(os.environ.get("LOCAL_RANK", 0))


def get_world_size(group: Any = None) -> int:
    """Device world; with ``group=`` a ProcessGroup, the group size."""
    if group is not None and hasattr(group, "size"):
        return group.size()
    return jax.device_count()


def get_process_count() -> int:
    return jax.process_count()


# ---------------------------------------------------------------------------
# Tier 1: in-jit named-axis collectives (the hot path).
# Use inside jit / shard_map bodies with a mesh axis name (or tuple of names).
# ---------------------------------------------------------------------------

def all_reduce(x, axis: Union[str, Sequence[str]] = ("dp", "fsdp"), op: str = "sum"):
    """psum/pmax/pmin over a named mesh axis (reference: dist.all_reduce)."""
    comms_logger.record("all_reduce", axis, x)
    with _scope("ds_comm_all_reduce"):
        if op in ("sum", ReduceOp.SUM):
            return lax.psum(x, axis)
        if op in ("avg", ReduceOp.AVG):
            return lax.pmean(x, axis)
        if op in ("max", ReduceOp.MAX):
            return lax.pmax(x, axis)
        if op in ("min", ReduceOp.MIN):
            return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x, axis: Union[str, Sequence[str]], gather_dim: int = 0, tiled: bool = True):
    """all_gather along a named axis (reference: all_gather_into_tensor)."""
    comms_logger.record("all_gather", axis, x)
    with _scope("ds_comm_all_gather"):
        return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: Union[str, Sequence[str]], scatter_dim: int = 0):
    """psum_scatter (reference: reduce_scatter_tensor) — the ZeRO-2/3 grad op."""
    comms_logger.record("reduce_scatter", axis, x)
    with _scope("ds_comm_reduce_scatter"):
        return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def all_to_all_single(x, axis: str, split_dim: int = 0, concat_dim: int = 0,
                      quantized: bool = False, quant_block: int = 256):
    """all_to_all (reference: all_to_all_single) — MoE dispatch / Ulysses.

    ``quantized=True`` (the ``comm_quantization.all_to_all`` seam) ships
    blockwise int8 codes + fp32 scales instead of the dense payload
    (collectives_q.q_all_to_all — quant/dequant fused into the caller's
    program, ~2-4x fewer wire bytes, both byte series recorded)."""
    if quantized:
        from deepspeed_tpu.comm.collectives_q import q_all_to_all

        return q_all_to_all(x, axis, split_dim, concat_dim,
                            block=quant_block)
    comms_logger.record("all_to_all", axis, x)
    with _scope("ds_comm_all_to_all"):
        return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=True)


def ppermute(x, axis: str, perm):
    """Point-to-point ring shift (reference: send/recv pairs in pipe/p2p.py)."""
    comms_logger.record("ppermute", axis, x)
    with _scope("ds_comm_ppermute"):
        return lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


class ProcessGroup:
    """A device subset usable as a collective group (reference:
    ``dist.new_group(ranks)``; VERDICT r2 weak #7 — named mesh axes replace
    mesh-aligned groups, this covers the non-mesh-aligned subsets).

    Backed by a one-axis sub-``Mesh`` over the chosen devices: use
    ``group.mesh`` with ``shard_map`` and ``group.axis`` ("sub") as the
    collective axis, or the eager helpers below for control-plane ops.
    """

    AXIS = "sub"

    def __init__(self, ranks, kind: str = "device"):
        from jax.sharding import Mesh

        assert kind in ("device", "process"), kind
        self.kind = kind
        self.ranks = list(ranks)
        if kind == "process":
            n = jax.process_count()
            missing = [r for r in ranks if not 0 <= r < n]
            if missing:
                raise ValueError(f"process ranks {missing} out of range "
                                 f"({n} processes)")
            self.mesh = None
            self.axis = None
            return
        devices = jax.devices()
        missing = [r for r in ranks if not 0 <= r < len(devices)]
        if missing:
            raise ValueError(f"ranks {missing} out of range "
                             f"({len(devices)} devices)")
        self.mesh = Mesh([devices[r] for r in ranks], (self.AXIS,))
        self.axis = self.AXIS

    def size(self) -> int:
        return len(self.ranks)

    def all_reduce(self, values, op: str = "sum"):
        """Eager allreduce over the subset (single-controller control
        plane): ``values`` carries ONE entry per group member (leading dim
        == ``size()``, or a list of per-member values); entry i is placed on
        member i's device and the reduction runs over the sub-mesh axis.
        Multi-process eager reduction is not supported — inside jit, use
        ``group.mesh``/``group.axis`` with shard_map instead."""
        from jax.sharding import PartitionSpec

        import functools

        if self.mesh is None:
            raise ValueError("per-member all_reduce needs a device-id group "
                             "(this one is kind='process'); use "
                             "all_reduce_across_processes")
        if jax.process_count() > 1:
            raise NotImplementedError(
                "eager per-member all_reduce is single-controller only; "
                "multi-process callers pass THIS process's value to "
                "all_reduce_across_processes (or use group.mesh with "
                "shard_map inside jit)")
        stacked = (jnp.stack([jnp.asarray(v) for v in values])
                   if isinstance(values, (list, tuple))
                   else jnp.asarray(values))
        if stacked.ndim == 0:
            raise ValueError("all_reduce takes one value PER MEMBER (leading "
                             f"dim {self.size()}), got a scalar")
        if stacked.shape[0] != self.size():
            raise ValueError(f"expected {self.size()} per-member values, "
                             f"got leading dim {stacked.shape[0]}")

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=PartitionSpec(self.AXIS),
                           out_specs=PartitionSpec(), check_vma=False)
        def _reduce(xl):
            return all_reduce(xl, self.AXIS, op=op)[0]

        placed = jax.device_put(
            stacked, jax.sharding.NamedSharding(self.mesh,
                                                PartitionSpec(self.AXIS)))
        return _reduce(placed)

    def all_reduce_across_processes(self, value, op: str = "sum"):
        """Eager control-plane reduce over the member PROCESSES on a real
        pod: every process passes its own ``value``; members' contributions
        are reduced and the result returned everywhere.  ``ranks`` MUST be
        process indices here (the device-subset view of this group is
        served by ``all_reduce``/``mesh``); out-of-range ranks raise rather
        than silently misindexing.  Control plane only: per-step gradient
        traffic belongs in jit."""
        import numpy as np

        n_proc = jax.process_count()
        if n_proc > 1 and self.kind != "process":
            raise ValueError(
                "all_reduce_across_processes needs a process-index group on "
                "a multi-process world (new_group(..., kind='process')); "
                "for device subsets use all_reduce (per-member values) or "
                "group.mesh with shard_map")
        bad = [r for r in self.ranks if r >= n_proc]
        if bad:
            raise ValueError(
                f"all_reduce_across_processes: ranks {bad} are not process "
                f"indices (process world is {n_proc})")
        arr = jnp.asarray(value)
        if n_proc == 1:
            gathered = np.asarray(arr)[None]
        else:
            from jax.experimental import multihost_utils

            gathered = np.asarray(multihost_utils.process_allgather(arr))
        subset = gathered[np.asarray(self.ranks)]
        if op in ("sum", ReduceOp.SUM):
            return jnp.asarray(subset.sum(axis=0))
        if op in ("avg", ReduceOp.AVG):
            return jnp.asarray(subset.mean(axis=0))
        if op in ("max", ReduceOp.MAX):
            return jnp.asarray(subset.max(axis=0))
        if op in ("min", ReduceOp.MIN):
            return jnp.asarray(subset.min(axis=0))
        raise ValueError(f"unsupported reduce op {op}")


def new_group(ranks, backend: Optional[str] = None,
              kind: str = "device") -> ProcessGroup:
    """Create a collective group over an arbitrary subset (reference:
    ``deepspeed.comm.new_group``).  ``kind="device"`` (default, the
    single-controller view: ranks are device ids, usable with
    ``group.mesh``/``shard_map`` and the per-member eager ``all_reduce``);
    ``kind="process"`` (multi-host control plane: ranks are process
    indices, usable with ``all_reduce_across_processes`` and the
    group-aware ``get_rank``/``get_world_size``)."""
    return ProcessGroup(ranks, kind=kind)


# ---------------------------------------------------------------------------
# Tier 2: eager control-plane ops (NOT for gradients).
# ---------------------------------------------------------------------------

def barrier(group: Any = None) -> None:
    """Synchronize all processes (reference: dist.barrier)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        with comms_logger.span("barrier", 0, world=jax.process_count()):
            multihost_utils.sync_global_devices("deepspeed_tpu.barrier")


def broadcast(x, src: int = 0, group: Any = None):
    """Broadcast a host value from process ``src`` to all processes."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        try:
            nbytes = int(x.size) * x.dtype.itemsize
            dtype = x.dtype.name
        except Exception:
            nbytes, dtype = 0, "unknown"
        with comms_logger.span("broadcast", nbytes, dtype,
                               world=jax.process_count()):
            return multihost_utils.broadcast_one_to_all(
                x, is_source=jax.process_index() == src)
    return x


def broadcast_object_list(objects, src: int = 0, group: Any = None):
    if jax.process_count() > 1:
        import pickle

        import numpy as np
        from jax.experimental import multihost_utils

        is_source = jax.process_index() == src
        payload = pickle.dumps(objects)
        true_len = jnp.asarray(len(payload), dtype=jnp.int32)
        n = int(multihost_utils.broadcast_one_to_all(true_len, is_source=is_source))
        # Receivers must present a buffer of the SOURCE's length — their own
        # payload may differ in size and is irrelevant.
        if is_source:
            buf = np.frombuffer(payload, dtype=np.uint8)
        else:
            buf = np.zeros(n, dtype=np.uint8)
        with comms_logger.span("broadcast_object", n, "uint8",
                               world=jax.process_count()):
            out = multihost_utils.broadcast_one_to_all(buf, is_source=is_source)
        return pickle.loads(bytes(bytearray(out))[:n])
    return objects


def log_summary() -> str:
    return comms_logger.log_summary()


def configure(deepspeed_config=None, **kwargs) -> None:
    if deepspeed_config is not None and getattr(deepspeed_config, "comms_logger", None):
        # Config can only turn accounting ON: every engine __init__ routes
        # through here, and a config without a comms_logger block must not
        # silently undo an explicit init_telemetry(comms=True) that came
        # first (disable programmatically via comms_logger.configure()).
        c = deepspeed_config.comms_logger
        comms_logger.configure(enabled=c.enabled or comms_logger.enabled,
                               verbose=c.verbose or comms_logger.verbose)
    elif kwargs:
        comms_logger.configure(**kwargs)
