"""Blockwise int8 quantization — the shared transport codec for every
host<->device relay and (future) quantized collective.

The ZeRO-Infinity / ZeRO-Offload streaming wall (ROADMAP item 3) and the
EQuARX-style quantized-collective layer (ROADMAP item 2) both need the same
primitive: an absmax-scaled int8 code per fixed-size block, cheap enough to
fuse into the producing/consuming program.  This module is that primitive,
in TWO twinned implementations with identical numerics:

- ``quantize_blockwise`` / ``dequantize_blockwise`` — jax-traceable, for
  the fused on-device dequant stage of the offload streaming path
  (``runtime/zero/streaming.py``) and for in-kernel stages a quantized
  collective wraps around all-gather / reduce-scatter;
- ``quantize_blockwise_np`` / ``dequantize_blockwise_np`` — numpy, for the
  host side of the relay (``OffloadedOptimizer`` int8 masters quantize on
  host; only ``q`` + ``scale`` travel the wire).

Code layout per array: the flat array is padded to a multiple of ``block``
and stored as ``q`` int8 ``[nb, block]`` plus ``scale`` fp32 ``[nb, 1]``
(scale = per-block absmax / 127).  This is the Adam8bit storage convention
(``ops/adam/adam8bit.py``), so host int8 optimizer moments round-trip
through the exact same code.  ``v``-style non-negative state uses the
sqrt-space trick from the same module (quantize sqrt(v), square on
dequant) via ``sqrt_space=True``.

Tree helpers carry a parallel (q_tree, scale_tree) pair with the SAME
treedef as the source so ``jax.tree.map`` composes, plus a static spec
tree (shape/dtype) for reassembly.

Worst-case relative error of one quantize/dequantize round-trip is
1/254 per element (half a code step at absmax scale); exact zeros stay
exact, and re-quantizing an already-dequantized block is lossless (the
values are exactly ``scale * int`` and the block absmax is unchanged).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_BLOCK = 256


# ---------------------------------------------------------------------------
# numpy twins (host side of the relay)
# ---------------------------------------------------------------------------

def quantize_blockwise_np(arr: np.ndarray, block: int = DEFAULT_BLOCK,
                          sqrt_space: bool = False
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Flat fp array -> (q int8 [nb, block], scale fp32 [nb, 1])."""
    flat = np.asarray(arr, np.float32).reshape(-1)
    if sqrt_space:
        flat = np.sqrt(flat)
    n = flat.size
    nb = -(-n // block)
    pad = nb * block - n
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    blocks = flat.reshape(nb, block)
    absmax = np.abs(blocks).max(axis=1, keepdims=True)
    scale = (absmax / 127.0).astype(np.float32)
    inv = np.where(scale > 0, 1.0 / np.where(scale > 0, scale, 1.0), 0.0)
    q = np.rint(blocks * inv).astype(np.int8)
    return q, scale


def dequantize_blockwise_np(q: np.ndarray, scale: np.ndarray, n: int,
                            sqrt_space: bool = False,
                            out: np.ndarray = None) -> np.ndarray:
    """(q, scale) -> flat fp32 [n] (into ``out`` when given)."""
    flat = (q.astype(np.float32) * scale).reshape(-1)[:n]
    if sqrt_space:
        flat = flat * flat
    if out is not None:
        out[:] = flat
        return out
    return flat


def encode_blockwise_np(arr: np.ndarray, block: int = DEFAULT_BLOCK) -> dict:
    """Wire-ready blockwise-int8 encoding of one host array: the int8
    code bytes + fp32 scale bytes plus the reassembly metadata.  This is
    the transport form the KV-page handoff (disaggregated serving) and
    any future bytes-on-a-socket caller share — the in-memory twins
    above never leave the process."""
    a = np.asarray(arr)
    q, scale = quantize_blockwise_np(a, block)
    return {"codec": "q8", "q": q.tobytes(), "scale": scale.tobytes(),
            "shape": tuple(int(s) for s in a.shape), "block": int(block)}


def decode_blockwise_np(enc: dict) -> np.ndarray:
    """Inverse of :func:`encode_blockwise_np` -> fp32 array of the
    original shape (the caller casts to its storage dtype)."""
    block = int(enc["block"])
    q = np.frombuffer(enc["q"], np.int8).reshape(-1, block)
    scale = np.frombuffer(enc["scale"], np.float32).reshape(-1, 1)
    shape = tuple(enc["shape"])
    n = int(np.prod(shape)) if shape else 1
    return dequantize_blockwise_np(q, scale, n).reshape(shape)


# ---------------------------------------------------------------------------
# jax twins (fused on-device dequant / future quantized collectives)
# ---------------------------------------------------------------------------

def quantize_blockwise(x: jax.Array, block: int = DEFAULT_BLOCK
                       ) -> Tuple[jax.Array, jax.Array]:
    """Traceable twin of :func:`quantize_blockwise_np` (linear space)."""
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.shape[0]
    nb = -(-n // block)
    flat = jnp.pad(flat, (0, nb * block - n))
    blocks = flat.reshape(nb, block)
    absmax = jnp.abs(blocks).max(axis=1, keepdims=True)
    scale = absmax / 127.0
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
    q = jnp.rint(blocks * inv).astype(jnp.int8)
    return q, scale


def dequantize_blockwise(q: jax.Array, scale: jax.Array, shape,
                         dtype=jnp.float32) -> jax.Array:
    """(q [nb, block], scale [nb, 1]) -> array of ``shape``/``dtype``.
    Fuses into the consuming program — the int8 bytes are what crossed
    the relay; the wide value only ever exists as a device transient."""
    n = int(np.prod(shape)) if shape else 1
    out = (q.astype(jnp.float32) * scale).reshape(-1)[:n]
    return out.reshape(shape).astype(dtype)


# ---------------------------------------------------------------------------
# pytree transport form
# ---------------------------------------------------------------------------

class QuantizedTree(NamedTuple):
    """A pytree quantized leaf-by-leaf: ``q``/``scale`` mirror the source
    treedef; ``spec`` holds static ShapeDtypeStructs for reassembly (and
    is NOT shipped — shapes are compile-time constants)."""

    q: Any
    scale: Any
    spec: Any

    @property
    def nbytes(self) -> int:
        """Relay payload bytes (q + scale) — the wire cost this codec
        exists to shrink."""
        return sum(int(np.prod(a.shape))
                   for a in jax.tree.leaves(self.q)) \
            + 4 * sum(int(np.prod(a.shape))
                      for a in jax.tree.leaves(self.scale))


def quantize_tree_np(tree: Any, block: int = DEFAULT_BLOCK) -> QuantizedTree:
    """Host-side: numpy pytree -> :class:`QuantizedTree` (numpy leaves)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    qs, ss, specs = [], [], []
    for leaf in leaves:
        a = np.asarray(leaf)
        q, s = quantize_blockwise_np(a, block)
        qs.append(q)
        ss.append(s)
        specs.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
    unflat = treedef.unflatten
    return QuantizedTree(unflat(qs), unflat(ss), unflat(specs))


def dequantize_tree(qt_q: Any, qt_scale: Any, spec: Any,
                    dtype=None) -> Any:
    """Traceable: (q_tree, scale_tree) -> value tree per ``spec``.  This
    is the fused dequant stage the streamed layer programs open with —
    pass ``dtype`` to override the spec dtypes (e.g. compute bf16)."""
    return jax.tree.map(
        lambda q, s, sp: dequantize_blockwise(
            q, s, sp.shape, dtype or sp.dtype),
        qt_q, qt_scale, spec)


def dequantize_tree_np(qt: QuantizedTree, dtype=None) -> Any:
    """Host twin of :func:`dequantize_tree` (numpy in, numpy out)."""
    def one(q, s, sp):
        flat = dequantize_blockwise_np(q, s, int(np.prod(sp.shape)))
        return flat.reshape(sp.shape).astype(dtype or sp.dtype)

    return jax.tree.map(one, qt.q, qt.scale, qt.spec)
