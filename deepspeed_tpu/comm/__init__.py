"""``deepspeed_tpu.comm`` — mesh-first communication layer (SURVEY.md §5.8)."""

from deepspeed_tpu.comm.collectives_q import (q_all_gather, q_all_reduce,
                                              q_all_to_all, q_reduce_scatter)
from deepspeed_tpu.comm.comm import (ProcessGroup, ReduceOp, all_gather, all_reduce,
                                     all_to_all_single, axis_index, barrier, broadcast,
                                     broadcast_object_list, comms_logger, configure,
                                     get_local_rank, get_process_count, get_rank,
                                     get_world_size, init_distributed, is_initialized,
                                     log_summary, new_group, ppermute, reduce_scatter)
from deepspeed_tpu.comm.mesh import (MESH_AXES, axis_size, batch_sharding, build_mesh,
                                     data_axes, get_data_parallel_world_size,
                                     get_expert_parallel_world_size, get_global_mesh,
                                     get_model_parallel_world_size,
                                     get_sequence_parallel_world_size, mesh_from_config,
                                     replicated, set_global_mesh)

__all__ = [
    "ReduceOp", "all_gather", "all_reduce", "all_to_all_single", "axis_index", "barrier",
    "broadcast", "broadcast_object_list", "comms_logger", "configure", "get_local_rank",
    "get_process_count", "get_rank", "get_world_size", "init_distributed", "is_initialized",
    "log_summary", "ppermute", "reduce_scatter", "MESH_AXES", "axis_size", "batch_sharding",
    "build_mesh", "data_axes", "get_data_parallel_world_size", "get_expert_parallel_world_size",
    "get_global_mesh", "get_model_parallel_world_size", "get_sequence_parallel_world_size",
    "mesh_from_config", "replicated", "set_global_mesh",
    "q_all_reduce", "q_all_gather", "q_reduce_scatter", "q_all_to_all",
]
