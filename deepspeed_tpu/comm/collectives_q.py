"""Quantized collective transport — int8 comm as a property of the comm
layer, not a ZeRO++ special (ROADMAP item 2; ZeRO++ arXiv:2306.10209,
EQuARX arXiv:2506.17615).

Every collective here ships blockwise-int8 codes + fp32 block scales
(the ``comm/quant.py`` codec — the same one the offload relay and the
int8 host masters use) instead of dense fp payloads, with the quant /
dequant stages traced INTO the surrounding program so the wide value only
ever exists as a device transient.  Callers opt in through the
``comm_quantization`` ds_config block (runtime/config.py):

- :func:`q_all_reduce` — the ZeRO stage 0/1/2 gradient sync: two-phase
  (int8 reduce-scatter via all_to_all, fp32 reduce after dequant, int8
  all-gather of the reduced chunks), with an optional **error-feedback
  residual** carried as caller state — ``residual`` in, compensated
  gradient quantized, new residual out — so the compressed grad
  all-reduce *converges* instead of accumulating bias (the 1-bit Adam
  discipline applied to int8).
- :func:`q_all_gather` / :func:`q_all_gather_flat` /
  :func:`q_all_gather_dim` — int8 parameter gathers (the ZeRO++ qwAG
  shape; the overlap schedule's per-bucket forward gathers).
- :func:`q_reduce_scatter` / :func:`q_reduce_scatter_flat` /
  :func:`q_reduce_scatter_dim` — quantize once, all_to_all the codes,
  dequantize + SUM in fp32 (one quantization error per element — the
  qgZ shape; the overlap schedule's AD-transpose reduce-scatters).
- :func:`q_all_to_all` — the MoE-dispatch / Ulysses reshard with int8
  payloads (``comm/comm.py:all_to_all_single(quantized=True)``).
- :func:`quantize_carry` / :func:`dequantize_carry` /
  :func:`q_ppermute` — the sequence-parallel ring form: quantize the KV
  chunk ONCE before the ring, rotate the *codes* (int8 bytes on every
  hop), dequantize per step for compute.  Re-quantizing a dequantized
  block is lossless (comm/quant.py), so the ring pays one quantization
  error total, not one per hop.
- :func:`q_boundary_ppermute` — the PIPELINE-boundary ring form built
  from the same three stages: each stage-to-stage activation hop is
  quantize -> rotate the codes -> dequantize.  The boundary value is
  different on every hop (each stage produces a new activation), so
  unlike the sequence ring this pays one quantization error *per hop*;
  a custom VJP sends the cotangent through the reverse ring the same
  quantized way.
- :func:`q_reshard` — the GSPMD form for callers that are NOT inside a
  manual region (MoE dispatch in ``moe/sharded_moe.py``): quantize,
  sharding-constrain the codes across the boundary so the
  GSPMD-inserted collective moves int8, dequantize; a custom VJP
  transports the cotangent the same way.

Accounting: each collective feeds BOTH the quantized byte series
(``ds_comm_<op>_bytes_total{dtype=int8|float32}`` — what crossed the
wire) and the dense twin (``ds_comm_<op>_dense_bytes_total`` — what the
dense collective would have moved) through ``monitor/comms.py``'s
trace-time ``record_q``, so the compression ratio reads off ONE trace.
Callers whose bytes are committed per-execution by the engine's analytic
comm plan (the overlap schedule) pass ``record=False`` — the two feeds
stay disjoint per path, as everywhere else in the repo.

Every exchange sits under its own unconditional ``ds_comm_*``
``named_scope`` (DSL005): toggling telemetry never changes the compiled
program, and the device-trace matcher keys per-op rows off the scope.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.comm.quant import (DEFAULT_BLOCK, dequantize_blockwise,
                                      quantize_blockwise)
from deepspeed_tpu.monitor.comms import comm_metrics
from deepspeed_tpu.profiling.trace import scope as _scope

__all__ = [
    "q_all_reduce", "q_all_reduce_tree",
    "q_all_gather", "q_all_gather_flat", "q_all_gather_dim",
    "q_reduce_scatter", "q_reduce_scatter_flat", "q_reduce_scatter_dim",
    "q_all_to_all", "q_reshard",
    "quantize_carry", "dequantize_carry", "q_ppermute",
    "q_boundary_ppermute",
    "axis_world",
]

Axis = Union[str, Sequence[str]]


def axis_world(axis: Axis) -> int:
    """Static extent of a (possibly tuple) named axis inside a manual
    region (``psum`` of a Python literal folds to the axis size)."""
    return int(lax.psum(1, axis))  # dslint: disable=DSL005 -- psum of a Python literal is constant-folded at trace time (static axis size), no device collective is emitted


def _record(op: str, axis: Axis, parts, dense_like) -> None:
    comm_metrics.record_q(op, axis, parts, dense_like)


def _axis_index(axis: Axis):
    """Linearized rank along a (possibly tuple) named axis."""
    if isinstance(axis, str):
        return lax.axis_index(axis)
    idx = jnp.zeros((), jnp.int32)
    for name in axis:
        idx = idx * lax.psum(1, name) + lax.axis_index(name)  # dslint: disable=DSL005 -- psum of a Python literal is constant-folded at trace time (static axis size), no device collective is emitted
    return idx


def _merge_leading(parts, dim: int):
    """[G, ...] stacked pieces -> their concatenation along ``dim``
    (g-major), as one moveaxis+reshape instead of a G-way slice+concat
    (which would emit O(G) ops per leaf into the traced program)."""
    moved = jnp.moveaxis(parts, 0, dim)
    shape = list(moved.shape)
    merged = shape[:dim] + [shape[dim] * shape[dim + 1]] + shape[dim + 2:]
    return moved.reshape(merged)


def _chunk_quantize(flat: jnp.ndarray, P: int, block: int):
    """Pad + split a flat fp32 vector into ``P`` equal destination chunks
    of whole blocks, quantizing each chunk separately so codes never span
    a destination boundary and scales travel with their blocks.

    Returns (q [P, nb, block], scale [P, nb, 1], chunk_len)."""
    n = flat.shape[0]
    chunk = -(-n // P)
    chunk = -(-chunk // block) * block
    flat = jnp.pad(flat, (0, P * chunk - n))
    q, s = jax.vmap(functools.partial(quantize_blockwise, block=block))(
        flat.reshape(P, chunk))
    return q, s, chunk


# ---------------------------------------------------------------------------
# all-reduce (the gradient sync) — two-phase int8 with error feedback
# ---------------------------------------------------------------------------

def q_all_reduce(x, axis: Axis, *, block: int = DEFAULT_BLOCK,
                 residual: Optional[jnp.ndarray] = None, mean: bool = True,
                 op: str = "q_all_reduce", record: bool = True):
    """Quantized all-reduce: quantize -> exchange int8+scales -> fp32
    reduce after dequant -> int8 all-gather of the reduced chunks.

    ``residual`` (same shape as ``x``, or None) is the caller-carried
    error-feedback state, TWO-LEVEL (the 1-bit worker+server discipline):
    the input is compensated (``x + residual``) before quantization, and
    the new residual carries BOTH what this rank's phase-1 quantization
    dropped AND — folded into this rank's own chunk slice — what the
    phase-2 requantization of the chunk it reduced dropped (each rank
    holds its reduced chunk and its codes locally, so the server error
    is free).  Thread it through to the next call and the quantization
    bias at both levels cancels instead of accumulating.  Returns
    ``(out, new_residual)`` where ``out`` is the ``mean`` (or sum) in
    ``x.dtype`` and ``new_residual`` is None when no residual was
    passed.
    """
    P = axis_world(axis)
    shape, dtype = x.shape, x.dtype
    n = x.size
    comp = x.astype(jnp.float32).reshape(-1)
    if residual is not None:
        comp = comp + residual.astype(jnp.float32).reshape(-1)
    if P <= 1:
        out = comp / 1.0  # already the sum == mean of one contribution
        new_res = jnp.zeros(shape, jnp.float32) if residual is not None \
            else None
        return out.reshape(shape).astype(dtype), new_res
    q, s, chunk = _chunk_quantize(comp, P, block)
    if residual is not None:
        dq = (q.astype(jnp.float32) * s).reshape(-1)[:n]
        worker_err = comp - dq
    # phase 1: int8 reduce-scatter via all_to_all — rank r collects every
    # source's chunk r and reduces it in fp32
    with _scope("ds_comm_q_all_reduce"):
        qt = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        st = lax.all_to_all(s, axis, split_axis=0, concat_axis=0,
                            tiled=False)
    reduced = (qt.astype(jnp.float32) * st).sum(axis=0).reshape(-1)  # [chunk]
    # phase 2: re-quantize the reduced chunk, int8 all-gather
    q2, s2 = quantize_blockwise(reduced, block)
    if residual is not None:
        # server-phase feedback: this rank owns chunk r of the reduced
        # SUM; what Q2 dropped re-enters through this rank's own next
        # contribution to chunk r (shifting the next sum by exactly the
        # missing amount) — without it the phase-2 rounding bias would
        # re-commit every call uncompensated
        server_err = reduced - (q2.astype(jnp.float32)
                                * s2).reshape(-1)[:chunk]
        new_res = (worker_err + lax.dynamic_update_slice(
            jnp.zeros((P * chunk,), jnp.float32), server_err,
            (_axis_index(axis) * chunk,))[:n]).reshape(shape)
    else:
        new_res = None
    if record:
        _record(op, axis, (q, s, q2, s2), x)
    with _scope("ds_comm_q_all_reduce"):
        qg = lax.all_gather(q2, axis, axis=0, tiled=False)
        sg = lax.all_gather(s2, axis, axis=0, tiled=False)
    out = (qg.astype(jnp.float32) * sg).reshape(-1)[:n]
    if mean:
        out = out / P
    return out.reshape(shape).astype(dtype), new_res


def q_all_reduce_tree(tree: Any, axis: Axis, *,
                      block: int = DEFAULT_BLOCK, residual_tree: Any = None,
                      mean: bool = True, op: str = "q_all_reduce",
                      record: bool = True) -> Tuple[Any, Any]:
    """Leaf-wise :func:`q_all_reduce` over a pytree; the residual tree
    mirrors the value tree (or None for residual-off)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    res_leaves = (jax.tree_util.tree_leaves(residual_tree)
                  if residual_tree is not None else [None] * len(leaves))
    outs, ress = [], []
    for leaf, res in zip(leaves, res_leaves):
        o, r = q_all_reduce(leaf, axis, block=block, residual=res,
                            mean=mean, op=op, record=record)
        outs.append(o)
        ress.append(r)
    out_tree = jax.tree_util.tree_unflatten(treedef, outs)
    new_res = (jax.tree_util.tree_unflatten(treedef, ress)
               if residual_tree is not None else None)
    return out_tree, new_res


# ---------------------------------------------------------------------------
# all-gather (the parameter fetch) — qwAG shape
# ---------------------------------------------------------------------------

def _q_ag_parts(local, axis: Axis, groups, block: int, op: str,
                record: bool):
    """Core int8 gather: returns (parts [G, n_local] fp32, pad)."""
    q, s = quantize_blockwise(local.astype(jnp.float32).reshape(-1),
                              block=block)
    pad = q.size - local.size
    if record:
        _record(op, axis, (q, s), local)
    with _scope("ds_comm_q_all_gather"):
        qg = lax.all_gather(q, axis, axis=0, tiled=False,
                            axis_index_groups=groups)
        sg = lax.all_gather(s, axis, axis=0, tiled=False,
                            axis_index_groups=groups)
    G = qg.shape[0]
    parts = (qg.astype(jnp.float32) * sg).reshape(G, -1)
    if pad:
        parts = parts[:, :parts.shape[1] - pad]
    return parts


def q_all_gather_flat(local, axis: Axis, groups=None,
                      block: int = DEFAULT_BLOCK,
                      op: str = "q_all_gather", record: bool = True):
    """int8 all-gather of a flat local shard -> flat fp32 concatenation
    (over the whole axis, or each subgroup when ``groups`` is given) —
    the ZeRO++ qwAG primitive."""
    return _q_ag_parts(local, axis, groups, block, op, record).reshape(-1)


def q_all_gather(x, axis: Axis, *, block: int = DEFAULT_BLOCK,
                 op: str = "q_all_gather", record: bool = True):
    """All-gather with int8 payload: each rank contributes its local x;
    result is the dequantized concatenation along dim 0, in ``x.dtype``."""
    parts = _q_ag_parts(x, axis, None, block, op, record)
    G = parts.shape[0]
    return parts.reshape((G * x.shape[0],) + x.shape[1:]).astype(x.dtype)


def q_all_gather_dim(leaf, axis: Axis, dim: int, *,
                     block: int = DEFAULT_BLOCK, op: str = "q_all_gather",
                     record: bool = True):
    """Tiled-gather twin: concatenate the dequantized per-rank shards
    along ``dim`` (the overlap schedule's per-leaf bucket gather)."""
    parts = _q_ag_parts(leaf, axis, None, block, op, record)
    G = parts.shape[0]
    parts = parts.reshape((G,) + leaf.shape)
    return _merge_leading(parts, dim).astype(leaf.dtype)


# ---------------------------------------------------------------------------
# reduce-scatter (the gradient shard) — qgZ shape
# ---------------------------------------------------------------------------

def _q_rs_shards(flat, axis: Axis, P: int, shard_elems: int, block: int,
                 op: str, record: bool, dense_like):
    """Core qgZ exchange: ``flat`` [P * shard_elems] fp32, destination r
    owns elements [r*shard_elems, (r+1)*shard_elems).  Each destination
    shard is quantized SEPARATELY (codes never span a shard boundary, so
    every rank's padding agrees), codes travel via all_to_all, and the
    receiver dequantizes + SUMS in fp32 — one quantization error per
    element, not log(P).  Returns the reduced [shard_elems] fp32 chunk."""
    rows = flat.reshape(P, shard_elems)
    q, s = jax.vmap(functools.partial(quantize_blockwise, block=block))(rows)
    if record:
        _record(op, axis, (q, s), dense_like)
    with _scope("ds_comm_q_reduce_scatter"):
        qt = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        st = lax.all_to_all(s, axis, split_axis=0, concat_axis=0,
                            tiled=False)
    parts = (qt.astype(jnp.float32) * st).reshape(P, -1)[:, :shard_elems]
    return parts.sum(axis=0)


def q_reduce_scatter_flat(full, axis: Axis, *, block: int = DEFAULT_BLOCK,
                          op: str = "q_reduce_scatter", record: bool = True):
    """[n_pad] local tensor (n_pad divisible by the axis extent) -> this
    rank's reduced [n_pad / P] shard (SUM over ranks, fp32 reduce after
    dequant) — the ZeRO++ qgRS primitive."""
    P = axis_world(axis)
    shard = full.size // P
    reduced = _q_rs_shards(full.astype(jnp.float32).reshape(-1), axis, P,
                           shard, block, op, record, full)
    return reduced.astype(full.dtype)


def q_reduce_scatter(x, axis: Axis, *, block: int = DEFAULT_BLOCK,
                     op: str = "q_reduce_scatter", record: bool = True):
    """Reduce-scatter along dim 0 (``x.shape[0]`` divisible by the axis
    extent): quantize once, all_to_all the int8 shards, dequantize and
    sum in fp32.  Returns this rank's reduced shard in ``x.dtype``."""
    P = axis_world(axis)
    shard = x.shape[0] // P
    shard_elems = shard * int(np.prod(x.shape[1:])) if x.ndim > 1 else shard
    reduced = _q_rs_shards(x.astype(jnp.float32).reshape(-1), axis, P,
                           shard_elems, block, op, record, x)
    return reduced.reshape((shard,) + x.shape[1:]).astype(x.dtype)


def q_reduce_scatter_dim(ct, axis: Axis, dim: int, *,
                         block: int = DEFAULT_BLOCK,
                         op: str = "q_reduce_scatter", record: bool = True):
    """``psum_scatter(..., scatter_dimension=dim, tiled=True)`` twin with
    int8 transport (the overlap schedule's AD-transpose reduce-scatter:
    cotangents leave the producing bucket as codes)."""
    moved = jnp.moveaxis(ct, dim, 0)
    shard = q_reduce_scatter(moved, axis, block=block, op=op, record=record)
    return jnp.moveaxis(shard, 0, dim)


# ---------------------------------------------------------------------------
# all-to-all (MoE dispatch / Ulysses reshard)
# ---------------------------------------------------------------------------

def q_all_to_all(x, axis: Axis, split_dim: int = 0, concat_dim: int = 0, *,
                 block: int = DEFAULT_BLOCK, op: str = "q_all_to_all",
                 record: bool = True):
    """Tiled ``all_to_all`` twin with int8 transport: split ``split_dim``
    into P per-destination chunks, quantize each, exchange the codes,
    dequantize, concatenate along ``concat_dim``."""
    P = axis_world(axis)
    if P <= 1:
        return x
    moved = jnp.moveaxis(x, split_dim, 0)            # [S, ...rest]
    S = moved.shape[0]
    chunkS = S // P
    rest = moved.shape[1:]
    parts = moved.reshape((P, chunkS) + rest)
    flat = parts.reshape(P, -1).astype(jnp.float32)
    q, s = jax.vmap(functools.partial(quantize_blockwise, block=block))(flat)
    if record:
        _record(op, axis, (q, s), x)
    with _scope("ds_comm_q_all_to_all"):
        qt = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        st = lax.all_to_all(s, axis, split_axis=0, concat_axis=0,
                            tiled=False)
    recv = (qt.astype(jnp.float32) * st).reshape(P, -1)[:, :flat.shape[1]]
    recv = recv.reshape((P, chunkS) + rest)          # [P, chunkS, ...rest]
    # undo the moveaxis per chunk, then merge the source dim into concat_dim
    recv = jnp.moveaxis(recv, 1, 1 + split_dim)      # [P, ...chunk at split]
    return _merge_leading(recv, concat_dim).astype(x.dtype)


# ---------------------------------------------------------------------------
# ring exchange (sequence parallelism) — rotate the CODES
# ---------------------------------------------------------------------------

def quantize_carry(x, block: int = DEFAULT_BLOCK):
    """Quantize a ring-carried tensor ONCE into its transport form
    ``{"q": int8 [nb, block], "s": fp32 [nb, 1]}``.  Rotating the codes
    (not the values) means every hop moves int8 bytes and the whole ring
    pays a single quantization error (requantization of a dequantized
    block is lossless — comm/quant.py)."""
    q, s = quantize_blockwise(x.astype(jnp.float32).reshape(-1), block=block)
    return {"q": q, "s": s}


def dequantize_carry(carry, shape, dtype=jnp.float32):
    """Traceable transport -> value stage for one ring step's compute."""
    return dequantize_blockwise(carry["q"], carry["s"], shape, dtype)


def q_ppermute(carry, axis: str, perm, *, op: str = "q_ppermute",
               record: bool = True, dense_like=None):
    """Rotate a quantized carry (or a pytree of them) one ring hop —
    int8 codes + fp32 scales on the wire instead of the dense chunk."""
    if record:
        parts = jax.tree_util.tree_leaves(carry)
        _record(op, axis, parts, dense_like)
    leaves, treedef = jax.tree_util.tree_flatten(carry)
    with _scope("ds_comm_q_ppermute"):
        rotated = [lax.ppermute(leaf, axis, perm) for leaf in leaves]
    return jax.tree_util.tree_unflatten(treedef, rotated)


def q_boundary_ppermute(x, axis: str, perm, *, block: int = DEFAULT_BLOCK,
                        op: str = "q_ppermute", record: bool = True):
    """Dense-in/dense-out quantized ring hop — the PIPELINE boundary form.

    The sequence ring rotates ONE tensor's codes the whole way round
    (:func:`quantize_carry` once, :func:`q_ppermute` per hop), paying a
    single quantization error.  A pipeline boundary carries a *different*
    activation on every hop — stage s's output, not a rotated copy — so
    each stage-to-stage transfer re-quantizes: quantize -> rotate the
    codes (int8 + fp32 block scales on the wire, under the same
    unconditional ``ds_comm_q_ppermute`` scope) -> dequantize on arrival.
    One quantization error per hop; bubble-step hops carry exact zeros
    (zero blocks quantize losslessly).

    A custom VJP transports the cotangent through the REVERSE ring the
    same quantized way (the :func:`q_reshard` codec discipline:
    quantization is a transport codec, not part of the differentiated
    function), so autodiff-driven schedules (the GPipe scan) get a
    quantized backward boundary for free; the fused 1F1B schedule calls
    this directly on its explicit reverse-ring sends.
    """
    inv_perm = [(d, s) for s, d in perm]
    shape, dtype = x.shape, x.dtype

    def _hop(v, prm):
        carry = quantize_carry(v, block)
        carry = q_ppermute(carry, axis, prm, op=op, record=record,
                           dense_like=v)
        return dequantize_carry(carry, shape, dtype)

    @jax.custom_vjp
    def _send(v):
        return _hop(v, perm)

    def _fwd(v):
        return _send(v), None

    def _bwd(_res, ct):
        return (_hop(ct.astype(dtype), inv_perm).astype(ct.dtype),)

    _send.defvjp(_fwd, _bwd)
    return _send(x)


# ---------------------------------------------------------------------------
# GSPMD reshard (MoE dispatch outside manual regions)
# ---------------------------------------------------------------------------

def _constrain_rows(t, mesh, spec):
    from jax.sharding import NamedSharding

    if mesh is None or getattr(mesh, "empty", False) or spec is None:
        return t
    return lax.with_sharding_constraint(t, NamedSharding(mesh, spec))


def q_reshard(x, mesh, dst_spec, src_spec=None, *,
              block: int = DEFAULT_BLOCK, op: str = "q_all_to_all",
              record: bool = True):
    """GSPMD-form quantized reshard for callers NOT inside a manual
    region (MoE dispatch): quantize ``x`` rowwise along dim 0, constrain
    the codes to ``src_spec`` then ``dst_spec`` so the GSPMD-inserted
    collective between them moves int8+scales, dequantize on the far
    side.  A custom VJP transports the cotangent the same way (mirrored
    direction) — quantization is a transport codec, not part of the
    differentiated function, so the straight-through gradient is the
    dequantized cotangent.

    ``dst_spec``/``src_spec`` are PartitionSpecs for the CODE tensors
    (``[rows, nb, block]`` int8 / ``[rows, nb, 1]`` fp32 — dim 0 is the
    row dim of ``x``, e.g. experts)."""
    rows = x.shape[0]
    shape, dtype = x.shape, x.dtype

    def _transport(t, a_spec, b_spec):
        flat = t.astype(jnp.float32).reshape(rows, -1)
        q, s = jax.vmap(functools.partial(quantize_blockwise,
                                          block=block))(flat)
        if record:
            _record(op, "gspmd", (q, s), t)
        with _scope("ds_comm_q_all_to_all"):
            q = _constrain_rows(_constrain_rows(q, mesh, a_spec), mesh,
                                b_spec)
            s = _constrain_rows(_constrain_rows(s, mesh, a_spec), mesh,
                                b_spec)
        out = (q.astype(jnp.float32) * s).reshape(rows, -1)
        out = out[:, :flat.shape[1]]
        return out.reshape(t.shape)

    @jax.custom_vjp
    def _reshard(v):
        return _transport(v, src_spec, dst_spec).astype(dtype)

    def _fwd(v):
        return _reshard(v), None

    def _bwd(_res, ct):
        return (_transport(ct, dst_spec, src_spec).astype(ct.dtype),)

    _reshard.defvjp(_fwd, _bwd)
    return _reshard(x.reshape(shape))
