"""Device-mesh construction and axis algebra.

TPU-native replacement for the reference's process-group machinery
(``deepspeed/utils/groups.py`` + ``runtime/pipe/topology.py``, SURVEY.md §2.1
"Process-group algebra", §5.8): instead of creating torch ProcessGroups per
parallelism dimension, we build one ``jax.sharding.Mesh`` whose *named axes*
are the parallelism dimensions.  Collectives then reference axis names inside
``jit``/``shard_map`` and XLA lowers them onto ICI (intra-slice) or DCN
(inter-slice) links.

Axis meanings (mirroring the reference's DP/TP/PP/EP/SP groups):

- ``pp``   pipeline stages. Outermost so a stage maps to a contiguous device
           block (pipeline neighbors exchange over one link; across slices
           this is the axis that rides DCN).
- ``dp``   pure data parallelism (gradients all-reduced, nothing sharded).
- ``fsdp`` the ZeRO axis: optimizer state (stage>=1), gradients (stage>=2) and
           parameters (stage 3) are sharded over it.
- ``ep``   expert parallelism for MoE all-to-all dispatch.
- ``sp``   sequence parallelism (Ulysses all-to-all / ring attention).
- ``tp``   tensor (model) parallelism. Innermost: TP collectives are on the
           critical path of every matmul, so they get the fastest links.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import logger

MESH_AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")

_GLOBAL_MESH: Optional[Mesh] = None


def build_mesh(dp: int = 0, fsdp: int = 0, tp: int = 1, pp: int = 1, sp: int = 1,
               ep: int = 1, devices: Optional[Sequence] = None,
               axis_order: Optional[Sequence[str]] = None) -> Mesh:
    """Build a Mesh over all (or the given) devices.

    Axis sizes of 0 are inferred: ``fsdp`` absorbs the remaining device count;
    if ``fsdp`` is explicitly set and ``dp`` is 0, ``dp`` absorbs it instead.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = {"tp": max(1, tp), "pp": max(1, pp), "sp": max(1, sp), "ep": max(1, ep)}
    known = math.prod(fixed.values())
    if n % known != 0:
        raise ValueError(f"device count {n} not divisible by tp*pp*sp*ep={known}")
    remainder = n // known
    if dp and fsdp:
        if dp * fsdp != remainder:
            raise ValueError(f"dp({dp})*fsdp({fsdp}) != remaining devices {remainder}")
    elif fsdp:
        if remainder % fsdp != 0:
            raise ValueError(f"fsdp={fsdp} does not divide remaining devices {remainder}")
        dp = remainder // fsdp
    else:
        dp = dp or 1
        if remainder % dp != 0:
            raise ValueError(f"dp={dp} does not divide remaining devices {remainder}")
        fsdp = remainder // dp
    sizes: Dict[str, int] = {"pp": fixed["pp"], "dp": dp, "fsdp": fsdp,
                             "ep": fixed["ep"], "sp": fixed["sp"], "tp": fixed["tp"]}
    order: Tuple[str, ...] = tuple(axis_order) if axis_order else MESH_AXES
    # Any axis missing from a custom order is appended with its configured size.
    order = tuple(a for a in order if a in sizes) + tuple(a for a in MESH_AXES if a not in order)
    shape = [sizes[a] for a in order]
    dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, order)
    logger.info("built mesh %s over %d devices", dict(zip(order, shape)), n)
    return mesh


def mesh_from_config(mesh_cfg, devices: Optional[Sequence] = None) -> Mesh:
    return build_mesh(dp=mesh_cfg.dp, fsdp=mesh_cfg.fsdp, tp=mesh_cfg.tp,
                      pp=mesh_cfg.pp, sp=mesh_cfg.sp, ep=mesh_cfg.ep,
                      devices=devices, axis_order=mesh_cfg.axis_order)


def set_global_mesh(mesh: Mesh) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh(create_default: bool = True) -> Optional[Mesh]:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None and create_default:
        _GLOBAL_MESH = build_mesh()
    return _GLOBAL_MESH


def axis_size(mesh: Mesh, axis: str) -> int:
    return int(mesh.shape.get(axis, 1))


def data_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Axes over which the global batch is split (dp × fsdp × ep is treated as
    batch-parallel at the input; ep resharding happens at MoE layers)."""
    return tuple(a for a in ("dp", "fsdp", "ep") if axis_size(mesh, a) > 1) or ("dp",)


def batch_sharding(mesh: Mesh, stacked: bool = False) -> NamedSharding:
    """Sharding for a [global_batch, ...] input batch (``stacked=True``:
    [grad_accum, micro_batch, ...] — the accumulation axis is a scan axis,
    only the micro dim is split over the data axes)."""
    if stacked:
        return NamedSharding(mesh, P(None, data_axes(mesh)))
    return NamedSharding(mesh, P(data_axes(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# Reference-parity group queries (deepspeed/utils/groups.py equivalents).
# On TPU a "group" is a mesh axis name (or tuple of names).
# ---------------------------------------------------------------------------

def get_data_parallel_group(mesh: Optional[Mesh] = None):
    # Matches get_data_parallel_world_size: ep carries batch shards outside
    # MoE layers, so a DP-group collective must span it too.
    return ("dp", "fsdp", "ep")


def get_model_parallel_group(mesh: Optional[Mesh] = None):
    return ("tp",)


def get_expert_parallel_group(mesh: Optional[Mesh] = None):
    return ("ep",)


def get_sequence_parallel_group(mesh: Optional[Mesh] = None):
    return ("sp",)


def get_pipeline_parallel_group(mesh: Optional[Mesh] = None):
    return ("pp",)


def get_data_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    """Number of batch shards: dp × fsdp × ep (all of data_axes — ep carries
    batch at the input and reshards to experts only inside MoE layers)."""
    mesh = mesh or get_global_mesh()
    return (axis_size(mesh, "dp") * axis_size(mesh, "fsdp")
            * axis_size(mesh, "ep"))


def get_model_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_global_mesh()
    return axis_size(mesh, "tp")


def get_expert_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_global_mesh()
    return axis_size(mesh, "ep")


def get_sequence_parallel_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_global_mesh()
    return axis_size(mesh, "sp")
