"""Kernel-injected decode path: fused per-layer Pallas kernels at s=1.

The TPU-native form of the reference's ``replace_with_kernel_inject``
machinery (``(R) module_inject/replace_module.py`` swapping HF blocks for
``DeepSpeedTransformerInference`` with fused QKV weights and the
``csrc/transformer/inference`` kernels; SURVEY.md §3.5): instead of swapping
modules, :func:`inject_decode_params` re-lays the weights for the fused
kernels (QKV concatenated into one [D, N] matmul per layer — the reference's
fused-QKV transform), and :func:`decode_step` runs a single token through
four kernel launches per layer (``ops/pallas/decode.py``) instead of the
~25-op unfused HLO chain.

Prefill keeps the standard :func:`~deepspeed_tpu.models.decoding.
forward_with_cache` path (it is matmul-bound, already MXU-shaped); only the
launch-bound s=1 loop uses the injected weights.  Both share the same KV
cache layout, so a generation prefills on the plain tree and decodes on the
injected one.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.layers import norm, rope_dim
from deepspeed_tpu.ops.pallas import rope_angles
from deepspeed_tpu.ops.pallas.decode import (flash_decode, fused_mlp,
                                             fused_norm_qkv, fused_proj_norm)


def supports_fused_decode(cfg, *, quantized_kv: bool = False,
                          tp: int = 1) -> bool:
    """The fused path covers the dense model zoo including int8 weights
    (dequant in-kernel); MoE MLPs, int8 KV caches, and tp>1 fall back to
    the reference-shaped loop."""
    return (not cfg.is_moe and not quantized_kv
            and tp == 1 and cfg.position in ("rope", "learned", "alibi"))


def inject_decode_params(params: Any, cfg) -> Dict[str, Any]:
    """Build the kernel-injected weight view from a model param tree.

    Layers are UNSTACKED into a tuple of per-layer dicts with their own
    device buffers: the decode step's static layer loop then feeds each
    Pallas kernel a whole array — profiling showed that slicing a stacked
    [L, ...] weight per layer inside the program re-materializes the
    slice (a full per-layer weight copy per token).  The QKV concat is the
    reference's fused-QKV injection transform."""
    from deepspeed_tpu.models.quant import QTensor, is_qtensor

    ly = params["layers"]
    attn, mlp = ly["attn"], ly["mlp"]
    if is_qtensor(attn["wq"]):  # int8 serving: concat payloads AND scales
        wqkv = QTensor(
            jnp.concatenate([attn["wq"].q, attn["wk"].q, attn["wv"].q], -1),
            jnp.concatenate([attn["wq"].scale, attn["wk"].scale,
                             attn["wv"].scale], -1))
    else:
        wqkv = jnp.concatenate([attn["wq"], attn["wk"], attn["wv"]], axis=-1)
    stacked: Dict[str, Any] = {
        "wqkv": wqkv,
        "wo": attn["wo"],
        "n1_scale": ly["attn_norm"]["scale"],
        "n2_scale": ly["mlp_norm"]["scale"],
        "w_up": mlp["w_up"],
        "w_down": mlp["w_down"],
    }
    if cfg.norm == "layernorm":
        stacked["n1_bias"] = ly["attn_norm"]["bias"]
        stacked["n2_bias"] = ly["mlp_norm"]["bias"]
    if cfg.use_bias or cfg.qkv_bias:
        stacked["bqkv"] = jnp.concatenate([attn["bq"], attn["bk"], attn["bv"]],
                                          axis=-1)
    if cfg.use_bias:
        stacked["bo"] = attn["bo"]
    if cfg.has_mlp_bias:
        stacked["b_up"] = mlp["b_up"]
        stacked["b_down"] = mlp["b_down"]
        if cfg.glu:
            stacked["b_gate"] = mlp["b_gate"]
    if cfg.glu:
        stacked["w_gate"] = mlp["w_gate"]
    def unstack(v, l):
        if is_qtensor(v):
            return QTensor(v.q[l], v.scale[l])
        return v[l]

    layers = tuple(
        {k: unstack(v, l) for k, v in stacked.items()}
        for l in range(cfg.num_layers))
    out = {"embed": params["embed"], "final_norm": params["final_norm"],
           "layers": layers}
    if not cfg.tie_embeddings:
        out["lm_head"] = params["lm_head"]
    if cfg.lm_head_bias:
        out["lm_head_bias"] = params["lm_head_bias"]
    return out


def decode_step(cfg, dparams, tokens, cache, pos, *,
                page_table=None, impl: Optional[str] = None):
    """One generation step: ``tokens`` [B, 1] at absolute position ``pos``
    -> (logits [B, V] fp32, cache).

    ``pos`` is a traced scalar (static batch: every row at the same depth)
    or an int32 [B] vector of per-row positions (continuous batching: each
    slot sits at its own depth; cache appends scatter per row and the
    flash-decode kernel masks per row).

    ``page_table`` [B, maxp] switches the cache to the paged pool layout
    ([L, num_pages, Hkv, page, Dh], ``serving/paged_kv.py``): appends
    scatter through the table and the flash-decode kernel indirects its
    DMA index map through it (per-row positions required).

    Four kernel launches per layer: norm+QKV, flash-decode attention,
    out-proj+residual+norm, MLP+residual (ops/pallas/decode.py); the cache
    row appends stay XLA in-place updates (on the donated cache)."""
    B = tokens.shape[0]
    H, Hkv, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    M, Mkv = H * Dh, Hkv * Dh
    kind, eps = cfg.norm, cfg.norm_eps
    pos = jnp.asarray(pos, jnp.int32)
    per_row = pos.ndim == 1                  # [B] per-slot depths
    if page_table is not None and not per_row:
        raise ValueError("paged KV decode requires per-row positions")
    x = jnp.take(dparams["embed"]["tok"], tokens[:, 0], axis=0)
    if cfg.position == "learned":
        x = x + jnp.take(dparams["embed"]["pos"],
                         pos if per_row else pos[None], axis=0)
    if cfg.embed_norm:  # bloom word_embeddings_layernorm
        x = norm(x, dparams["embed"]["norm"], "layernorm", cfg.norm_eps)
    dtype = cache["k"].dtype
    x = x.astype(dtype)

    if cfg.position == "rope":
        rd = rope_dim(cfg)
        # scalar: [1, rd/2] broadcast over the batch; per-row: [B, rd/2]
        cos, sin = rope_angles(pos if per_row else pos[None], rd,
                               theta=cfg.rope_theta)
    else:
        cos = sin = None

    def rope_rows(t):
        """[B, Hx, Dh] -> rotate the first rd dims of each head."""
        if cos is None:
            return t
        half = rd // 2
        if per_row:
            c = cos[:, None].astype(jnp.float32)     # [B, 1, rd/2]
            s = sin[:, None].astype(jnp.float32)
        else:
            c = cos[0].astype(jnp.float32)
            s = sin[0].astype(jnp.float32)
        x1 = t[..., :half].astype(jnp.float32)
        x2 = t[..., half:rd].astype(jnp.float32)
        rot = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
        return jnp.concatenate([rot.astype(t.dtype), t[..., rd:]], axis=-1) \
            if rd < t.shape[-1] else rot.astype(t.dtype)

    scale = 1.0 / (Dh ** 0.5)

    # Statically unrolled layer loop over UNSTACKED per-layer weights: a
    # lax.scan (or per-layer slicing of stacked weights) re-materializes a
    # full per-layer weight copy per token — profiled at ~40% of the decode
    # step.  Cache rows update in place on the stacked [L, ...] buffers
    # (donated through the generation loop); flash_decode indexes the
    # stacked cache with a static layer offset, so no cache slice
    # materializes either.
    kc_all, vc_all = cache["k"], cache["v"]
    pos0 = jnp.zeros((), jnp.int32)
    from deepspeed_tpu.models.quant import is_qtensor

    def wq_pair(w):
        """(payload, per-out-channel scale | None) for dense or int8."""
        if is_qtensor(w):
            return w.q, w.scale
        return w, None

    for l, lp in enumerate(dparams["layers"]):
        wqkv, s_qkv = wq_pair(lp["wqkv"])
        qkv = fused_norm_qkv(x, lp["n1_scale"], lp.get("n1_bias"),
                             wqkv, lp.get("bqkv"), kind=kind, eps=eps,
                             wscale=s_qkv, impl=impl)
        q = rope_rows(qkv[:, :M].reshape(B, H, Dh))
        k = rope_rows(qkv[:, M:M + Mkv].reshape(B, Hkv, Dh))
        v = qkv[:, M + Mkv:].reshape(B, Hkv, Dh)
        if page_table is not None:
            # paged append: row b writes at row pos[b] % page of physical
            # page page_table[b, pos[b] // page] (parked rows' tables
            # point at the junk page 0 — their writes land where no live
            # slot reads); same one-batched-scatter aliasing argument
            page = kc_all.shape[3]
            pp = page_table[jnp.arange(B), pos // page]
            po = pos % page
            kc_all = kc_all.at[l, pp, :, po, :].set(k.astype(kc_all.dtype))
            vc_all = vc_all.at[l, pp, :, po, :].set(v.astype(vc_all.dtype))
        elif per_row:
            # per-slot append: row b writes at its own depth pos[b], as ONE
            # batched scatter.  Measured (CPU, 16-step scan, donated
            # cache): scatter 37ms vs a per-row dynamic_update_slice loop
            # 432ms — the per-row-index DUS defeats XLA's in-place
            # aliasing and copies the cache per write.
            bidx = jnp.arange(B)
            kc_all = kc_all.at[l, bidx, :, pos, :].set(
                k.astype(kc_all.dtype))
            vc_all = vc_all.at[l, bidx, :, pos, :].set(
                v.astype(vc_all.dtype))
        else:
            kc_all = jax.lax.dynamic_update_slice(
                kc_all, k[None, :, :, None, :].astype(kc_all.dtype),
                (l, pos0, pos0, pos, pos0))
            vc_all = jax.lax.dynamic_update_slice(
                vc_all, v[None, :, :, None, :].astype(vc_all.dtype),
                (l, pos0, pos0, pos, pos0))
        ctx = flash_decode(q, kc_all, vc_all, pos, sm_scale=scale,
                           layer=l, alibi=cfg.position == "alibi",
                           page_table=page_table, impl=impl)
        wo, s_wo = wq_pair(lp["wo"])
        r, h = fused_proj_norm(ctx.reshape(B, M), x, wo, lp.get("bo"),
                               lp["n2_scale"], lp.get("n2_bias"), kind=kind,
                               eps=eps, parallel=cfg.parallel_residual,
                               wscale=s_wo, impl=impl)
        wu, su = wq_pair(lp["w_up"])
        wd, sd = wq_pair(lp["w_down"])
        wg, sg = (wq_pair(lp["w_gate"]) if "w_gate" in lp else (None, None))
        wscales = (su, sg, sd) if su is not None else None
        x = fused_mlp(h, r, wu, wd, wg,
                      lp.get("b_up"), lp.get("b_gate"), lp.get("b_down"),
                      act=cfg.activation, wscales=wscales, impl=impl)
    new_cache = {"k": kc_all, "v": vc_all}
    x = norm(x, dparams["final_norm"], kind, eps)
    if cfg.tie_embeddings:
        head = dparams["embed"]["tok"].T.astype(x.dtype)
    else:
        head = dparams["lm_head"].astype(x.dtype)
    logits = (x @ head).astype(jnp.float32)
    if cfg.lm_head_bias:
        logits = logits + dparams["lm_head_bias"].astype(jnp.float32)
    return logits, new_cache
