"""Decoder-only transformer (Llama / GPT-2 / Mixtral families), TPU-native.

The reference has no model zoo — users hand torch modules to
``deepspeed.initialize`` and the kernel-injection policies recognize the
architecture (``deepspeed/module_inject/containers/``: GPT2, LLaMA, Mixtral…,
SURVEY.md §2.1).  Here the same families are implemented directly as a
functional jax model designed for the compiler:

- **Stacked layers + ``lax.scan``**: all layer params carry a leading [L]
  dim and one compiled layer body is scanned — O(1) compile time in depth,
  and XLA pipelines the per-layer collectives.
- **Remat per layer** (``jax.checkpoint``) is the activation-checkpointing
  equivalent of the reference's ``runtime/activation_checkpointing`` —
  recompute-in-backward as a compiler transform instead of autograd hooks.
- **Logical TP specs** (``logical_pspecs``) mark Megatron column/row splits
  over the ``tp`` mesh axis (the AutoTP classification, auto_tp.py) and
  expert splits over ``ep``; the engine merges these with the ZeRO ``fsdp``
  sharding (runtime/zero/partition.py).
- Fused kernels: RMSNorm/LayerNorm, RoPE, flash attention from
  ``deepspeed_tpu/ops/pallas`` (the csrc kernel equivalents).

API shape follows the flax convention the engine expects
(``init(rng, batch)`` / ``apply(params, batch, rngs=...)``): with ``labels``
the model returns the scalar LM loss (fp32 accumulation), else logits.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.mesh import axis_size, get_global_mesh
from deepspeed_tpu.models.config import ModelConfig, get_model_config
from deepspeed_tpu.models.layers import (activation_fn, apply_partial_rope,
                                         attention_core, constrain, norm,
                                         _repeat_kv, rope_cache, rope_dim)
from deepspeed_tpu.ops.pallas import apply_rotary_pos_emb


def _uniform(rng, shape, scale, dtype):
    return jax.random.uniform(rng, shape, dtype, -scale, scale)




class CausalLM:
    """Functional causal language model over a device mesh."""

    def __init__(self, config: ModelConfig, mesh: Optional[Mesh] = None):
        self.config = config
        self._mesh = mesh

    @property
    def mesh(self) -> Optional[Mesh]:
        return self._mesh if self._mesh is not None else get_global_mesh(create_default=False)

    def set_param_offload_specs(self, specs) -> None:
        """Engine hook: runtime PartitionSpecs for the param tree, needed so
        the per-layer host->device streaming moves carry explicit shardings
        (ZeRO-Infinity param tiering)."""
        self._offload_specs = specs

    # ------------------------------------------------------------------
    # parameters
    # ------------------------------------------------------------------
    def init(self, rng, tokens=None, labels=None) -> Dict[str, Any]:
        cfg = self.config
        dtype = jnp.float32  # master params fp32; engine casts for compute
        D, F, V, L = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size, cfg.num_layers
        H, Hkv, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        E = cfg.num_experts
        keys = iter(jax.random.split(rng, 32))
        s_in = D ** -0.5
        s_ff = F ** -0.5

        def linit(key, shape, scale):
            # Layer weights always carry the stacked [L] leading dim; scan vs
            # python-loop is a forward-pass choice, not a layout choice.
            return _uniform(key, (L,) + shape, scale, dtype)

        norm_p = {"scale": jnp.ones((L, D), dtype)}
        if cfg.norm == "layernorm":
            norm_p["bias"] = jnp.zeros((L, D), dtype)
        attn = {
            "wq": linit(next(keys), (D, H * Dh), s_in),
            "wk": linit(next(keys), (D, Hkv * Dh), s_in),
            "wv": linit(next(keys), (D, Hkv * Dh), s_in),
            "wo": linit(next(keys), (H * Dh, D), (H * Dh) ** -0.5),
        }
        if cfg.use_bias or cfg.qkv_bias:
            attn.update(bq=jnp.zeros((L, H * Dh), dtype),
                        bk=jnp.zeros((L, Hkv * Dh), dtype),
                        bv=jnp.zeros((L, Hkv * Dh), dtype))
        if cfg.use_bias:
            attn.update(bo=jnp.zeros((L, D), dtype))
        if cfg.is_moe:
            mlp = {
                "gate_w": _uniform(next(keys), (L, D, E), s_in, dtype),
                "w_up": _uniform(next(keys), (L, E, D, F), s_in, dtype),
                "w_down": _uniform(next(keys), (L, E, F, D), s_ff, dtype),
            }
            if cfg.glu:
                mlp["w_gate"] = _uniform(next(keys), (L, E, D, F), s_in, dtype)
        else:
            mlp = {
                "w_up": linit(next(keys), (D, F), s_in),
                "w_down": linit(next(keys), (F, D), s_ff),
            }
            if cfg.glu:
                mlp["w_gate"] = linit(next(keys), (D, F), s_in)
            if cfg.has_mlp_bias:
                mlp.update(b_up=jnp.zeros((L, F), dtype),
                           b_down=jnp.zeros((L, D), dtype))
                if cfg.glu:
                    mlp["b_gate"] = jnp.zeros((L, F), dtype)
        layers = {"attn_norm": norm_p,
                  "mlp_norm": jax.tree.map(jnp.copy, norm_p),
                  "attn": attn, "mlp": mlp}
        fnorm = {"scale": jnp.ones((D,), dtype)}
        if cfg.norm == "layernorm":
            fnorm["bias"] = jnp.zeros((D,), dtype)
        params = {
            "embed": {"tok": jax.random.normal(next(keys), (V, D), dtype) * 0.02},
            "layers": layers,
            "final_norm": fnorm,
        }
        if cfg.position == "learned":
            params["embed"]["pos"] = jax.random.normal(
                next(keys), (cfg.max_seq_len, D), dtype) * 0.02
        if cfg.embed_norm:  # bloom: layernorm right after the token embed
            params["embed"]["norm"] = {"scale": jnp.ones((D,), dtype),
                                       "bias": jnp.zeros((D,), dtype)}
        if not cfg.tie_embeddings:
            params["lm_head"] = jax.random.normal(next(keys), (D, V), dtype) * s_in
        if cfg.lm_head_bias:
            params["lm_head_bias"] = jnp.zeros((V,), dtype)
        return params

    def logical_pspecs(self) -> Dict[str, Any]:
        """Tensor/expert-parallel logical specs (the AutoTP column/row map).

        Layer weights have a leading stacked [L] dim (never sharded here —
        ``fsdp`` may claim it later for ZeRO-3).
        """
        cfg = self.config
        col = P(None, None, "tp")       # [L, D, H*Dh] / [L, D, F] — column split
        row = P(None, "tp", None)       # [L, F, D] / [L, H*Dh, D] — row split
        norm_spec = {"scale": P(None, None)}
        if cfg.norm == "layernorm":
            norm_spec["bias"] = P(None, None)
        attn = {"wq": col, "wk": col, "wv": col, "wo": row}
        if cfg.use_bias or cfg.qkv_bias:
            # column-split outputs carry tp-split biases; row outputs are
            # reduced across tp, so their bias stays replicated
            attn.update(bq=P(None, "tp"), bk=P(None, "tp"), bv=P(None, "tp"))
        if cfg.use_bias:
            attn.update(bo=P(None, None))
        if cfg.is_moe:
            mlp = {"gate_w": P(None, None, None),
                   "w_up": P(None, "ep", None, "tp"),
                   "w_down": P(None, "ep", "tp", None)}
            if cfg.glu:
                mlp["w_gate"] = P(None, "ep", None, "tp")
        else:
            mlp = {"w_up": col, "w_down": row}
            if cfg.glu:
                mlp["w_gate"] = col
            if cfg.has_mlp_bias:
                mlp.update(b_up=P(None, "tp"), b_down=P(None, None))
                if cfg.glu:
                    mlp["b_gate"] = P(None, "tp")
        fnorm = {"scale": P(None)}
        if cfg.norm == "layernorm":
            fnorm["bias"] = P(None)
        specs = {
            "embed": {"tok": P("tp", None)},
            "layers": {"attn_norm": norm_spec,
                       "mlp_norm": dict(norm_spec),
                       "attn": attn, "mlp": mlp},
            "final_norm": fnorm,
        }
        if cfg.position == "learned":
            specs["embed"]["pos"] = P(None, None)
        if cfg.embed_norm:
            specs["embed"]["norm"] = {"scale": P(None), "bias": P(None)}
        if not cfg.tie_embeddings:
            specs["lm_head"] = P(None, "tp")
        if cfg.lm_head_bias:
            specs["lm_head_bias"] = P("tp")
        mesh = self.mesh
        if mesh is not None and not mesh.empty:
            # pipeline parallelism: stage ownership = stacked-layer-dim shard
            from deepspeed_tpu.runtime.pipe.spmd import pp_layer_pspecs
            specs["layers"] = pp_layer_pspecs(specs["layers"], mesh)
        return specs

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------
    def _attn_out(self, lp, x, k_attn, cos, sin, batch_ax, use_drop):
        """Attention sub-block OUTPUT (residual not added)."""
        cfg = self.config
        mesh = self.mesh
        B, S, D = x.shape
        H, Hkv, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        h = norm(x, lp["attn_norm"], cfg.norm, cfg.norm_eps)
        a = lp["attn"]
        q = h @ a["wq"]
        k = h @ a["wk"]
        v = h @ a["wv"]
        if cfg.use_bias or cfg.qkv_bias:
            q, k, v = q + a["bq"], k + a["bk"], v + a["bv"]
        q = q.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, Hkv, Dh).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, Hkv, Dh).transpose(0, 2, 1, 3)
        if cfg.position == "rope":  # [B, H, S, Dh] is the kernel's layout
            q = apply_partial_rope(q, cos, sin)
            k = apply_partial_rope(k, cos, sin)
        k = _repeat_kv(k, H // Hkv)
        v = _repeat_kv(v, H // Hkv)
        o = attention_core(q, k, v, mesh, causal=True, sp_mode=cfg.sp_mode,
                           alibi=cfg.position == "alibi",
                           ring_q=getattr(cfg, "seq_ring_q", False),
                           ring_q_block=getattr(cfg, "comm_quant_block",
                                                256))
        o = o.transpose(0, 2, 1, 3).reshape(B, S, H * Dh)
        o = o @ a["wo"]
        if cfg.use_bias:
            o = o + a["bo"]
        o = o.astype(x.dtype)
        if use_drop:
            o = _dropout(o, k_attn, cfg.dropout)
        return o

    def _attn_block(self, lp, x, k_attn, cos, sin, batch_ax, use_drop):
        x = x + self._attn_out(lp, x, k_attn, cos, sin, batch_ax, use_drop)
        return constrain(x, self.mesh, batch_ax, "sp", None)

    def _mlp_block(self, lp, x, k_mlp, batch_ax, use_drop):
        cfg = self.config
        mesh = self.mesh
        h = norm(x, lp["mlp_norm"], cfg.norm, cfg.norm_eps)
        if cfg.is_moe:
            from deepspeed_tpu.moe.sharded_moe import moe_mlp
            # split: the RTS permutation and the dropout mask below must not
            # consume the same key
            k_rts = None
            if k_mlp is not None:
                k_rts, k_mlp = jax.random.split(k_mlp)
            mlp_out, aux = moe_mlp(lp["mlp"], h, cfg, mesh, rng=k_rts)
        else:
            act = activation_fn(cfg.activation)
            m = lp["mlp"]
            up = h @ m["w_up"]
            if cfg.has_mlp_bias:
                up = up + m["b_up"]
            if cfg.glu:
                gate = h @ m["w_gate"]
                if cfg.has_mlp_bias:
                    gate = gate + m["b_gate"]
                gated = act(gate) * up
            else:
                gated = act(up)
            mlp_out = gated @ m["w_down"]
            if cfg.has_mlp_bias:
                mlp_out = mlp_out + m["b_down"]
            aux = jnp.zeros((), jnp.float32)
        mlp_out = mlp_out.astype(x.dtype)
        if use_drop:
            mlp_out = _dropout(mlp_out, k_mlp, cfg.dropout)
        x = x + mlp_out
        return constrain(x, mesh, batch_ax, "sp", None), aux

    def _layer(self, lp, x, key, cos, sin, batch_ax, use_drop):
        k_attn, k_mlp = (jax.random.split(key) if use_drop else (None, None))
        if self.config.parallel_residual:
            # gpt-neox/pythia: both sub-blocks read the LAYER INPUT
            attn_o = self._attn_out(lp, x, k_attn, cos, sin, batch_ax,
                                    use_drop)
            mlp_y, aux = self._mlp_block(lp, x, k_mlp, batch_ax, use_drop)
            # _mlp_block returns x + mlp(ln2(x)); add the attention branch
            x = mlp_y + attn_o
            return constrain(x, self.mesh, batch_ax, "sp", None), aux
        x = self._attn_block(lp, x, k_attn, cos, sin, batch_ax, use_drop)
        return self._mlp_block(lp, x, k_mlp, batch_ax, use_drop)

    def apply(self, params, tokens, labels=None, rngs=None, loss_mask=None):
        cfg = self.config
        mesh = self.mesh
        batch_ax = ("dp", "fsdp", "ep")
        if cfg.param_offload:
            # ZeRO-Infinity param tiering: non-layer params come over once
            # here; scanned layer weights stream per-layer inside the scan
            # body (bounded device window; XLA's latency-hiding scheduler
            # overlaps the copies with the previous layer's compute).  The
            # engine injects the runtime PartitionSpecs (set_param_offload_specs)
            # because the SPMD partitioner requires memory-space moves to
            # carry explicit shardings on multi-device meshes.
            specs = getattr(self, "_offload_specs", None)
            from deepspeed_tpu.accelerator.real_accelerator import \
                supports_pinned_host

            if supports_pinned_host():
                def to_dev(t, spec_t):
                    def put(a, s):
                        if s is None or mesh is None or mesh.empty:
                            return jax.device_put(a, jax.memory.Space.Device)
                        from jax.sharding import NamedSharding
                        return jax.device_put(
                            a, NamedSharding(mesh, s, memory_kind="device"))
                    if spec_t is None:
                        return jax.tree.map(lambda a: put(a, None), t)
                    return jax.tree.map(put, t, spec_t)
            else:
                # capability-gated fallback: one memory space on this
                # backend (CPU advertises only unpinned_host), so there is
                # nothing to stream across — the in-jit memory-space move
                # would be rejected at lowering
                def to_dev(t, spec_t):
                    return t

            self._offload_to_dev = to_dev
            params = {**params,
                      "embed": to_dev(params["embed"],
                                      specs["embed"] if specs else None),
                      "final_norm": to_dev(params["final_norm"],
                                           specs["final_norm"] if specs else None)}
            if "lm_head" in params:
                params["lm_head"] = to_dev(params["lm_head"],
                                           specs["lm_head"] if specs else None)
        tokens = constrain(tokens, mesh, batch_ax, "sp")
        x = jnp.take(params["embed"]["tok"], tokens, axis=0)
        if cfg.position == "learned":
            S = tokens.shape[1]
            x = x + params["embed"]["pos"][:S][None]
        if cfg.embed_norm:  # bloom word_embeddings_layernorm
            x = norm(x, params["embed"]["norm"], "layernorm", cfg.norm_eps)
        x = constrain(x, mesh, batch_ax, "sp", None)

        if cfg.position == "rope":
            cos, sin = rope_cache(tokens.shape[1], rope_dim(cfg), cfg.rope_theta)
            cos, sin = cos.astype(x.dtype), sin.astype(x.dtype)
        else:
            cos = sin = jnp.zeros((), x.dtype)

        drop_rng = rngs.get("dropout") if isinstance(rngs, dict) else rngs
        use_drop = cfg.dropout > 0 and drop_rng is not None
        keys = (jax.random.split(drop_rng, cfg.num_layers) if use_drop
                else jnp.zeros((cfg.num_layers,), jnp.uint32))

        body = functools.partial(self._layer, cos=cos, sin=sin, batch_ax=batch_ax,
                                 use_drop=use_drop)
        if cfg.remat:
            # "dots" saves matmul outputs and recomputes only the cheap
            # elementwise chain — a middle point between full remat (+1/3
            # FLOPs) and no remat (full activation residency).  Measured on
            # v5e: also saving the flash-attention output does NOT pay — the
            # custom_vjp still recomputes its forward for the lse residual,
            # so the extra residency only adds memory pressure.
            # "mlp_only" leaves the attention sub-block out of the remat
            # region entirely (its residuals persist; the flash kernel never
            # re-runs) and fully remats the MLP half — the fastest policy on
            # v5e when activations fit.
            if (cfg.remat_policy in ("mlp_only", "mlp_dots")
                    and not cfg.parallel_residual):
                # (parallel-residual layers have no post-attention stream to
                # split the remat around; they fall through to whole-layer
                # policies below)
                mlp_policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                              if cfg.remat_policy == "mlp_dots" else None)

                def body(lp, x, key, _self=self):
                    k_attn, k_mlp = (jax.random.split(key) if use_drop
                                     else (None, None))
                    x = _self._attn_block(lp, x, k_attn, cos, sin, batch_ax,
                                          use_drop)
                    mlp = jax.checkpoint(
                        functools.partial(_self._mlp_block, batch_ax=batch_ax,
                                          use_drop=use_drop),
                        prevent_cse=False, policy=mlp_policy)
                    return mlp(lp, x, k_mlp)
            else:
                if cfg.remat_policy == "offload_dots":
                    # cpu_checkpointing: saved matmul outputs page to pinned
                    # host memory and stream back in backward — activations
                    # stop occupying HBM between fwd and bwd (reference
                    # activation_checkpointing cpu_checkpointing semantics).
                    # The CPU backend cannot execute the placement custom
                    # call inside sharded programs; residuals stay saved
                    # on-"device" there (same memory on CPU anyway).
                    if jax.default_backend() == "cpu":
                        from deepspeed_tpu.utils.logging import logger as _lg

                        _lg.warning("cpu_checkpointing: offloaded residuals "
                                    "unsupported on the CPU backend; saving "
                                    "dots without the host memory-space move")
                        policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                    else:
                        policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                            "device", "pinned_host")
                elif cfg.remat_policy == "dots":
                    policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                elif (cfg.remat_policy in ("mlp_only", "mlp_dots")
                      and cfg.parallel_residual):
                    # no post-attention stream to split around: degrade to
                    # whole-layer saved-dots, and say so
                    from deepspeed_tpu.utils.logging import logger as _lg

                    _lg.warning(
                        "remat_policy=%r has no mlp-scoped form for parallel-"
                        "residual layers; using whole-layer 'dots' instead",
                        cfg.remat_policy)
                    policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                else:
                    policy = None
                body = jax.checkpoint(body, prevent_cse=False, policy=policy)
        pp = axis_size(mesh, "pp") if mesh is not None and not mesh.empty else 1

        if cfg.param_offload:
            specs = getattr(self, "_offload_specs", None)
            layer_specs = (jax.tree.map(lambda s: P(*tuple(s)[1:]),
                                        specs["layers"]) if specs else None)

        def scan_body(carry, xs):
            lp, key = xs
            if cfg.param_offload:  # stream this layer's weights to device
                lp = self._offload_to_dev(lp, layer_specs)
            y, aux = body(lp, carry, key)
            return y, aux

        if pp > 1:
            if not cfg.scan_layers:
                raise ValueError("pipeline parallelism requires scan_layers=True "
                                 "(stacked layer params)")
            from deepspeed_tpu.runtime.pipe.spmd import spmd_pipeline

            def stage_fn(wl, xmb, keys_l, cos, sin):
                y, auxes = jax.lax.scan(
                    lambda c, xs: scan_body(c, xs), xmb, (wl, keys_l))
                return y, jnp.sum(auxes)

            if labels is not None:
                # loss-in-pipeline: the last stage folds each finished
                # microbatch straight into CE sums — the O(global-batch)
                # replicated hidden-state buffer never exists
                head_pp = (params["embed"]["tok"].T if cfg.tie_embeddings
                           else params["lm_head"])
                # the consts tuple only grows a bias entry when the model
                # has one (static): no zeros-add over the fp32 logits —
                # the largest loss-tail tensor — for bias-free models
                hb_pp = ((params["lm_head_bias"],) if cfg.lm_head_bias
                         else ())
                mask_arg = (loss_mask if loss_mask is not None
                            else jnp.ones(labels.shape, jnp.int32))
                has_mask = loss_mask is not None

                # Uneven global batch: the loss-in-pipeline schedules need
                # B % M == 0, so pad to the next multiple with rows the CE
                # mask drops (label -1, mask 0, zero embedding) — exact
                # loss and gradients, because pad rows contribute zero to
                # both the nll sum and the token count.
                M_eff = cfg.pp_microbatches or pp
                pad_rows = (-x.shape[0]) % M_eff
                if pad_rows:
                    x = jnp.concatenate(
                        [x, jnp.zeros((pad_rows,) + x.shape[1:], x.dtype)])
                    labels = jnp.concatenate(
                        [labels, jnp.full((pad_rows,) + labels.shape[1:],
                                          -1, labels.dtype)])
                    mask_arg = jnp.concatenate(
                        [mask_arg,
                         jnp.zeros((pad_rows,) + mask_arg.shape[1:],
                                   mask_arg.dtype)])

                def reduce_mb(y_mb, r_xs, consts):
                    # dense CE over one microbatch (small by construction);
                    # blockwise CE's checkpoint+scan trips XLA CHECKs under
                    # the partial-manual region on CPU (jax 0.9)
                    lab_mb, m_mb = r_xs
                    fnorm_c, head_c, *hb_c = consts
                    h = norm(y_mb, fnorm_c, cfg.norm, cfg.norm_eps)
                    logits = (h[:, :-1] @ head_c.astype(h.dtype)
                              ).astype(jnp.float32)
                    if hb_c:
                        logits = logits + hb_c[0].astype(jnp.float32)
                    lab = lab_mb[:, 1:]
                    lse = jax.scipy.special.logsumexp(logits, axis=-1)
                    # one-hot contraction, not take_along_axis: XLA's SPMD
                    # partitioner CHECK-crashes partitioning that gather
                    # under the partial-manual pp region (jax 0.9)
                    gold = jnp.einsum(
                        "bsv,bsv->bs", logits,
                        jax.nn.one_hot(jnp.maximum(lab, 0), logits.shape[-1],
                                       dtype=logits.dtype))
                    nll = lse - gold
                    if cfg.z_loss:
                        nll = nll + cfg.z_loss * lse ** 2
                    valid = lab >= 0
                    if has_mask:
                        valid = valid & (m_mb[:, 1:] > 0)
                    return {"nll": jnp.where(valid, nll, 0.0).sum(),
                            "cnt": valid.sum().astype(jnp.float32)}

                if cfg.pp_schedule == "1f1b":
                    # token count is data-only, so it can divide each
                    # microbatch's contribution BEFORE the pipeline — the
                    # fused schedule needs additive per-microbatch scalars
                    from deepspeed_tpu.runtime.pipe.spmd import \
                        spmd_pipeline_1f1b

                    valid_all = labels[:, 1:] >= 0
                    if has_mask:
                        valid_all = valid_all & (mask_arg[:, 1:] > 0)
                    cnt = jnp.maximum(valid_all.sum().astype(jnp.float32),
                                      1.0)

                    def loss_mb(y_mb, r_xs, consts):
                        *red_c, cnt_c = consts
                        d = reduce_mb(y_mb, r_xs, tuple(red_c))
                        return d["nll"] / cnt_c

                    return spmd_pipeline_1f1b(
                        stage_fn, loss_mb, params["layers"], x, mesh,
                        num_microbatches=cfg.pp_microbatches,
                        broadcast_args=(cos, sin), scan_args=keys,
                        loss_xs=(labels, mask_arg),
                        loss_consts=(params["final_norm"], head_pp) + hb_pp
                        + (cnt,),
                        aux_coef=(cfg.moe_aux_loss_coef if cfg.is_moe
                                  else 0.0),
                        quantize_boundary=cfg.pp_boundary_q,
                        quant_block=cfg.comm_quant_block,
                        comm_record=cfg.pp_comm_record)

                # When the model remats per layer (cfg.remat), the scan's
                # per-step residuals are already bounded by the tuned layer
                # policy — an outer save-nothing wrap would override it.
                # Only un-rematted models take the pipeline's own stage remat.
                red, aux_loss = spmd_pipeline(
                    stage_fn, params["layers"], x, mesh,
                    num_microbatches=cfg.pp_microbatches,
                    broadcast_args=(cos, sin), scan_args=keys,
                    reduce_fn=reduce_mb, reduce_xs=(labels, mask_arg),
                    reduce_consts=(params["final_norm"], head_pp) + hb_pp,
                    remat_stage=not bool(cfg.remat),
                    quantize_boundary=cfg.pp_boundary_q,
                    quant_block=cfg.comm_quant_block,
                    comm_record=cfg.pp_comm_record)
                loss = red["nll"] / jnp.maximum(red["cnt"], 1.0)
                return (loss + cfg.moe_aux_loss_coef * aux_loss
                        if cfg.is_moe else loss)

            x, aux_loss = spmd_pipeline(stage_fn, params["layers"], x, mesh,
                                        num_microbatches=cfg.pp_microbatches,
                                        broadcast_args=(cos, sin), scan_args=keys,
                                        remat_stage=not bool(cfg.remat),
                                        quantize_boundary=cfg.pp_boundary_q,
                                        quant_block=cfg.comm_quant_block,
                                        comm_record=cfg.pp_comm_record)
        elif cfg.scan_layers:
            x, auxes = jax.lax.scan(scan_body, x, (params["layers"], keys))
            aux_loss = jnp.sum(auxes)
        elif cfg.param_offload:
            # unrolled layers with host-tiered params: to_dev IS the
            # prefetch hook — layer i+1's host->device move is emitted
            # tied (optimization_barrier) to layer i's INPUT, so XLA may
            # run the copy concurrent with layer i's matmuls but cannot
            # hoist the whole stacked tree to the program head (the PR 6
            # barrier-tied bucket idiom applied to the memory tier;
            # double-buffered: at most two layers' params are in flight)
            aux_loss = jnp.zeros((), jnp.float32)
            lspecs = (jax.tree.map(lambda s: P(*tuple(s)[1:]),
                                   getattr(self, "_offload_specs",
                                           {}).get("layers"))
                      if getattr(self, "_offload_specs", None) else None)
            nxt = self._offload_to_dev(
                jax.tree.map(lambda a: a[0], params["layers"]), lspecs)
            for i in range(cfg.num_layers):
                lp = nxt
                if i + 1 < cfg.num_layers:
                    sl = jax.tree.map(lambda a: a[i + 1], params["layers"])
                    x, sl = jax.lax.optimization_barrier((x, sl))
                    nxt = self._offload_to_dev(sl, lspecs)
                x, aux = body(lp, x, keys[i])
                aux_loss = aux_loss + aux
        else:
            aux_loss = jnp.zeros((), jnp.float32)
            for i in range(cfg.num_layers):
                lp = jax.tree.map(lambda a: a[i], params["layers"])
                x, aux = body(lp, x, keys[i])
                aux_loss = aux_loss + aux

        if labels is None:
            x = norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
            head = (params["embed"]["tok"].T if cfg.tie_embeddings
                    else params["lm_head"]).astype(x.dtype)
            logits = x @ head
            if cfg.lm_head_bias:
                logits = logits + params["lm_head_bias"].astype(logits.dtype)
            return constrain(logits, mesh, batch_ax, "sp", "tp")
        head = (params["embed"]["tok"].T if cfg.tie_embeddings
                else params["lm_head"])
        loss = self._loss_tail(params["final_norm"], head, x, labels, loss_mask,
                               head_bias=params.get("lm_head_bias"))
        return loss + cfg.moe_aux_loss_coef * aux_loss if cfg.is_moe else loss

    def _loss_tail(self, fnorm, head, x, labels, loss_mask, head_bias=None):
        """Final norm + LM cross-entropy — the single implementation behind
        both ``apply`` and the streamed head segment (their numerical parity
        is load-bearing for the offload tests).  ``head`` is [D, V].

        Next-token objective (HF CausalLM convention: shift inside when
        labels == input_ids): logits[t] predicts labels[t+1]."""
        cfg = self.config
        mesh = self.mesh
        batch_ax = ("dp", "fsdp", "ep")
        h = norm(x, fnorm, cfg.norm, cfg.norm_eps)
        head = head.astype(h.dtype)
        shifted_labels = labels[:, 1:]
        shifted_mask = loss_mask[:, 1:] if loss_mask is not None else None
        B, S, _ = h.shape
        chunk = cfg.ce_chunk
        if chunk is None:  # auto: chunk when the fp32 logits would be >2^28 elts
            chunk = 2048 if B * S * cfg.vocab_size > (1 << 28) else 0
        if chunk:
            return blockwise_cross_entropy(h[:, :-1], head, shifted_labels,
                                           chunk=chunk, z_loss=cfg.z_loss,
                                           mask=shifted_mask,
                                           head_bias=head_bias)
        logits = h[:, :-1] @ head
        if head_bias is not None:
            logits = logits + head_bias.astype(logits.dtype)
        logits = constrain(logits, mesh, batch_ax, "sp", "tp")
        return cross_entropy(logits, shifted_labels, z_loss=cfg.z_loss,
                             mask=shifted_mask)

    # flax-style call-through so `model.apply(params, batch...)` also accepts
    # dict batches via engine's kwargs path
    def __call__(self, params, *args, **kwargs):
        return self.apply(params, *args, **kwargs)

    # ------------------------------------------------------------------
    # streamed per-layer segments (ZeRO-Infinity grad streaming)
    # ------------------------------------------------------------------
    def stream_segments(self):
        """Pure per-segment functions for the engine's streamed fwd/bwd driver
        (``runtime/zero/stream_grad.py``).  The reference's ZeRO-Infinity
        streams params *and* grads per layer (``(R)
        runtime/swap_tensor/partitioned_param_swapper.py`` role); these
        segments let the engine run one layer at a time so no [model]-sized
        buffer — params or grads — ever exists on device.

        Returns None when the model cannot be segment-streamed (pipeline
        parallelism owns the layer loop there).
        """
        cfg = self.config
        mesh = self.mesh
        if mesh is not None and not mesh.empty and axis_size(mesh, "pp") > 1:
            return None
        batch_ax = ("dp", "fsdp", "ep")

        def embed_fwd(embed, tokens):
            toks = constrain(tokens, mesh, batch_ax, "sp")
            x = jnp.take(embed["tok"], toks, axis=0)
            if cfg.position == "learned":
                x = x + embed["pos"][: toks.shape[1]][None]
            if cfg.embed_norm:
                x = norm(x, embed["norm"], "layernorm", cfg.norm_eps)
            return constrain(x, mesh, batch_ax, "sp", None)

        def layer_fwd(lp, x, key, cos, sin, use_drop):
            return self._layer(lp, x, key, cos, sin, batch_ax, use_drop)

        def head_loss(head_tree, x, labels, loss_mask):
            head = head_tree["head"]
            if cfg.tie_embeddings:  # head passed as the [V, D] tok table
                head = head.T
            return self._loss_tail(head_tree["final_norm"], head, x, labels,
                                   loss_mask,
                                   head_bias=head_tree.get("head_bias"))

        def rope(S, dtype):
            if cfg.position != "rope":
                return jnp.zeros((), dtype), jnp.zeros((), dtype)
            cos, sin = rope_cache(S, rope_dim(cfg), cfg.rope_theta)
            return cos.astype(dtype), sin.astype(dtype)

        return {
            "num_layers": cfg.num_layers,
            "dropout": cfg.dropout,
            "moe_coef": cfg.moe_aux_loss_coef if cfg.is_moe else 0.0,
            "tied": cfg.tie_embeddings,
            "embed_fwd": embed_fwd,
            "layer_fwd": layer_fwd,
            "head_loss": head_loss,
            "rope": rope,
        }


def _dropout(x, key, rate: float):
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros((), x.dtype))


def cross_entropy(logits, labels, z_loss: float = 0.0, mask=None):
    """Token-level CE in fp32; ignore_index=-100 (HF convention)."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, jnp.maximum(labels, 0)[..., None],
                               axis=-1).squeeze(-1)
    nll = lse - gold
    if z_loss:
        nll = nll + z_loss * lse ** 2
    valid = labels >= 0
    if mask is not None:
        valid = valid & (mask > 0)
    nll = jnp.where(valid, nll, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)


def blockwise_cross_entropy(x, head, labels, chunk: int, z_loss: float = 0.0,
                            mask=None, return_sums: bool = False,
                            head_bias=None):
    """LM loss without materializing the full [B, S, V] logits.

    The reference's fused-softmax CUDA kernels attack the same bandwidth
    problem from below (SURVEY.md §2.2 "Transformer training kernels"); on TPU
    the winning shape is blockwise: scan over token chunks, each producing a
    [chunk, V] logits block (one MXU matmul) reduced to per-token nll in fp32,
    with ``jax.checkpoint`` so the backward pass recomputes the block instead
    of saving it.  Peak logits memory drops from O(B·S·V) to O(chunk·V) while
    the matmuls stay MXU-sized.
    """
    B, S, D = x.shape
    N = B * S
    xf = x.reshape(N, D)
    lf = labels.reshape(N)
    mf = None if mask is None else mask.reshape(N)
    pad = (-N) % chunk
    if pad:
        xf = jnp.concatenate([xf, jnp.zeros((pad, D), xf.dtype)])
        lf = jnp.concatenate([lf, jnp.full((pad,), -100, lf.dtype)])
        if mf is not None:
            mf = jnp.concatenate([mf, jnp.zeros((pad,), mf.dtype)])
    n_blocks = xf.shape[0] // chunk
    xs = xf.reshape(n_blocks, chunk, D)
    ls = lf.reshape(n_blocks, chunk)
    ms = None if mf is None else mf.reshape(n_blocks, chunk)

    @jax.checkpoint
    def block(carry, args):
        xc, lc = args[0], args[1]
        mc = args[2] if len(args) > 2 else None
        logits = (xc @ head).astype(jnp.float32)
        if head_bias is not None:
            logits = logits + head_bias.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, jnp.maximum(lc, 0)[:, None],
                                   axis=-1).squeeze(-1)
        nll = lse - gold
        if z_loss:
            nll = nll + z_loss * lse ** 2
        valid = lc >= 0
        if mc is not None:
            valid = valid & (mc > 0)
        tot, cnt = carry
        return (tot + jnp.where(valid, nll, 0.0).sum(),
                cnt + valid.sum()), None

    xs_args = (xs, ls) if ms is None else (xs, ls, ms)
    (tot, cnt), _ = jax.lax.scan(block, (jnp.zeros((), jnp.float32),
                                         jnp.zeros((), jnp.int32)), xs_args)
    if return_sums:
        return tot, cnt
    return tot / jnp.maximum(cnt, 1)


def causal_lm(preset: str, mesh: Optional[Mesh] = None, **overrides) -> CausalLM:
    return CausalLM(get_model_config(preset, **overrides), mesh=mesh)
