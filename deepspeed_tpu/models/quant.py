"""Quantized weight tensors for serving.

Reference role: the int8 inference path (``(R)
csrc/transformer/inference/csrc/dequantize.cu``, ``init_inference(dtype=
torch.int8)``; SURVEY.md §3.5).  TPU-native shape: weights are stored as
int8 with a per-output-channel fp32 scale; ``QTensor.astype(dtype)``
dequantizes, so model code written as ``x @ w.astype(h.dtype)`` consumes
quantized or dense weights unchanged — XLA fuses the ``int8 -> bf16 *
scale`` dequant into the matmul's operand read, which is the role the CUDA
dequant kernels play.  HBM cost: ~1 byte/weight (+ scale/d_in), and decode
is bandwidth-bound, so int8 weights are also a decode *throughput* lever,
not just a memory one.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class QTensor:
    """int8 payload + broadcastable fp32 scale.  Quacks like an array for
    the handful of attributes model code touches (.astype/.shape/.ndim)."""

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    # -- array-protocol surface used by the models ----------------------
    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def size(self):
        return self.q.size

    @property
    def dtype(self):
        return jnp.int8

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def astype(self, dtype):
        """Dequantize.  fp32 multiply then cast: one fused elementwise op
        under XLA, folded into the consuming matmul's operand."""
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)

    def __matmul__(self, other):
        return self.astype(other.dtype) @ other

    def __rmatmul__(self, other):
        return other @ self.astype(other.dtype)

    # -- pytree ----------------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QTensor(int8{self.q.shape}, scale{self.scale.shape})"


def quantize_weight(w: jnp.ndarray, axis: int = -2) -> QTensor:
    """Symmetric per-output-channel int8: absmax over the contraction axis
    (default -2, the d_in dim of a [..., d_in, d_out] matmul weight)."""
    w32 = jnp.asarray(w).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=axis, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QTensor(q, scale)


def is_qtensor(x: Any) -> bool:
    return isinstance(x, QTensor)


def quantize_layer_params(params: Any, cfg=None) -> Any:
    """Quantize the transformer-layer matmul weights of a model param tree
    (>=2D leaves under ``layers`` plus ``lm_head``); embeddings (gathered,
    not matmul'd), norms, and biases stay dense.  MoE expert weights are
    left dense (the expert dispatch einsums index weights in ways QTensor
    does not mimic)."""
    out = dict(params)
    skip_mlp = bool(getattr(cfg, "is_moe", False))

    def quant_leaf(path, leaf):
        keys = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if skip_mlp and "mlp" in keys:
            return leaf
        # stacked layer trees hold matmul weights as [L, d_in, d_out]
        # (ndim 3); 2D leaves under ``layers`` are stacked per-layer
        # VECTORS (norm scales, biases) — quantizing those saves nothing
        # and their [L]-leading scale shape would break the layer scan
        if getattr(leaf, "ndim", 0) >= 3:
            return quantize_weight(leaf)
        return leaf

    if "layers" in out:
        out["layers"] = jax.tree_util.tree_map_with_path(
            quant_leaf, out["layers"])
    if "lm_head" in out and getattr(out["lm_head"], "ndim", 0) >= 2:
        out["lm_head"] = quantize_weight(out["lm_head"])
    return out


def dequantize_tree(params: Any, dtype=jnp.bfloat16) -> Any:
    """QTensor leaves -> dense arrays (for paths that need plain params)."""
    return jax.tree.map(
        lambda x: x.astype(dtype) if is_qtensor(x) else x, params,
        is_leaf=is_qtensor)
