"""Model building blocks wired to the Pallas kernel library.

These are the TPU-native counterparts of the reference's fused module zoo
(``deepspeed/ops/transformer/`` wrappers over ``csrc/transformer/*.cu``,
SURVEY.md §2.2): norms and RoPE dispatch to the Pallas kernels in
``deepspeed_tpu/ops/pallas`` (with jnp/XLA fallback off-TPU), attention runs
the blockwise flash kernel under ``shard_map`` when the mesh layout allows it,
and everything else is left to XLA fusion on purpose (the MXU gets the
matmuls; elementwise epilogues fuse).

Sharding model (GSPMD): weights carry logical tensor-parallel specs
(Megatron-style column/row split over the ``tp`` axis — the analog of the
reference's AutoTP LinearLayer/LinearAllreduce classification,
``deepspeed/module_inject/auto_tp.py``); activations get
``with_sharding_constraint`` pins at layer boundaries so XLA inserts the
all-reduce after row-parallel matmuls exactly where the reference called
``dist.all_reduce``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import axis_size, data_axes
from deepspeed_tpu.ops.pallas import (apply_rotary_pos_emb, flash_attention,
                                      layer_norm, mha_reference, rms_norm,
                                      rope_angles)
from deepspeed_tpu.ops.pallas.common import resolve_impl


def constrain(x, mesh: Optional[Mesh], *spec):
    """Pin activation sharding; no-op without a mesh.

    Axis names absent from ``mesh`` are dropped, so the built-in models'
    (dp/fsdp/tp/sp/ep) constraints degrade gracefully on custom meshes.
    Axes that are MANUAL in the current trace context (the model running
    inside a shard_map region, e.g. the ZeRO++ or 1-bit paths) are dropped
    too — with_sharding_constraint rejects manual axes, and the data is
    already device-local there.
    """
    if mesh is None or mesh.empty:
        return x
    # jax < 0.4.36 has no jax.sharding.get_abstract_mesh; fall back to the
    # private accessor, else assume no manual axes (pre-shard_map-manual jax)
    get_am = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_am is None:
        from jax._src import mesh as _mesh_lib
        get_am = getattr(_mesh_lib, "get_abstract_mesh", None)
    am = get_am() if get_am is not None else None
    manual = set(getattr(am, "manual_axes", ()) or ())
    # jax 0.4.x experimental shard_map does not surface its manual axes on
    # the abstract mesh; inside the region they ARE bound named axes, so
    # the trace-time axis env names them (observed: the overlap schedule's
    # full-manual train step tracing the model's constrain calls)
    try:
        from jax._src import core as _jcore
        manual |= set(getattr(_jcore.get_axis_env(), "axis_sizes", {}))
    except Exception:
        pass
    names = set(mesh.axis_names) - manual
    if not names:
        return x  # fully-manual region: nothing left to constrain

    def keep(entry, dim_size):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in names)
        else:
            kept = (entry,) if entry in names else ()
        # drop the whole entry if the dim doesn't divide across it (e.g.
        # batch-1 serving on a multi-chip data mesh)
        total = 1
        for a in kept:
            total *= axis_size(mesh, a)
        if not kept or dim_size % total != 0:
            return None
        return kept if len(kept) > 1 else kept[0]

    entries = tuple(keep(e, d) for e, d in zip(spec, x.shape))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*entries)))


def norm(x, params, kind: str, eps: float):
    """Dispatch to the fused Pallas norm kernels (csrc layer_norm/rms_norm)."""
    if kind == "rmsnorm":
        return rms_norm(x, params["scale"], eps=eps)
    return layer_norm(x, params["scale"], params["bias"], eps=eps)


def activation_fn(name: str):
    return {"silu": jax.nn.silu,
            "gelu": functools.partial(jax.nn.gelu, approximate=True),
            "gelu_exact": functools.partial(jax.nn.gelu, approximate=False),
            "relu": jax.nn.relu}[name]


def _repeat_kv(k, n_rep: int):
    """GQA: expand [B, Hkv, S, D] -> [B, Hkv*n_rep, S, D]."""
    if n_rep == 1:
        return k
    b, h, s, d = k.shape
    return jnp.broadcast_to(k[:, :, None], (b, h, n_rep, s, d)).reshape(b, h * n_rep, s, d)


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """Per-head ALiBi slopes (Press et al.): geometric 2^(-8i/H) for
    power-of-two H, with the standard interpolation for other head counts
    (reference: ``(R) csrc/transformer/inference/csrc/softmax.cu`` alibi
    path / HF ``build_alibi_tensor``)."""
    import math

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    n = 2 ** math.floor(math.log2(num_heads))
    slopes = pow2_slopes(n)
    if n < num_heads:
        extra = pow2_slopes(2 * n)
        slopes += extra[0::2][: num_heads - n]
    return jnp.asarray(slopes, jnp.float32)


def alibi_bias(num_heads: int, q_pos, k_pos) -> jnp.ndarray:
    """[H, |q|, |k|] additive attention bias: slope_h * (k - q) (non-positive
    under the causal mask)."""
    slopes = alibi_slopes(num_heads)
    rel = k_pos[None, :].astype(jnp.float32) - q_pos[:, None].astype(jnp.float32)
    return slopes[:, None, None] * rel[None]


def attention_core(q, k, v, mesh: Optional[Mesh], causal: bool = True,
                   impl: Optional[str] = None, sp_mode: str = "auto",
                   alibi: bool = False, ring_q: bool = False,
                   ring_q_block: int = 256):
    """Multi-head attention on [B, H, S, Dh] tensors.

    Dispatch (SURVEY.md §5.7):
    - sp > 1 and heads divisible → **Ulysses**: all-to-all seq↔head reshard
      around full-sequence attention (deepspeed_tpu/sequence/layer.py).
    - sp > 1 otherwise (or ``sp_mode="ring"``) → **ring attention**: KV
      rotation via ppermute, O(S/P) memory.
    - sp == 1 on TPU with a compatible layout → flash kernel under shard_map
      (batch over data axes, heads over ``tp``).
    - anything else → jnp reference under plain GSPMD.
    """
    impl = resolve_impl(impl)
    b, h, s, d = q.shape

    def ref_bias():
        if not alibi:
            return None
        pos = jnp.arange(s)
        return alibi_bias(h, pos, pos)[None]

    if mesh is None or mesh.empty:
        return mha_reference(q, k, v, causal=causal, bias=ref_bias())
    batch_ax = data_axes(mesh)
    nb = 1
    for a in batch_ax:
        nb *= axis_size(mesh, a)
    ntp = axis_size(mesh, "tp")
    nsp = axis_size(mesh, "sp")
    divisible = b % nb == 0 and h % ntp == 0
    if nsp > 1 and divisible and s % nsp == 0:
        if alibi:
            raise NotImplementedError(
                "alibi + sequence parallelism is not supported (the ring/"
                "ulysses shards would need position-offset bias plumbing)")
        from deepspeed_tpu.sequence.layer import ring_attention, ulysses_attention
        local_heads = h // ntp
        if sp_mode == "ring" or local_heads % nsp != 0:
            # ring_q: comm_quantization.sequence_ring — the KV rotation
            # carries int8 codes (quantized once) instead of dense chunks
            return ring_attention(q, k, v, mesh, causal=causal,
                                  quantized=ring_q,
                                  quant_block=ring_q_block)
        inner = None
        if impl == "pallas" and s % 128 == 0:
            inner = functools.partial(flash_attention, causal=causal)
        return ulysses_attention(q, k, v, mesh, attn_fn=inner, causal=causal)
    if alibi and ntp > 1:
        raise NotImplementedError(
            "alibi + tensor parallelism needs per-shard head-slope offsets; "
            "serve BLOOM-class models with tp=1 for now")
    if impl != "pallas" or nsp > 1 or not divisible or s % 128 != 0:
        return mha_reference(q, k, v, causal=causal, bias=ref_bias())
    spec = P(batch_ax, "tp", None, None)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def _sharded(qq, kk, vv):
        return flash_attention(qq, kk, vv, causal=causal, alibi=alibi)

    return _sharded(q, k, v)


def rope_cache(seq_len: int, head_dim: int, theta: float):
    return rope_angles(jnp.arange(seq_len), head_dim, theta=theta)


def apply_partial_rope(x, cos, sin):
    """Rotate the first ``2*cos.shape[-1]`` head dims, pass the rest through
    (gpt-neox ``rotary_pct``).  The rotated span is defined by the cos/sin
    width alone — build them with :func:`rope_cache` over ``rope_dim(cfg)``."""
    rot = 2 * cos.shape[-1]
    if rot == x.shape[-1]:
        return apply_rotary_pos_emb(x, cos, sin)
    rotated = apply_rotary_pos_emb(x[..., :rot], cos, sin)
    return jnp.concatenate([rotated, x[..., rot:]], axis=-1)


def rope_dim(cfg) -> int:
    """Rotated head dims (even; head_dim * rotary_pct, neox convention)."""
    d = int(cfg.head_dim * cfg.rotary_pct)
    return max(2, d - (d % 2))
