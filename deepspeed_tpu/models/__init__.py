"""Built-in model families (Llama / GPT-2 / Mixtral), TPU-native.

The reference wraps external torch models (SURVEY.md §2.1 module_inject
policies); a jax framework ships its own functional implementations of the
same architecture families instead.
"""

from deepspeed_tpu.models.config import ModelConfig, get_model_config
from deepspeed_tpu.models.transformer import CausalLM, causal_lm, cross_entropy

__all__ = ["ModelConfig", "get_model_config", "CausalLM", "causal_lm",
           "cross_entropy"]
