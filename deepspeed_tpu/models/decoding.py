"""KV-cache prefill/decode paths for the built-in models.

TPU-native counterpart of the reference's inference kernel path
(``csrc/transformer/inference/``: preallocated KV-cache workspace in
``inference_context.h`` sized by ``max_out_tokens``, fused decode kernels;
SURVEY.md §2.2, §3.5).  The cache is a functional pytree of static-shape
[L, B, Hkv, Smax, Dh] buffers updated with ``dynamic_update_slice`` and
donated across steps by the engine — the jax equivalent of the reference's
global inference workspace arena.

Prefill attends densely under a position mask; decode (s=1) runs a
length-aware flash-decode: online softmax over cache blocks inside a
``lax.while_loop`` bounded by the current position, so per-token attention
work tracks the sequence actually generated instead of ``Smax`` — while the
traced program stays static-shape (one compiled step).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.layers import (activation_fn, apply_partial_rope,
                                         constrain, norm, _repeat_kv, rope_dim)
from deepspeed_tpu.ops.pallas import rope_angles

NEG_INF = -1e30


def init_kv_cache(cfg, batch: int, max_len: int, dtype=jnp.bfloat16,
                  quantized: bool = False) -> Dict[str, Any]:
    """``quantized=True`` stores int8 K/V with a per-(position, head) fp32
    scale over the head dim — ~1.03 bytes/element vs 2 for bf16 (reference
    int8 KV role, ``(R) inference_context.h`` workspace + dequant kernels).

    Caches longer than one decode block are rounded UP to a block multiple
    so the length-aware flash-decode path always applies (the padding rows
    cost memory only; they are never visited)."""
    L, Hkv, Dh = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    if max_len > DECODE_BLOCK and max_len % DECODE_BLOCK:
        rounded = -(-max_len // DECODE_BLOCK) * DECODE_BLOCK
        # callers sizing masks/position buffers must read cache['k'].shape[-2]
        # rather than their requested max_len — say so, once
        global _WARNED_ROUNDED_CACHE
        if not _WARNED_ROUNDED_CACHE:
            _WARNED_ROUNDED_CACHE = True
            from deepspeed_tpu.utils.logging import logger

            logger.info(
                "init_kv_cache: max_len %d rounded up to %d (a %d-multiple) "
                "for the flash-decode path; size position buffers from "
                "cache['k'].shape[-2]", max_len, rounded, DECODE_BLOCK)
        max_len = rounded
    if quantized:
        return {
            "k": jnp.zeros((L, batch, Hkv, max_len, Dh), jnp.int8),
            "v": jnp.zeros((L, batch, Hkv, max_len, Dh), jnp.int8),
            "k_scale": jnp.zeros((L, batch, Hkv, max_len, 1), jnp.float32),
            "v_scale": jnp.zeros((L, batch, Hkv, max_len, 1), jnp.float32),
            # decode activations still need a dtype anchor (cache dtype is
            # int8); keep it alongside the buffers
            "x_dtype": jnp.zeros((), dtype),
        }
    return {
        "k": jnp.zeros((L, batch, Hkv, max_len, Dh), dtype),
        "v": jnp.zeros((L, batch, Hkv, max_len, Dh), dtype),
    }


def _quantize_kv_rows(x):
    """[B, Hkv, s, Dh] -> (int8 payload, fp32 [B, Hkv, s, 1] scale)."""
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


DECODE_BLOCK = 256  # flash-decode cache block (power of two, MXU-friendly)
_WARNED_ODD_CACHE = False
_WARNED_ROUNDED_CACHE = False


def _as_row_pos(q_pos):
    """Normalize query positions to [Bq, s]: a [s] vector is shared across
    the batch (Bq=1 broadcasts); a [B, s] matrix is per-row (continuous
    batching, where every sequence sits at its own depth)."""
    q_pos = jnp.asarray(q_pos)
    return q_pos[None] if q_pos.ndim == 1 else q_pos


def _cached_attention_dense(q, kcache, vcache, q_pos, scale, k_scale=None,
                            v_scale=None, slopes=None):
    """Masked attention over the whole static cache (prefill path, s > 1);
    int8 caches are dequantized on the fly (fused into the einsum reads);
    ``slopes`` [H] adds the ALiBi per-head linear position bias.  ``q_pos``
    is [s] (batch-shared) or [B, s] (per-row positions)."""
    B, H, s, Dh = q.shape
    Hkv = kcache.shape[1]
    q_pos = _as_row_pos(q_pos)                         # [Bq, s]
    kf = kcache.astype(jnp.float32)
    vf = vcache.astype(jnp.float32)
    if k_scale is not None:
        kf = kf * k_scale
    if v_scale is not None:
        vf = vf * v_scale
    k = _repeat_kv(kf, H // Hkv)
    v = _repeat_kv(vf, H // Hkv)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k) * scale
    key_pos = jnp.arange(k.shape[-2])
    if slopes is not None:
        rel = (key_pos[None, None, :] - q_pos[:, :, None]).astype(jnp.float32)
        logits = logits + slopes[None, :, None, None] * rel[:, None]
    mask = key_pos[None, None, :] <= q_pos[:, :, None]  # causal vs absolute pos
    logits = jnp.where(mask[:, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return out.astype(q.dtype)


def _cached_attention_flash_decode(q, kcache, vcache, q_pos, scale,
                                   k_scale=None, v_scale=None, slopes=None,
                                   block: int = DECODE_BLOCK):
    """Length-aware decode attention (VERDICT r3 weak #10): online-softmax
    over cache blocks, visiting only blocks up to the current position — a
    ``lax.while_loop`` flash-decode whose per-token compute is
    O(cur_len rounded up to ``block``), not O(Smax).  The dense path scans
    the whole static cache every token, which at Smax=8k and cur_len=100 is
    ~80x wasted attention FLOPs/bandwidth."""
    B, H, s, Dh = q.shape
    Hkv = kcache.shape[1]
    Smax = kcache.shape[2]
    rep = H // Hkv
    q_pos = _as_row_pos(q_pos)                         # [Bq, s]
    qf = q.astype(jnp.float32)
    # visit blocks [0, n_blocks): everything at or before the newest query
    # (per-row positions: the deepest row bounds the loop; shallower rows'
    # extra blocks are fully masked, and exp(NEG_INF - m) underflows to an
    # exact 0 contribution, so per-row outputs match a per-row-bounded scan)
    n_blocks = jnp.max(q_pos) // block + 1

    def body(carry):
        i, m, l, acc = carry
        start = i * block
        kb = jax.lax.dynamic_slice_in_dim(kcache, start, block, axis=2)
        vb = jax.lax.dynamic_slice_in_dim(vcache, start, block, axis=2)
        kb = kb.astype(jnp.float32)
        vb = vb.astype(jnp.float32)
        if k_scale is not None:
            ksb = jax.lax.dynamic_slice_in_dim(k_scale, start, block, axis=2)
            kb = kb * ksb
        if v_scale is not None:
            vsb = jax.lax.dynamic_slice_in_dim(v_scale, start, block, axis=2)
            vb = vb * vsb
        kb = _repeat_kv(kb, rep)
        vb = _repeat_kv(vb, rep)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qf, kb) * scale
        key_pos = start + jnp.arange(block)
        if slopes is not None:
            rel = (key_pos[None, None, :]
                   - q_pos[:, :, None]).astype(jnp.float32)
            logits = logits + slopes[None, :, None, None] * rel[:, None]
        mask = key_pos[None, None, :] <= q_pos[:, :, None]  # [Bq, s, block]
        logits = jnp.where(mask[:, None], logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l_new = l * correction + p.sum(axis=-1)
        acc_new = (acc * correction[..., None]
                   + jnp.einsum("bhqk,bhkd->bhqd", p, vb))
        return i + 1, m_new, l_new, acc_new

    init = (jnp.zeros((), jnp.int32),
            jnp.full((B, H, s), NEG_INF, jnp.float32),
            jnp.zeros((B, H, s), jnp.float32),
            jnp.zeros((B, H, s, Dh), jnp.float32))
    _, m, l, acc = jax.lax.while_loop(lambda c: c[0] < n_blocks, body, init)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _cached_attention(q, kcache, vcache, q_pos, scale, k_scale=None,
                      v_scale=None, slopes=None):
    """q: [B, H, s, Dh]; caches: [B, Hkv, Smax, Dh]; q_pos: absolute
    positions of the queries — [s] (batch-shared) or [B, s] (per-row, the
    continuous-batching decode where every sequence is at its own depth).
    Decode (s == 1, cache larger than one block) takes the length-aware
    flash-decode path; prefill stays dense.  ``slopes`` [H] = ALiBi bias."""
    s = q.shape[2]
    Smax = kcache.shape[2]
    if s == 1 and Smax > DECODE_BLOCK:
        if Smax % DECODE_BLOCK == 0:
            return _cached_attention_flash_decode(q, kcache, vcache, q_pos,
                                                  scale, k_scale, v_scale,
                                                  slopes)
        # init_kv_cache rounds lengths up; an externally-built odd cache
        # falls back to the dense scan — say so, once
        global _WARNED_ODD_CACHE
        if not _WARNED_ODD_CACHE:
            _WARNED_ODD_CACHE = True
            from deepspeed_tpu.utils.logging import logger

            logger.warning(
                "decode: cache length %d is not a multiple of %d; the "
                "length-aware flash-decode is disabled and every token "
                "re-scans the full cache (build caches via init_kv_cache)",
                Smax, DECODE_BLOCK)
    return _cached_attention_dense(q, kcache, vcache, q_pos, scale,
                                   k_scale, v_scale, slopes)


def _rope_rows(t, cos, sin):
    """Per-row partial RoPE: t [B, Hx, s, Dh]; cos/sin [B, s, half] carry
    each row's own absolute positions (continuous-batching decode)."""
    rot = 2 * cos.shape[-1]
    half = cos.shape[-1]
    c = cos[:, None].astype(jnp.float32)               # [B, 1, s, half]
    sn = sin[:, None].astype(jnp.float32)
    x1 = t[..., :half].astype(jnp.float32)
    x2 = t[..., half:rot].astype(jnp.float32)
    r = jnp.concatenate([x1 * c - x2 * sn, x2 * c + x1 * sn],
                        axis=-1).astype(t.dtype)
    return (jnp.concatenate([r, t[..., rot:]], axis=-1)
            if rot < t.shape[-1] else r)


def paged_logical_view(buf, page_table):
    """Gather a slot-contiguous LOGICAL cache view out of the paged pool:
    ``buf`` [P, Hkv, page, D] physical pages, ``page_table`` [B, maxp]
    int32 -> [B, Hkv, maxp*page, D].  The XLA fallback/reference read path
    for the paged cache (the Pallas flash-decode kernel instead indirects
    its DMA index map through the table, so no gather materializes);
    unallocated table entries gather the junk page, whose rows sit beyond
    every live position and are masked like any other padding."""
    g = buf[page_table]                                 # [B, maxp, page...]
    B, mp, Hkv, pg, D = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, mp * pg, D)


def _scatter_paged_rows(buf, rows, pos, page_table):
    """Per-row single-token append through the page table: ``rows``
    [B, Hkv, 1, D] written at logical positions ``pos`` [B] into the
    physical pool ``buf`` [P, Hkv, page, D] (row b lands at row
    ``pos[b] % page`` of page ``page_table[b, pos[b] // page]``).  One
    batched scatter, same aliasing argument as :func:`_scatter_rows`;
    parked rows (table pointing at junk page 0) scatter junk harmlessly."""
    B = rows.shape[0]
    page = buf.shape[2]
    pp = page_table[jnp.arange(B), pos // page]         # physical page [B]
    po = pos % page
    return buf.at[pp, :, po, :].set(rows[:, :, 0, :].astype(buf.dtype))


def _scatter_rows(buf, rows, start_pos):
    """Write ``rows`` [B, Hx, s, D] into ``buf`` [B, Hx, Smax, D] at
    per-row start positions ``start_pos`` [B] (each batch row lands at its
    own cache depth) as ONE batched scatter — measured much faster than a
    per-row dynamic_update_slice loop, whose per-row dynamic start index
    defeats XLA's in-place aliasing and copies the buffer per write."""
    B, _, s, _ = rows.shape
    bidx = jnp.arange(B)[:, None]                      # [B, 1]
    pidx = start_pos[:, None] + jnp.arange(s)[None, :]  # [B, s]
    return buf.at[bidx, :, pidx, :].set(
        rows.transpose(0, 2, 1, 3).astype(buf.dtype))


def forward_with_cache(model, params, tokens, cache, start_pos,
                       page_table=None):
    """Run the model over ``tokens`` [B, s] starting at absolute position
    ``start_pos``, reading/updating the KV cache.

    ``start_pos`` is a scalar (the whole batch at one depth — static-batch
    prefill/decode) or an int32 [B] vector of per-row positions (the
    continuous-batching decode, where every slot sits at its own depth).

    ``page_table`` [B, maxp] switches the cache to the PAGED layout
    (``serving/paged_kv.py``: [L, num_pages, Hkv, page, Dh] pools shared
    by all slots): appends scatter through the table and reads gather a
    logical view per layer — the dense XLA fallback/reference for the
    Pallas paged kernel.  Paged mode is decode-only (``s == 1``, per-row
    positions); serving prefill gathers the slot's pages around this
    function instead.

    Returns (logits [B, s, V], new_cache).  Used for prefill (s = prompt
    length, start_pos=0), decode (s = 1), and chunked per-slot prefill
    (s = chunk, scalar start_pos = chunk offset).
    """
    cfg = model.config
    mesh = model.mesh
    batch_ax = ("dp", "fsdp", "ep")
    B, s = tokens.shape
    H, Hkv, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    quant_kv = "k_scale" in cache
    start_pos = jnp.asarray(start_pos, jnp.int32)
    per_row = start_pos.ndim == 1                      # [B] vector of depths
    paged = page_table is not None
    if paged and (not per_row or s != 1):
        raise ValueError("paged KV decode requires per-row positions and "
                         "s == 1 (prefill runs on a gathered slot view)")
    x = jnp.take(params["embed"]["tok"], tokens, axis=0)
    if cfg.position == "learned":
        if per_row:
            pos_idx = start_pos[:, None] + jnp.arange(s)       # [B, s]
            x = x + jnp.take(params["embed"]["pos"], pos_idx, axis=0)
        else:
            pos_idx = start_pos + jnp.arange(s)
            x = x + jnp.take(params["embed"]["pos"], pos_idx, axis=0)[None]
    if cfg.embed_norm:  # bloom word_embeddings_layernorm
        x = norm(x, params["embed"]["norm"], "layernorm", cfg.norm_eps)
    x = x.astype(cache["x_dtype"].dtype if quant_kv else cache["k"].dtype)
    x = constrain(x, mesh, batch_ax, None, None)
    if per_row:
        q_pos = start_pos[:, None] + jnp.arange(s)             # [B, s]
    else:
        q_pos = start_pos + jnp.arange(s)                      # [s]
    if cfg.position == "alibi":
        from deepspeed_tpu.models.layers import alibi_slopes
        slopes = alibi_slopes(H)
    else:
        slopes = None

    # logical sequence capacity: the paged pool's per-slot window is the
    # page table width x page depth, not the physical buffer's last dim
    s_max = cache["k"].shape[-2] * (page_table.shape[1] if paged else 1)
    if cfg.position == "rope":
        # angles for the whole cache window once; gather the query slice
        cos_all, sin_all = rope_angles(jnp.arange(s_max),
                                       rope_dim(cfg), theta=cfg.rope_theta)
        if per_row:
            cos = cos_all[q_pos].astype(x.dtype)               # [B, s, half]
            sin = sin_all[q_pos].astype(x.dtype)
        else:
            cos = jax.lax.dynamic_slice_in_dim(cos_all, start_pos,
                                               s).astype(x.dtype)
            sin = jax.lax.dynamic_slice_in_dim(sin_all, start_pos,
                                               s).astype(x.dtype)
    else:
        cos = sin = jnp.zeros((), x.dtype)
    scale = 1.0 / (Dh ** 0.5)

    def layer_step(carry, xs):
        h_in = carry
        if quant_kv:
            lp, kc, vc, ksc, vsc = xs
        else:
            lp, kc, vc = xs
            ksc = vsc = None
        x0 = h_in  # layer input (parallel residual reads it twice)
        h = norm(h_in, lp["attn_norm"], cfg.norm, cfg.norm_eps)
        a = lp["attn"]
        q = h @ a["wq"].astype(h.dtype)
        k = h @ a["wk"].astype(h.dtype)
        v = h @ a["wv"].astype(h.dtype)
        if cfg.use_bias or cfg.qkv_bias:
            q = q + a["bq"].astype(h.dtype)
            k = k + a["bk"].astype(h.dtype)
            v = v + a["bv"].astype(h.dtype)
        q = q.reshape(B, s, H, Dh).transpose(0, 2, 1, 3)
        k = k.reshape(B, s, Hkv, Dh).transpose(0, 2, 1, 3)
        v = v.reshape(B, s, Hkv, Dh).transpose(0, 2, 1, 3)
        if cfg.position == "rope":
            if per_row:
                q = _rope_rows(q, cos, sin)
                k = _rope_rows(k, cos, sin)
            else:
                q = apply_partial_rope(q, cos, sin)
                k = apply_partial_rope(k, cos, sin)
        if quant_kv:
            kq, ks = _quantize_kv_rows(k)
            vq, vs = _quantize_kv_rows(v)
            if paged:
                kc = _scatter_paged_rows(kc, kq, start_pos, page_table)
                vc = _scatter_paged_rows(vc, vq, start_pos, page_table)
                ksc = _scatter_paged_rows(ksc, ks, start_pos, page_table)
                vsc = _scatter_paged_rows(vsc, vs, start_pos, page_table)
            elif per_row:
                kc = _scatter_rows(kc, kq, start_pos)
                vc = _scatter_rows(vc, vq, start_pos)
                ksc = _scatter_rows(ksc, ks, start_pos)
                vsc = _scatter_rows(vsc, vs, start_pos)
            else:
                kc = jax.lax.dynamic_update_slice(kc, kq, (0, 0, start_pos, 0))
                vc = jax.lax.dynamic_update_slice(vc, vq, (0, 0, start_pos, 0))
                ksc = jax.lax.dynamic_update_slice(ksc, ks,
                                                   (0, 0, start_pos, 0))
                vsc = jax.lax.dynamic_update_slice(vsc, vs,
                                                   (0, 0, start_pos, 0))
        elif paged:
            kc = _scatter_paged_rows(kc, k, start_pos, page_table)
            vc = _scatter_paged_rows(vc, v, start_pos, page_table)
        elif per_row:
            kc = _scatter_rows(kc, k, start_pos)
            vc = _scatter_rows(vc, v, start_pos)
        else:
            kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype),
                                              (0, 0, start_pos, 0))
            vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                              (0, 0, start_pos, 0))
        if paged:
            # XLA fallback read: gather the logical per-slot view through
            # the table (junk-page rows sit past every live position and
            # mask out); the Pallas kernel path never materializes this
            o = _cached_attention(
                q, paged_logical_view(kc, page_table),
                paged_logical_view(vc, page_table), q_pos, scale,
                paged_logical_view(ksc, page_table) if quant_kv else None,
                paged_logical_view(vsc, page_table) if quant_kv else None,
                slopes)
        else:
            o = _cached_attention(q, kc, vc, q_pos, scale, ksc, vsc, slopes)
        o = o.transpose(0, 2, 1, 3).reshape(B, s, H * Dh)
        o = o @ a["wo"].astype(h.dtype)
        if cfg.use_bias:
            o = o + a["bo"].astype(h.dtype)
        if cfg.parallel_residual:
            # gpt-neox: MLP reads the LAYER INPUT; both branches add at once
            mlp_src = x0
        else:
            h_in = h_in + o
            mlp_src = h_in

        h = norm(mlp_src, lp["mlp_norm"], cfg.norm, cfg.norm_eps)
        if cfg.is_moe:
            from deepspeed_tpu.moe.sharded_moe import moe_mlp
            mlp_out, _ = moe_mlp(jax.tree.map(lambda a: a.astype(h.dtype), lp["mlp"]),
                                 h, cfg, mesh)
        else:
            act = activation_fn(cfg.activation)
            m = lp["mlp"]
            up = h @ m["w_up"].astype(h.dtype)
            if cfg.has_mlp_bias:
                up = up + m["b_up"].astype(h.dtype)
            if cfg.glu:
                gate = h @ m["w_gate"].astype(h.dtype)
                if cfg.has_mlp_bias:
                    gate = gate + m["b_gate"].astype(h.dtype)
                gated = act(gate) * up
            else:
                gated = act(up)
            mlp_out = gated @ m["w_down"].astype(h.dtype)
            if cfg.has_mlp_bias:
                mlp_out = mlp_out + m["b_down"].astype(h.dtype)
        h_in = (x0 + o + mlp_out) if cfg.parallel_residual else (h_in + mlp_out)
        if quant_kv:
            return h_in, (kc, vc, ksc, vsc)
        return h_in, (kc, vc)

    if quant_kv:
        x, (kc_new, vc_new, ks_new, vs_new) = jax.lax.scan(
            layer_step, x, (params["layers"], cache["k"], cache["v"],
                            cache["k_scale"], cache["v_scale"]))
        new_cache = {"k": kc_new, "v": vc_new, "k_scale": ks_new,
                     "v_scale": vs_new, "x_dtype": cache["x_dtype"]}
    else:
        x, (kc_new, vc_new) = jax.lax.scan(
            layer_step, x, (params["layers"], cache["k"], cache["v"]))
        new_cache = {"k": kc_new, "v": vc_new}
    x = norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    if cfg.tie_embeddings:
        head = params["embed"]["tok"].T.astype(x.dtype)
    else:
        head = params["lm_head"].astype(x.dtype)  # QTensor-aware (.astype)
    logits = (x @ head).astype(jnp.float32)
    if cfg.lm_head_bias:
        logits = logits + params["lm_head_bias"].astype(jnp.float32)
    return logits, new_cache


def sample_token(logits, rng, temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 1.0, do_sample: bool = True):
    """logits: [B, V] -> token ids [B] (greedy when do_sample=False)."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)            # first idx past mass
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, NEG_INF, logits)
    return jax.random.categorical(rng, logits, axis=-1)
