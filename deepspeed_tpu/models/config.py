"""Model configurations for the built-in model families.

The reference ships no model zoo of its own — it wraps user/HF torch modules
(SURVEY.md §2.1 "Module injection / TP": policies for BERT, GPT2, GPT-Neo/J/
NeoX, OPT, BLOOM, LLaMA, Megatron).  A TPU-native framework cannot wrap torch
modules, so we ship functional jax implementations of the same architecture
families instead; `ModelConfig` spans them with feature flags:

- Llama family  : RMSNorm + RoPE + SwiGLU + GQA   (``llama`` presets)
- GPT-2 family  : LayerNorm + learned positions + GELU (``gpt2`` presets)
- Mixtral family: Llama backbone + top-k MoE MLP  (``mixtral`` presets)

All presets follow the public architecture descriptions of those model
families; sizes match the milestone configs in BASELINE.json.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class ModelConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None     # GQA; None -> == num_heads
    head_dim: Optional[int] = None         # None -> hidden_size // num_heads
    max_seq_len: int = 4096
    norm: str = "rmsnorm"                  # "rmsnorm" (llama) | "layernorm" (gpt2)
    norm_eps: float = 1e-5
    activation: str = "silu"               # "silu" (swiglu) | "gelu"
    glu: bool = True                       # gated MLP (llama) vs plain (gpt2)
    position: str = "rope"                 # "rope" | "learned" | "alibi"
    rope_theta: float = 10000.0
    tie_embeddings: bool = False
    use_bias: bool = False                 # attn/mlp projection biases (gpt2)
    qkv_bias: bool = False                 # biases on q/k/v only (qwen2)
    mlp_bias: bool = False                 # biases on the MLP only (gpt-j)
    lm_head_bias: bool = False             # bias on the LM head (gpt-j)
    embed_norm: bool = False               # layernorm after token embed (bloom)
    # gpt-neox/pythia: x + attn(ln1(x)) + mlp(ln2(x)) — the MLP reads the
    # LAYER INPUT, not the post-attention stream
    parallel_residual: bool = False
    rotary_pct: float = 1.0                # fraction of head dims rotated (neox)
    dropout: float = 0.0                   # residual dropout (needs a dropout rng)
    # MoE (mixtral family); num_experts == 0 -> dense MLP
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coef: float = 0.01
    moe_drop_tokens: bool = True       # False -> capacity covers every token
    moe_use_rts: bool = False          # random token selection for capacity
    # "scatter": O(N·k·D) scatter/gather dispatch (default);
    # "einsum": GShard one-hot [N,E,C] einsums (O(N²·k/E), parity reference)
    moe_dispatch: str = "scatter"
    # quantized-collective transport (ds_config "comm_quantization" sets
    # these at engine init; comm/collectives_q.py): int8 codes cross the
    # ep dispatch boundary / the sp ring instead of dense activations
    moe_q_dispatch: bool = False
    seq_ring_q: bool = False
    comm_quant_block: int = 256
    # pipeline boundary transport (ds_config "comm_quantization.pipeline"
    # arms pp_boundary_q at engine init): int8 codes + block scales ride
    # the stage-boundary rings instead of the dense activation/cotangent
    pp_boundary_q: bool = False
    # trace-time boundary byte ledger (runtime/pipe/spmd.py).  The engine
    # sets this False and commits its analytic per-execution comm plan
    # instead — the two feeds must stay disjoint (double-count rule)
    pp_comm_record: bool = True
    # training-time knobs
    sp_mode: str = "auto"                  # "auto" | "ulysses" | "ring" (sp>1)
    pp_microbatches: int = 0               # pipeline microbatches (0 -> pp size)
    # "gpipe": fill-drain scan + autodiff (stashes M+pp-1 boundaries);
    # "1f1b": fused fwd+bwd scan, circular buffer of 2pp-1 boundaries —
    # the reference TrainSchedule's memory bound (training with labels only)
    pp_schedule: str = "gpipe"
    # Activation checkpointing (ds_config "activation_checkpointing" section
    # overrides these at engine init). None = off: recompute-in-backward costs
    # ~1/3 extra FLOPs, so it must be opted into when the model doesn't fit,
    # not paid by default. Large presets below turn it on.
    remat: Optional[bool] = None
    # "full" | "dots" | "mlp_only" | "mlp_dots" | "offload_dots" (saved
    # matmul outputs page to pinned host memory — cpu_checkpointing)
    remat_policy: str = "full"
    # ZeRO-Infinity parameter tiering (engine sets this from ds_config
    # offload_param): params live in host memory; the forward streams each
    # scanned layer's weights to the device on demand, so device-resident
    # param bytes are O(one layer), not O(model).
    param_offload: bool = False
    scan_layers: bool = True               # lax.scan over stacked layer params
    z_loss: float = 0.0
    # Cross-entropy chunking (tokens per block; the [chunk, V] logits block is
    # the only logits materialization). 0 = dense; None = auto (chunk when the
    # full [B*S, V] fp32 logits would exceed ~2^28 elements).
    ce_chunk: Optional[int] = None

    def __post_init__(self):
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_heads
        assert self.num_heads % self.num_kv_heads == 0
        if self.pp_schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"pp_schedule must be 'gpipe' or '1f1b', got "
                             f"{self.pp_schedule!r}")
        if self.position not in ("rope", "learned", "alibi"):
            raise ValueError(f"position must be 'rope', 'learned' or "
                             f"'alibi', got {self.position!r}")

    @property
    def has_mlp_bias(self) -> bool:
        return self.use_bias or self.mlp_bias

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0


_PRESETS = {
    # GPT-2 family (BASELINE.json configs[1]: GPT-2 125M rung)
    "gpt2-small": dict(vocab_size=50257, hidden_size=768, intermediate_size=3072,
                       num_layers=12, num_heads=12, max_seq_len=1024,
                       norm="layernorm", activation="gelu", glu=False,
                       position="learned", tie_embeddings=True),
    "gpt2-medium": dict(vocab_size=50257, hidden_size=1024, intermediate_size=4096,
                        num_layers=24, num_heads=16, max_seq_len=1024,
                        norm="layernorm", activation="gelu", glu=False,
                        position="learned", tie_embeddings=True),
    "gpt2-xl": dict(vocab_size=50257, hidden_size=1600, intermediate_size=6400,
                    num_layers=48, num_heads=25, max_seq_len=1024,
                    norm="layernorm", activation="gelu", glu=False,
                    position="learned", tie_embeddings=True, remat=True),
    # Llama family (configs[2]/[4]: 8B on v5p-8, 70B on v5p-128; llama2-7b is
    # the BASELINE.json "7B" north-star size)
    "llama-tiny": dict(vocab_size=32000, hidden_size=256, intermediate_size=688,
                       num_layers=4, num_heads=8, num_kv_heads=4, max_seq_len=2048),
    # 1.34B dense rung (VERDICT r4 item 1: a >1B model that fits one 16GB
    # chip with int8 optimizer states + bf16 grad accum + remat).  Vocab
    # padded to a multiple of 128 for MXU tiling; head_dim 128 fills the
    # systolic array (D=64 heads halve it — see ops/pallas notes).
    "llama-1b4": dict(vocab_size=50304, hidden_size=2048, intermediate_size=5632,
                      num_layers=24, num_heads=16, num_kv_heads=16,
                      max_seq_len=2048, tie_embeddings=True, remat=True,
                      remat_policy="mlp_dots"),
    "llama2-7b": dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                      num_layers=32, num_heads=32, max_seq_len=4096, remat=True),
    "llama2-13b": dict(vocab_size=32000, hidden_size=5120, intermediate_size=13824,
                       num_layers=40, num_heads=40, max_seq_len=4096, remat=True),
    "llama3-8b": dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                      num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192,
                      rope_theta=500000.0, remat=True),
    "llama3-70b": dict(vocab_size=128256, hidden_size=8192, intermediate_size=28672,
                       num_layers=80, num_heads=64, num_kv_heads=8, max_seq_len=8192,
                       rope_theta=500000.0, remat=True),
    # Mixtral family (configs[3]: MoE expert-parallel rung)
    "mixtral-tiny": dict(vocab_size=32000, hidden_size=256, intermediate_size=512,
                         num_layers=4, num_heads=8, num_kv_heads=4, max_seq_len=2048,
                         num_experts=8, num_experts_per_tok=2),
    "mixtral-8x7b": dict(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                         num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192,
                         rope_theta=1000000.0, num_experts=8, num_experts_per_tok=2,
                         remat=True),
}


def get_model_config(name: str, **overrides) -> ModelConfig:
    if name not in _PRESETS:
        raise KeyError(f"unknown model preset {name!r}; available: {sorted(_PRESETS)}")
    kw = dict(_PRESETS[name])
    kw.update(overrides)
    return ModelConfig(**kw)
