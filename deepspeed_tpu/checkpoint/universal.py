"""Universal checkpoint: topology-independent per-parameter layout.

Reference: ``deepspeed/checkpoint/`` — ``ds_to_universal.py`` converts a
(DP/TP/PP)-sharded checkpoint into per-parameter canonical fragments that can
be loaded at a different parallel topology (SURVEY.md §2.1, §5.4).

The TPU-native checkpoint already stores logically-full arrays, so *any*
checkpoint loads at any mesh (re-sharding is ``device_put`` with the new
topology's shardings).  The universal format still earns its keep for:
- per-parameter files → partial/streamed loading of huge models,
- a stable, inspectable on-disk contract (name → .npy) for external tools,
- stacked-layer splitting (the reference's per-layer files) so a checkpoint
  from ``scan_layers`` models can initialize per-layer consumers.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np


class DeepSpeedCheckpoint:
    """Inspection API over a native checkpoint dir (reference class name).

    The reference exposes tp/pp/dp degrees parsed from filename patterns; the
    TPU format records them in ``client_state.json``.
    """

    def __init__(self, ckpt_dir: str, tag: Optional[str] = None):
        self.dir = ckpt_dir
        if tag is None:
            with open(os.path.join(ckpt_dir, "latest")) as fh:
                tag = fh.read().strip()
        self.tag = str(tag)
        self.path = os.path.join(ckpt_dir, self.tag)
        meta_path = os.path.join(self.path, "client_state.json")
        self.meta: Dict[str, Any] = {}
        if os.path.exists(meta_path):
            with open(meta_path) as fh:
                self.meta = json.load(fh)

    @property
    def zero_stage(self) -> int:
        return int(self.meta.get("zero_stage", 0))

    @property
    def world_size(self) -> int:
        return int(self.meta.get("world_size", 1))

    def load_params(self) -> Any:
        return self._load_payload("model_states")

    def load_optim(self) -> Optional[Any]:
        """Optimizer-state dict (``opt_state`` + step bookkeeping) or None for
        a params-only checkpoint."""
        return self._load_payload("optim_states", optional=True)

    def _load_payload(self, name: str, optional: bool = False):
        from deepspeed_tpu.runtime.checkpoint_engine import (MsgpackCheckpointEngine,
                                                             ShardedCheckpointEngine,
                                                             is_sharded_checkpoint)
        from deepspeed_tpu.runtime.checkpoint_engine.sharded import nest_keystrs

        sharded = os.path.join(self.path, name)
        if is_sharded_checkpoint(sharded):
            return nest_keystrs(ShardedCheckpointEngine().load(sharded))
        legacy = os.path.join(self.path, name + ".msgpack")
        if not os.path.exists(legacy):
            if optional:
                return None
            raise FileNotFoundError(f"no {name} payload in {self.path}")
        return MsgpackCheckpointEngine().load(legacy)


def ds_to_universal(input_dir: str, output_dir: str, tag: Optional[str] = None,
                    split_layers: bool = False) -> str:
    """Convert a native checkpoint to the universal per-parameter layout:

    output_dir/
      meta.json                     (source meta + param/optim index)
      params/<path with '/'→'.'>.npy
      optim/<path with '/'→'.'>.npy  (exp_avg/exp_avg_sq/... leaves, so a
                                      universal checkpoint can resume training
                                      at a different topology, matching the
                                      reference's fp32 master + optimizer
                                      fragment export)
    With ``split_layers=True``, stacked [L, ...] layer params are written as
    one file per layer (<name>.layer<k>.npy), the reference's per-layer form.
    """
    from deepspeed_tpu.utils.tensor_fragment import _path_str

    ckpt = DeepSpeedCheckpoint(input_dir, tag)
    params = ckpt.load_params()

    def export_tree(tree, subdir: str) -> Dict[str, Any]:
        out = os.path.join(output_dir, subdir)
        os.makedirs(out, exist_ok=True)
        index: Dict[str, Any] = {}
        for pth, leaf in jax.tree_util.tree_leaves_with_path(tree):
            name = _path_str(pth)
            fname = name.replace("/", ".")
            arr = np.asarray(leaf)
            if split_layers and name.startswith("layers/") and arr.ndim > 0:
                for i in range(arr.shape[0]):
                    np.save(os.path.join(out, f"{fname}.layer{i}.npy"), arr[i])
                index[name] = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                               "layers": int(arr.shape[0])}
            else:
                np.save(os.path.join(out, fname + ".npy"), arr)
                index[name] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
        return index

    index = export_tree(params, "params")
    optim = ckpt.load_optim()
    optim_index = export_tree(optim, "optim") if optim is not None else None
    with open(os.path.join(output_dir, "meta.json"), "w") as fh:
        json.dump({"source": ckpt.meta, "tag": ckpt.tag, "format": "universal/1",
                   "params": index, "optim": optim_index}, fh, indent=1)
    return output_dir


def load_universal_params(universal_dir: str, target: Any) -> Any:
    """Rebuild a param pytree (matching ``target``'s structure/shapes) from a
    universal dir; loading at a different mesh/ZeRO stage is the caller's
    ``device_put`` (reference: --universal-checkpoint load path)."""
    return _load_universal_tree(universal_dir, target, "params")


def load_universal_optim(universal_dir: str, target: Any) -> Any:
    """Rebuild the optimizer-state tree exported by :func:`ds_to_universal`
    (raises KeyError if the universal dir is params-only)."""
    return _load_universal_tree(universal_dir, target, "optim")


def _load_universal_tree(universal_dir: str, target: Any, section: str) -> Any:
    from deepspeed_tpu.utils.tensor_fragment import _path_str

    with open(os.path.join(universal_dir, "meta.json")) as fh:
        meta = json.load(fh)
    if meta.get(section) is None:
        raise KeyError(f"universal checkpoint has no {section!r} section")
    pdir = os.path.join(universal_dir, section)

    def load_leaf(pth, leaf):
        name = _path_str(pth)
        info = meta[section].get(name)
        if info is None:
            raise KeyError(f"universal checkpoint {section} section missing {name!r}")
        if "layers" in info:
            arr = np.stack([np.load(os.path.join(pdir, name.replace('/', '.') +
                                                 f".layer{i}.npy"))
                            for i in range(info["layers"])])
        else:
            arr = np.load(os.path.join(pdir, name.replace("/", ".") + ".npy"))
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(f"{name}: universal shape {arr.shape} != target "
                             f"{tuple(leaf.shape)}")
        return arr.astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(load_leaf, target)
