"""Universal checkpointing (reference: ``deepspeed/checkpoint/``)."""

from deepspeed_tpu.checkpoint.universal import (DeepSpeedCheckpoint,
                                                ds_to_universal,
                                                load_universal_optim,
                                                load_universal_params)

__all__ = ["DeepSpeedCheckpoint", "ds_to_universal", "load_universal_params",
           "load_universal_optim"]
