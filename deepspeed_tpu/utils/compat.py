"""jax version compatibility shims.

The repo targets the modern jax surface (``jax.shard_map``,
``jax.sharding.get_abstract_mesh``); older runners provide the same
functionality under experimental/private names.  ``install_jax_compat()``
patches the missing public attributes onto the ``jax`` module once, so
every call site (and the tests' ``from jax import shard_map``) can use the
one modern spelling.  Idempotent; a no-op on jax versions that already
ship the public API.
"""

from __future__ import annotations

import functools

import jax


def _shard_map_from_experimental():
    """Adapter over ``jax.experimental.shard_map.shard_map`` (jax <= 0.4.x)
    accepting the modern ``jax.shard_map`` calling conventions used here:

    - ``check_vma=`` (renamed from the old ``check_rep=``);
    - ``axis_names={...}`` (manual over a subset of mesh axes), which the
      experimental version spells as the complementary ``auto=`` set;
    - partial application without ``f`` (``jax.shard_map(mesh=..., ...)``
      returns a decorator), which the experimental version rejects.
    """
    from jax.experimental.shard_map import shard_map as _sm

    @functools.wraps(_sm)
    def shard_map(f=None, *args, check_vma=None, check_rep=None,
                  axis_names=None, **kwargs):
        if f is None:
            return functools.partial(shard_map, *args, check_vma=check_vma,
                                     check_rep=check_rep,
                                     axis_names=axis_names, **kwargs)
        if check_rep is None:
            check_rep = check_vma
        if check_rep is not None:
            kwargs["check_rep"] = check_rep
        if axis_names is not None:
            mesh = kwargs.get("mesh", args[0] if args else None)
            kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
        return _sm(f, *args, **kwargs)

    return shard_map


def _install_optimization_barrier_ad() -> None:
    """Backport differentiation rules for ``lax.optimization_barrier``.

    jax 0.4.37 ships the primitive without JVP/transpose rules (added
    upstream later), so a barrier inside a differentiated function raises
    ``NotImplementedError``.  The overlap scheduler
    (runtime/zero/overlap.py) uses barriers to pin the compute/collective
    interleaving inside the train-step program — in both directions: the
    rules below barrier the tangents/cotangents exactly like upstream, so
    the backward schedule mirrors the forward sequencing."""
    try:
        from jax._src.interpreters import ad
        from jax._src.lax import lax as lax_internal

        prim = lax_internal.optimization_barrier_p
    except (ImportError, AttributeError):
        # private internals moved (other jax version): leave the primitive
        # as-is — a jax that reorganized these modules ships its own AD
        # rules, and even if not, only the overlap schedule needs them
        return
    if prim in ad.primitive_jvps:      # newer jax: rules already present
        return

    def _jvp(primals, tangents):
        tangents = [ad.instantiate_zeros(t) for t in tangents]
        return (jax.lax.optimization_barrier(tuple(primals)),
                jax.lax.optimization_barrier(tuple(tangents)))

    def _transpose(cts, *primals):
        cts = [ad.instantiate_zeros(ct) for ct in cts]
        return jax.lax.optimization_barrier(tuple(cts))

    ad.primitive_jvps[prim] = _jvp
    ad.primitive_transposes[prim] = _transpose


def install_jax_compat() -> None:
    """Install public-API fallbacks on the ``jax`` module (idempotent)."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_from_experimental()
    _install_optimization_barrier_ad()
    if not hasattr(jax.lax, "axis_size"):
        # the classic idiom: psum of a concrete 1 over a named axis
        # constant-folds to the (static) axis size
        jax.lax.axis_size = functools.partial(jax.lax.psum, 1)
    import inspect

    if "dtype" not in inspect.signature(jax.make_array_from_callback).parameters:
        # newer jax casts the callback's output via dtype=; older jax infers
        # the dtype from what the callback returns.  Reproduce the cast in
        # the callback — silently dropping dtype would hand mismatched-dtype
        # buffers to downstream compiled programs.  ALSO force the result
        # through a compiled identity copy: this jaxlib's CPU runtime
        # zero-copies aligned numpy shards, and a PERSISTENT-CACHE-
        # DESERIALIZED executable that donates such an aliased buffer
        # segfaults (reproduced: sharded-checkpoint reshard load + warm
        # /tmp/dstpu_xla_cache); the copy hands it runtime-owned buffers.
        import numpy as _np

        _mafc = jax.make_array_from_callback

        @functools.lru_cache(maxsize=None)
        def _owned_copy(sharding):
            # memoized per sharding: a checkpoint load calls this once per
            # param, and a fresh jit(lambda) each time would re-trace every
            # call (dispatch cache keys on function identity)
            return jax.jit(lambda x: x.copy(), out_shardings=sharding)

        @functools.wraps(_mafc)
        def make_array_from_callback(shape, sharding, data_callback,
                                     dtype=None):
            cb = (data_callback if dtype is None else
                  lambda idx: _np.asarray(data_callback(idx), dtype=dtype))
            return _owned_copy(sharding)(_mafc(shape, sharding, cb))

        jax.make_array_from_callback = make_array_from_callback
