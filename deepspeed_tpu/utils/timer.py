"""Wall-clock and throughput timers.

TPU-native analog of the reference's ``deepspeed/utils/timer.py`` (SURVEY.md
§5.1): named start/stop timers with optional device synchronization, and a
``ThroughputTimer`` that reports samples/sec and an estimated TFLOPS.  On TPU
"device sync" means blocking on the last dispatched computation
(``jax.block_until_ready`` has no global variant, so we synchronize via
``jax.effects_barrier`` when available, falling back to a device transfer).
"""

from __future__ import annotations

import re
import time
from typing import Dict, List, Optional

from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.utils.logging import logger


def _device_synchronize() -> None:
    try:
        import jax

        # Cheap full-queue sync: transfer a token scalar off-device.
        jax.device_get(jax.numpy.zeros(()))
    except Exception:  # pragma: no cover
        pass


class _Timer:
    def __init__(self, name: str, synchronize: bool = False,
                 annotate: bool = False):
        self.name = name
        self.synchronize = synchronize
        self.annotate = annotate  # emit a jax.profiler TraceAnnotation range
        self._annotation = None
        self._start: Optional[float] = None
        self._elapsed = 0.0
        self._records: List[float] = []
        self.started = False
        # bridge into the metrics registry: every stop() records into
        # ds_train_<name>_seconds, so training phase timings share one
        # schema (and one /metrics endpoint) with serving/inference.  A
        # one-branch no-op while the registry is disabled.
        slug = re.sub(r"[^a-z0-9_]", "_", name.lower())
        self._metric = get_registry().histogram(
            f"ds_train_{slug}_seconds",
            f"wall-clock '{name}' phase (engine timers)")

    def start(self) -> None:
        if self.started:
            raise RuntimeError(f"timer {self.name} already started")
        if self.synchronize:
            _device_synchronize()
        if self.annotate:
            # host-timeline range in the xplane trace (the NVTX-range analog;
            # no-op cost when no trace is being captured)
            try:
                import jax

                self._annotation = jax.profiler.TraceAnnotation(f"ds_{self.name}")
                self._annotation.__enter__()
            except Exception:  # pragma: no cover
                self._annotation = None
        self._start = time.time()
        self.started = True

    def stop(self, record: bool = True) -> None:
        if not self.started:
            raise RuntimeError(f"timer {self.name} not started")
        if self.synchronize:
            _device_synchronize()
        if self._annotation is not None:
            self._annotation.__exit__(None, None, None)
            self._annotation = None
        elapsed = time.time() - self._start
        self._elapsed += elapsed
        if record:
            self._records.append(elapsed)
        self._metric.record(elapsed)
        self.started = False

    def reset(self) -> None:
        self._elapsed = 0.0
        self._records = []
        self.started = False

    def elapsed(self, reset: bool = True) -> float:
        value = self._elapsed
        if reset:
            self.reset()
        return value

    def mean(self) -> float:
        return sum(self._records) / len(self._records) if self._records else 0.0


class SynchronizedWallClockTimer:
    """Registry of named timers; mirrors the reference API shape."""

    FORWARD = "forward"
    BACKWARD = "backward"
    STEP = "step"
    BATCH = "batch"

    def __init__(self, synchronize: bool = False, annotate: bool = False):
        self.timers: Dict[str, _Timer] = {}
        self.synchronize = synchronize
        self.annotate = annotate

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name, synchronize=self.synchronize,
                                       annotate=self.annotate)
        return self.timers[name]

    def has(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: Optional[List[str]] = None, reset: bool = True, memory_breakdown: bool = False) -> str:
        names = names if names is not None else list(self.timers)
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0
                parts.append(f"{name}: {ms:.2f}ms")
        line = " | ".join(parts)
        if line:
            logger.info("time (ms) | %s", line)
        return line

    def means(self) -> Dict[str, float]:
        return {name: t.mean() for name, t in self.timers.items()}


class ThroughputTimer:
    """Tracks samples/sec and estimated TFLOPS across steps.

    ``flops_per_sample`` may be supplied (e.g. from the model's XLA cost
    analysis — see deepspeed_tpu/profiling) to get a TFLOPS estimate.
    """

    def __init__(self, batch_size: int, start_step: int = 2, monitor_memory: bool = False,
                 logging_fn=None, flops_per_sample: Optional[float] = None):
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.logging_fn = logging_fn or logger.info
        self.flops_per_sample = flops_per_sample
        self.epoch_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self._start_time: Optional[float] = None
        self.started = False

    def start(self) -> None:
        self.started = True
        self._start_time = time.time()

    def stop(self, global_step: bool = True, report_speed: bool = False) -> None:
        if not self.started:
            return
        self.started = False
        duration = time.time() - self._start_time
        if global_step:
            self.global_step_count += 1
            if self.global_step_count > self.start_step:
                self.total_elapsed_time += duration
                self.step_elapsed_time += duration
        if report_speed and self.global_step_count % 10 == 0:
            self.logging_fn(
                f"step={self.global_step_count} samples/sec={self.avg_samples_per_sec():.2f}"
            )

    def avg_samples_per_sec(self) -> float:
        steps = self.global_step_count - self.start_step
        if steps <= 0 or self.total_elapsed_time == 0.0:
            return 0.0
        return steps * self.batch_size / self.total_elapsed_time

    def avg_tflops(self) -> Optional[float]:
        if self.flops_per_sample is None:
            return None
        return self.avg_samples_per_sec() * self.flops_per_sample / 1e12
