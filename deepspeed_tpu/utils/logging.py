"""Rank-aware logging for deepspeed_tpu.

TPU-native analog of the reference's ``deepspeed/utils/logging.py`` (see
SURVEY.md §2.1 "Utils: logging/timers"): a module-level ``logger`` plus
``log_dist(message, ranks)`` that only emits on the requested process
indices.  On TPU the "rank" is the JAX process index (one process per host,
SPMD inside), not a per-device rank.
"""

from __future__ import annotations

import functools
import logging
import os
import sys
from typing import Iterable, Optional

LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


class LoggerFactory:
    @staticmethod
    def create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
        lg = logging.getLogger(name)
        lg.setLevel(level)
        lg.propagate = False
        if not lg.handlers:
            handler = logging.StreamHandler(stream=sys.stdout)
            handler.setFormatter(logging.Formatter(LOG_FORMAT))
            lg.addHandler(handler)
        return lg


logger = LoggerFactory.create_logger(
    level=getattr(logging, os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper(), logging.INFO)
)


@functools.lru_cache(maxsize=1)
def _process_index() -> int:
    try:
        import jax  # dslint: disable=DSL003 -- guarded optional: on a jax-less operator box the except arm returns rank 0 and log_dist still works; only multi-process engines need the real index

        return jax.process_index()
    except Exception:  # pragma: no cover - jax always importable in this env
        return 0


def should_log_on(ranks: Optional[Iterable[int]]) -> bool:
    """True when the current process should emit for the given rank filter."""
    if ranks is None:
        return True
    ranks = list(ranks)
    if -1 in ranks:
        return True
    return _process_index() in ranks


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the listed process indices (default: all)."""
    if should_log_on(ranks):
        logger.log(level, "[rank %d] %s", _process_index(), message)


def warning_once(message: str, _seen=set()) -> None:  # noqa: B006 - intentional cache
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)


def get_log_level() -> int:
    return logger.getEffectiveLevel()


def set_log_level(level: int) -> None:
    logger.setLevel(level)
