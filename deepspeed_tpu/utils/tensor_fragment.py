"""Cross-stage access to full params / optimizer states / gradients.

Reference: ``deepspeed/utils/tensor_fragment.py`` (SURVEY.md §2.1) — the
``safe_get_full_*`` / ``safe_set_full_*`` API that reads and writes logically
full tensors regardless of how ZeRO partitioned them.  In the TPU framework
"partitioned" means "sharded jax array", so *gather* is ``jax.device_get``
(XLA assembles the shards) and *set* is ``jax.device_put`` back to the leaf's
existing sharding — no fragment-offset bookkeeping exists to reproduce.

Params are addressed by pytree path strings like ``"layers/attn/wq"``
(the reference addresses torch parameter objects; a functional pytree has no
stable object identity, so paths are the handle).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def list_param_paths(tree: Any) -> List[str]:
    return [_path_str(p) for p, _ in jax.tree_util.tree_leaves_with_path(tree)]


def _find(tree: Any, name: str):
    matches = [(pth, leaf) for pth, leaf in jax.tree_util.tree_leaves_with_path(tree)
               if _path_str(pth) == name or _path_str(pth).endswith("/" + name)]
    if not matches:
        raise KeyError(f"no leaf matching {name!r}; known: {list_param_paths(tree)[:10]}...")
    if len(matches) > 1:
        raise KeyError(f"ambiguous name {name!r}: {[_path_str(p) for p, _ in matches]}")
    return matches[0]


def _replace_leaf(tree: Any, name: str, value) -> Any:
    def swap(pth, leaf):
        if _path_str(pth) == name or _path_str(pth).endswith("/" + name):
            v = jnp.asarray(value, dtype=leaf.dtype)
            if v.shape != leaf.shape:
                raise ValueError(f"shape mismatch for {name}: {v.shape} vs {leaf.shape}")
            if hasattr(leaf, "sharding"):
                return jax.device_put(v, leaf.sharding)
            return v
        return leaf

    return jax.tree_util.tree_map_with_path(swap, tree)


# -- params ----------------------------------------------------------------

def safe_get_full_fp32_param(engine, name: str) -> np.ndarray:
    """Gather the full fp32 master value of a (possibly sharded) param."""
    _, leaf = _find(engine.state.params, name)
    if getattr(engine, "_onebit_stacked", False):
        leaf = leaf[0]  # model-shaped view: worker-0's replica
    return np.asarray(jax.device_get(leaf), dtype=np.float32)


def safe_set_full_fp32_param(engine, name: str, value) -> None:
    if getattr(engine, "_onebit_stacked", False):
        # setting a param sets every worker replica
        _, leaf = _find(engine.state.params, name)
        value = np.broadcast_to(np.asarray(value)[None], leaf.shape)
    engine.state = engine.state._replace(
        params=_replace_leaf(engine.state.params, name, value))


# -- optimizer state -------------------------------------------------------

# Reference state_key → candidate namedtuple fields across our optimizer
# states: optax ScaleByAdamState uses mu/nu, the Pallas FusedAdamState m/v.
_STATE_ALIASES = {"exp_avg": ("mu", "m"), "exp_avg_sq": ("nu", "v")}


def _candidate_fields(state_key: str):
    return _STATE_ALIASES.get(state_key, (state_key,))


def safe_get_full_optimizer_state(engine, name: str, state_key: str) -> np.ndarray:
    """state_key ∈ {"exp_avg", "exp_avg_sq"} (reference naming) or any concrete
    field name ("mu"/"nu" for optax Adam, "m"/"v" for the fused kernel)."""
    fields = _candidate_fields(state_key)
    for st in jax.tree_util.tree_leaves(
            engine.state.opt_state, is_leaf=lambda x: hasattr(x, "_fields")):
        for field in fields:
            if hasattr(st, "_fields") and field in st._fields:
                sub = getattr(st, field)
                _, leaf = _find(sub, name)
                if getattr(engine, "_onebit_stacked", False):
                    # stacked fields (exp_avg, error buffers) carry a [W]
                    # replica axis; replicated ones (exp_avg_sq, anchor)
                    # don't — compare against the stacked param shape
                    _, p = _find(engine.state.params, name)
                    if leaf.shape == p.shape:
                        leaf = leaf[0]
                return np.asarray(jax.device_get(leaf), dtype=np.float32)
    raise KeyError(f"optimizer state has no field {state_key!r}")


def safe_set_full_optimizer_state(engine, name: str, state_key: str, value) -> None:
    fields = _candidate_fields(state_key)
    hit = []
    value = np.asarray(value)

    def swap_state(st):
        if hasattr(st, "_fields"):
            for field in fields:
                if field in st._fields:
                    hit.append(field)
                    sub = getattr(st, field)
                    v = value
                    if getattr(engine, "_onebit_stacked", False):
                        # model-shaped value -> broadcast to every worker
                        # replica when the stored leaf is [W]-stacked (the
                        # getter returns the model-shaped view)
                        _, leaf = _find(sub, name)
                        if leaf.shape != v.shape and leaf.shape[1:] == v.shape:
                            v = np.broadcast_to(v[None], leaf.shape)
                    return st._replace(**{field: _replace_leaf(sub, name, v)})
        return st

    is_leaf = lambda x: hasattr(x, "_fields") and any(
        f in getattr(x, "_fields", ()) for f in fields)
    new_opt = jax.tree_util.tree_map(swap_state, engine.state.opt_state,
                                     is_leaf=is_leaf)
    if not hit:
        raise KeyError(f"optimizer state has no field {state_key!r}")
    engine.state = engine.state._replace(opt_state=new_opt)


# -- gradients -------------------------------------------------------------

def safe_get_full_grad(engine, name: str) -> Optional[np.ndarray]:
    """The accumulated gradient for a param (None before any forward).
    1-bit engines accumulate per-worker local grads on a [W] axis; the
    "full" gradient is their mean (the dense-equivalent value)."""
    if engine.state is None:
        return None
    _, leaf = _find(engine.state.grad_acc, name)
    out = np.asarray(jax.device_get(leaf), dtype=np.float32)
    if getattr(engine, "_onebit", False) and out.ndim:
        out = out.mean(axis=0)
    return out
