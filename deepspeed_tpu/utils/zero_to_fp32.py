"""Consolidate a checkpoint into a single fp32 state dict.

Reference: ``deepspeed/utils/zero_to_fp32.py`` (SURVEY.md §2.1, §5.4) — the
offline script shipped into checkpoint dirs that merges ``zero_pp_rank_*``
optimizer-state shards into one fp32 ``state_dict``.  The TPU checkpoint
layout stores logically-full arrays (sharding is a runtime placement, not a
file layout), so consolidation = load + cast + flatten; the entry points and
CLI semantics match the reference so downstream tooling ports unchanged.
"""

from __future__ import annotations

import argparse
import os
from typing import Any, Dict, Optional

import jax
import numpy as np


def _load_checkpoint_params(checkpoint_dir: str, tag: Optional[str] = None) -> Any:
    from deepspeed_tpu.runtime.checkpoint_engine import (MsgpackCheckpointEngine,
                                                         ShardedCheckpointEngine,
                                                         is_sharded_checkpoint)
    from deepspeed_tpu.runtime.checkpoint_engine.sharded import nest_keystrs

    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as fh:
                tag = fh.read().strip()
        else:
            raise FileNotFoundError(f"no 'latest' file in {checkpoint_dir}; pass tag=")
    sharded = os.path.join(checkpoint_dir, str(tag), "model_states")
    if is_sharded_checkpoint(sharded):
        return nest_keystrs(ShardedCheckpointEngine().load(sharded))
    path = os.path.join(checkpoint_dir, str(tag), "model_states.msgpack")
    return MsgpackCheckpointEngine().load(path)


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str,
                                             tag: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Flat {"layers/attn/wq": fp32 ndarray, ...} state dict (reference
    function name; the reference returns torch tensors keyed by module path)."""
    from deepspeed_tpu.utils.tensor_fragment import _path_str

    params = _load_checkpoint_params(checkpoint_dir, tag)
    flat = {}
    for pth, leaf in jax.tree_util.tree_leaves_with_path(params):
        arr = np.asarray(leaf)
        flat[_path_str(pth)] = arr.astype(np.float32) if np.issubdtype(
            arr.dtype, np.floating) else arr
    return flat


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str, output_file: str,
                                               tag: Optional[str] = None) -> str:
    """Write the consolidated fp32 state dict as an .npz (reference writes a
    torch .bin; npz is the dependency-free equivalent here)."""
    flat = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    np.savez(output_file if output_file.endswith(".npz") else output_file + ".npz",
             **flat)
    out = output_file if output_file.endswith(".npz") else output_file + ".npz"
    print(f"saved consolidated fp32 state dict ({len(flat)} tensors) to {out}")
    return out


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("-t", "--tag", default=None)
    args = p.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir, args.output_file,
                                               args.tag)


if __name__ == "__main__":
    main()
