from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.tensor_fragment import (list_param_paths,
                                                 safe_get_full_fp32_param,
                                                 safe_get_full_grad,
                                                 safe_get_full_optimizer_state,
                                                 safe_set_full_fp32_param,
                                                 safe_set_full_optimizer_state)
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer

__all__ = ["log_dist", "logger", "SynchronizedWallClockTimer", "ThroughputTimer",
           "safe_get_full_fp32_param", "safe_set_full_fp32_param",
           "safe_get_full_optimizer_state", "safe_set_full_optimizer_state",
           "safe_get_full_grad", "list_param_paths"]
