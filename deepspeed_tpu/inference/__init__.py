"""Inference engine (reference: ``deepspeed/inference/``)."""

from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.inference.engine import InferenceEngine

__all__ = ["DeepSpeedInferenceConfig", "InferenceEngine"]
