"""Inference engine (reference: ``deepspeed/inference/engine.py``, SURVEY.md §3.5).

``init_inference(model, config)`` → engine with ``generate``.  The reference's
machinery maps onto TPU as:

- kernel injection (``replace_with_kernel_inject``) → the fused decode path
  is the only path (models/decoding.py); the flag is accepted for parity.
- AutoTP sharding → the model's logical tp specs applied over a ``tp`` mesh
  (the same column/row classification auto_tp.py derives by name analysis).
- KV-cache workspace (``max_out_tokens``, inference_context.h arena) →
  preallocated [L, B, Hkv, Smax, Dh] cache pytree, donated through the jitted
  decode step so XLA updates it in place.
- per-token fused decode loop → one compiled prefill program per
  power-of-two prompt bucket + ONE compiled ``lax.while_loop`` program for
  the whole generation (on-device sampling + EOS reduction; the host is
  involved only at prefill and the final fetch).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm.mesh import build_mesh, get_global_mesh, set_global_mesh
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.models.decoding import (forward_with_cache, init_kv_cache,
                                           sample_token)
from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.runtime.zero.partition import params_pspecs, shardings_from_pspecs
from deepspeed_tpu.utils.logging import log_dist


def pow2_bucket(n: int, lo: int = 1, cap: Optional[int] = None) -> int:
    """Next power-of-two >= n, floored at ``lo`` and capped at ``cap`` —
    the single bucketing rule behind prompt-length / batch / serving-chunk
    buckets (compiled programs are keyed to bucket sizes, not exact
    sizes)."""
    b = lo
    while b < n:
        b *= 2
    return b if cap is None else min(b, cap)


class InferenceEngine:
    def __init__(self, model, config: DeepSpeedInferenceConfig, params: Any = None,
                 mesh=None):
        self.module = model                      # reference attr name
        self._config = config
        tp = config.tensor_parallel.tp_size if config.tensor_parallel else 1
        if mesh is None:
            mesh = get_global_mesh(create_default=False)
        if mesh is None or (tp > 1 and mesh.shape.get("tp", 1) != tp):
            mesh = build_mesh(tp=tp)
            set_global_mesh(mesh)
        self.mesh = mesh
        # int8 = quantized WEIGHTS; activations/KV math stays bf16
        self._int8_weights = config.dtype in ("int8", "qint8")
        if self._int8_weights:
            self.dtype = jnp.bfloat16
        else:
            self.dtype = jnp.bfloat16 if config.dtype in ("bfloat16", "bf16") else (
                jnp.float16 if config.dtype in ("float16", "fp16", "half") else jnp.float32)
        self._params = None
        self._dparams = None
        self._cache = None
        self._gen_fns = {}
        self._prefill_fns = {}
        self._rng = jax.random.PRNGKey(config.seed)
        self._forward_fn = None
        # generate() is NOT reentrant (see generate); the flag is
        # test-and-set under a lock so the cross-thread race raises
        # instead of slipping two callers past the check
        import threading
        self._generating = False
        self._gen_lock = threading.Lock()
        # inference metrics (one-branch no-ops while the registry is
        # disabled): generate() latency + volume, cache-bucket rebinds
        # (reallocation drops compiled fns), and program compiles — the
        # counters that attribute a latency regression to recompilation
        reg = get_registry()
        self._m_gen_s = reg.histogram(
            "ds_infer_generate_seconds", "one generate() call, wall time")
        self._m_gen = reg.counter(
            "ds_infer_generate_total", "generate() calls")
        self._m_gen_toks = reg.counter(
            "ds_infer_generated_tokens_total", "tokens returned by generate()")
        self._m_rebinds = reg.counter(
            "ds_infer_cache_rebinds_total",
            "KV-cache reallocations (bucket growth; drops compiled fns)")
        self._m_compiles = reg.counter(
            "ds_infer_compiles_total",
            "programs built (prefill buckets + decode loops)")
        if params is not None:
            self.set_params(params)
        elif getattr(config, "checkpoint", None):
            self.load_checkpoint(config.checkpoint)

    # ------------------------------------------------------------------
    def set_params(self, params: Any) -> None:
        """Shard params over the mesh per the model's logical tp specs
        (AutoTP equivalent) and cast to the serving dtype."""
        logical = (self.module.logical_pspecs()
                   if hasattr(self.module, "logical_pspecs") else None)
        specs = params_pspecs(params, self.mesh, shard=False, logical_specs=logical)
        shardings = shardings_from_pspecs(specs, self.mesh)
        cast = jax.tree.map(
            lambda a: a.astype(self.dtype)
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else jnp.asarray(a),
            params)
        if self._int8_weights:
            from jax.sharding import PartitionSpec as P

            from deepspeed_tpu.models.quant import (QTensor,
                                                    is_qtensor,
                                                    quantize_layer_params)

            cast = jax.jit(lambda p: quantize_layer_params(
                p, getattr(self.module, "config", None)))(cast)

            # Carry the AutoTP logical specs THROUGH quantization: the q
            # payload keeps the dense leaf's spec; the per-out-channel
            # scale keeps only the last-dim entry (its contraction dim is
            # size 1).  Dropping the specs here would silently replicate
            # the whole model on every TP device.
            def qspec(leaf, spec):
                if not is_qtensor(leaf):
                    return spec
                entries = list(spec) + [None] * (leaf.ndim - len(spec))
                scale_spec = (P(*entries[:-2], None, entries[-1])
                              if leaf.ndim >= 2 else P())
                return QTensor(P(*entries), scale_spec)

            specs = jax.tree.map(qspec, cast, specs, is_leaf=is_qtensor)
            shardings = shardings_from_pspecs(specs, self.mesh)
        self._params = jax.device_put(cast, shardings)
        self._build_injected_view()
        self._gen_fns = {}
        self._prefill_fns = {}
        n = sum(x.size for x in jax.tree.leaves(self._params))
        nbytes = sum(x.nbytes for x in jax.tree.leaves(self._params))
        log_dist(f"inference engine ready: {n/1e6:.2f}M params "
                 f"({nbytes/2**30:.2f}GB), tp={self.mesh.shape.get('tp', 1)}, "
                 f"dtype {'int8-weights/' if self._int8_weights else ''}"
                 f"{self.dtype.__name__}"
                 f"{', kernel-injected decode' if self._dparams is not None else ''}",
                 ranks=[0])

    def _build_injected_view(self) -> None:
        """Kernel injection (reference ``replace_with_kernel_inject``): lay
        the weights out for the fused Pallas decode kernels.  Auto-on when
        supported; ``use_fused_decode=False`` opts out."""
        from deepspeed_tpu.models.fused_decode import (inject_decode_params,
                                                       supports_fused_decode)

        self._dparams = None
        cfg = getattr(self.module, "config", None)
        if self._config.use_fused_decode is False:
            return  # explicit opt-out wins, even over replace_with_kernel_inject
        if cfg is None:
            return
        force = self._config.replace_with_kernel_inject
        ok = supports_fused_decode(
            cfg, quantized_kv=self._config.quantize_kv_cache,
            tp=self.mesh.shape.get("tp", 1))
        if not ok:
            if force or self._config.use_fused_decode:
                log_dist("kernel injection requested but unsupported for "
                         "this model/config (MoE, int8 KV cache, or tp>1; "
                         "int8 WEIGHTS alone are supported): using the "
                         "unfused decode path", ranks=[0])
            return
        # eager, not jitted: pass-through leaves (embed/final_norm/lm_head —
        # the largest single tensors) stay ALIASED to self._params instead
        # of being copied by a jit boundary.  The per-layer unstacked
        # weights are genuinely new buffers (that is the injection), so
        # layer weights are resident twice — prefill keeps the plain tree.
        self._dparams = inject_decode_params(self._params, cfg)

    def load_checkpoint(self, path: str) -> None:
        from deepspeed_tpu.runtime.checkpoint_engine import (
            MsgpackCheckpointEngine, ShardedCheckpointEngine, is_sharded_checkpoint)
        from deepspeed_tpu.runtime.checkpoint_engine.sharded import nest_keystrs
        import os

        from deepspeed_tpu.module_inject.containers import (hf_to_params,
                                                            is_hf_checkpoint,
                                                            load_hf_state_dict)

        f = path
        if is_hf_checkpoint(path):
            # published HuggingFace checkpoint (safetensors/.bin + config.json)
            self.set_params(hf_to_params(load_hf_state_dict(path),
                                         self.module.config))
            return
        if os.path.isdir(path):
            latest = os.path.join(path, "latest")
            if os.path.exists(latest):
                f = os.path.join(path, open(latest).read().strip(), "model_states")
            else:
                f = os.path.join(path, "model_states")
            if is_sharded_checkpoint(f):
                self.set_params(nest_keystrs(ShardedCheckpointEngine().load(f)))
                return
            f += ".msgpack"
        self.set_params(MsgpackCheckpointEngine().load(f))

    # ------------------------------------------------------------------
    @staticmethod
    def _bucket(n: int, cap: int) -> int:
        """Next power-of-two >= n (min 16), capped — prefill compiles once
        per bucket instead of once per distinct prompt length."""
        return pow2_bucket(n, lo=16, cap=cap)

    def _bucket_batch(self, batch: int) -> int:
        """Next power-of-two >= batch (capped at max_batch_size when set):
        the cache/compiled fns are keyed to the bucketed batch, so a batch-3
        call after a batch-8 call reuses the batch-8 allocation and programs
        (padded rows masked out) instead of reallocating + recompiling."""
        b = pow2_bucket(batch, lo=1, cap=self._config.max_batch_size or None)
        return max(b, batch)

    def _ensure_compiled(self, batch: int, max_len: int):
        """Returns the RUN batch (the allocated cache's batch dim, >= the
        request batch — callers pad rows up to it).

        Both cache dims are bucketed so mixed-size traffic reuses one
        allocation (and the compiled fns keyed to its shapes) instead of
        reallocating + recompiling per exact size: batch rounds up to a
        power of two, length to a power-of-two bucket capped at the
        ``max_out_tokens`` budget; neither ever shrinks."""
        cfg = self.module.config
        need_b = self._bucket_batch(batch)
        need_len = self._bucket(max_len, self._config.max_out_tokens + 1)
        cur = self._cache
        if cur is None or cur["k"].shape[1] < need_b or \
                cur["k"].shape[3] < need_len:
            if cur is not None:
                need_b = max(need_b, cur["k"].shape[1])
                need_len = max(need_len, cur["k"].shape[3])
                self._m_rebinds.inc()   # growth realloc: compiled fns drop
            self._cache = init_kv_cache(
                cfg, need_b, need_len, dtype=self.dtype,
                quantized=self._config.quantize_kv_cache)
            self._prefill_fns = {}
            self._gen_fns = {}
        return self._cache["k"].shape[1]

    def _prefill(self, params, cache, tokens, pos, last_idx):
        """Returns (last-position logits [B, V], cache).  ``last_idx`` (the
        true prompt length - 1, a traced scalar) is sliced INSIDE the
        program — returning the full [B, Sb, V] logits for a 50k vocab would
        materialize GBs just to keep one row."""
        s = tokens.shape[1]
        if s not in self._prefill_fns:
            self._m_compiles.inc()
            model = self.module

            @functools.partial(jax.jit, donate_argnums=(1,))
            def prefill(params, cache, tokens, pos, last_idx):
                logits, cache = forward_with_cache(model, params, tokens, cache, pos)
                last = jax.lax.dynamic_index_in_dim(logits, last_idx, axis=1,
                                                    keepdims=False)
                return last, cache

            self._prefill_fns[s] = prefill
        return self._prefill_fns[s](params, cache, tokens, pos,
                                    jnp.asarray(last_idx, jnp.int32))

    def _gen_loop(self, settings):
        """One compiled program for the WHOLE decode loop: lax.while_loop
        with on-device sampling and EOS reduction — no per-token host sync
        or dispatch (VERDICT r2 weak #3 / item 8).

        The body generates ``decode_unroll`` tokens per loop iteration
        (per-iteration loop overhead amortizes across them).  Sub-steps past
        the (max-token, cache-bound, all-EOS) exit condition write to SPARE
        slots — one extra buf column and the cache rows past ``max_len`` —
        and don't advance ``pos``/``step``, so the unrolled tail is exact
        without a ``lax.cond`` (profiled: a cond around the sub-step forces
        a full KV-cache copy per branch).  With kernel injection active the
        sub-step is the fused Pallas decode (models/fused_decode.py);
        otherwise the reference-shaped unfused forward."""
        if settings in self._gen_fns:
            return self._gen_fns[settings]
        self._m_compiles.inc()
        eos, do_sample, temperature, top_k, top_p = settings
        model = self.module
        fused = self._dparams is not None
        unroll = max(1, int(self._config.decode_unroll))

        def step_fn(params, tokens, cache, pos):
            if fused:
                from deepspeed_tpu.models.fused_decode import decode_step

                return decode_step(model.config, params, tokens, cache, pos)
            logits, cache = forward_with_cache(model, params, tokens, cache,
                                               pos)
            return logits[:, -1], cache

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def loop(params, cache, buf, logits0, pos0, max_steps, max_pos,
                 nrows, rng):
            # rows >= nrows are batch-bucket padding: they start finished,
            # so the all-EOS early exit is governed by the real rows only.
            # max_pos (= the request's cache budget) is TRACED so mixed
            # request sizes share one compiled program.
            B, W = buf.shape
            cache_len = cache["k"].shape[-2]

            def cond(st):
                buf, cache, logits, pos, step, rng, finished = st
                go = (step < max_steps) & (pos < max_pos)
                if eos >= 0:
                    go = go & ~jnp.all(finished)
                return go

            def substep(st, guarded):
                buf, cache, logits, pos, step, rng, finished = st
                valid = cond(st) if guarded else None
                rng, srng = jax.random.split(rng)
                nxt = sample_token(logits, srng, temperature=temperature,
                                   top_k=top_k, top_p=top_p, do_sample=do_sample)
                if eos >= 0:
                    nxt = jnp.where(finished, eos, nxt)
                    hit = nxt == eos
                    finished = finished | (hit if valid is None
                                           else hit & valid)
                buf_pos = pos if valid is None else jnp.where(valid, pos, W - 1)
                buf = jax.lax.dynamic_update_slice(
                    buf, nxt[:, None].astype(buf.dtype), (0, buf_pos))
                fwd_pos = (pos if valid is None
                           else jnp.where(valid, pos, cache_len - 1))
                new_logits, cache = step_fn(
                    params, nxt[:, None].astype(jnp.int32), cache, fwd_pos)
                if valid is not None:
                    new_logits = jnp.where(valid, new_logits, logits)
                    adv = valid.astype(pos.dtype)
                else:
                    adv = 1
                return (buf, cache, new_logits, pos + adv, step + adv, rng,
                        finished)

            def body(st):
                # the first sub-step is covered by the while cond; later
                # ones guard themselves via masked writes
                st = substep(st, guarded=False)
                for _ in range(unroll - 1):
                    st = substep(st, guarded=True)
                return st

            st = (buf, cache, logits0, pos0, jnp.zeros((), jnp.int32), rng,
                  jnp.arange(B) >= nrows)
            buf, cache, _, pos, step, rng, _ = jax.lax.while_loop(cond, body, st)
            return buf, cache, pos, step, rng

        self._gen_fns[settings] = loop
        return loop

    # ------------------------------------------------------------------
    def generate(self, input_ids, max_new_tokens: int = 128, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None, rng=None):
        """Autoregressive generation; returns [B, S+n] ids where n <=
        max_new_tokens (rows that hit EOS early hold EOS padding).

        The decode loop is a single jitted ``lax.while_loop`` — sampling and
        the EOS all-finished reduction run on device; the host is involved
        only at prefill and at the final fetch.  Prompts are right-padded to
        power-of-two buckets so prefill compiles per bucket, not per length;
        the batch is likewise padded up to the allocated cache's (power-of-
        two-bucketed) batch so shrinking batches reuse programs.

        NOT reentrant: the KV cache is donated through the jitted programs
        and ``self._cache`` is nulled for the duration of the call, so a
        second concurrent ``generate()`` (another thread, or a callback
        re-entering mid-flight) would race on freed buffers.  Re-entry
        raises ``RuntimeError`` immediately instead of crashing confusingly
        inside XLA.  For concurrent request serving use
        ``deepspeed_tpu.serving.ServingEngine``.
        """
        if self._params is None:
            raise RuntimeError("no weights: pass params=, config.checkpoint, or set_params()")
        with self._gen_lock:
            if self._generating:
                raise RuntimeError(
                    "InferenceEngine.generate() is not reentrant: the KV "
                    "cache is donated to the running decode program. "
                    "Serialize calls, or use deepspeed_tpu.serving."
                    "ServingEngine for concurrent requests.")
            self._generating = True
        try:
            t0 = time.perf_counter()
            tokens = jnp.asarray(input_ids)
            if tokens.ndim == 1:
                tokens = tokens[None]
            B, S = tokens.shape
            max_len = min(self._config.max_out_tokens, S + max_new_tokens)
            if self._config.max_batch_size and B > self._config.max_batch_size:
                raise ValueError(
                    f"batch {B} exceeds max_batch_size "
                    f"{self._config.max_batch_size}")
            if S + max(1, self._config.min_out_tokens) > \
                    self._config.max_out_tokens:
                raise ValueError(
                    f"cache budget max_out_tokens="
                    f"{self._config.max_out_tokens} cannot cover "
                    f"min_out_tokens={self._config.min_out_tokens} after a "
                    f"{S}-token prompt")
            out = self._generate(tokens, B, S, max_len, max_new_tokens,
                                 do_sample, temperature, top_k, top_p,
                                 eos_token_id, rng)
            self._m_gen_s.record(time.perf_counter() - t0)
            self._m_gen.inc()
            self._m_gen_toks.inc(B * (out.shape[1] - S))
            return out
        finally:
            with self._gen_lock:
                self._generating = False

    def _generate(self, tokens, B, S, max_len, max_new_tokens, do_sample,
                  temperature, top_k, top_p, eos_token_id, rng):
        # +1: a spare cache row past max_len absorbs masked-off unrolled
        # sub-step writes (never attended — valid rows stop at max_len)
        run_b = self._ensure_compiled(B, max_len + 1)
        if run_b > B:  # pad rows up to the bucketed cache batch
            tokens = jnp.pad(tokens, ((0, run_b - B), (0, 0)))
        cache = self._cache
        self._cache = None  # donated below; invalidate the handle

        # prefill on the padded bucket; garbage cache slots in [S, Sb) are
        # masked by position until overwritten by decode
        Sb = self._bucket(S, cache["k"].shape[3])
        padded = jnp.pad(tokens, ((0, 0), (0, Sb - S))) if Sb > S else tokens
        logits, cache = self._prefill(self._params, cache, padded, 0, S - 1)

        # The token buffer is FULLY bucketed (prompt bucket Sb + pow2
        # output bucket + 1 spare column) so mixed (S, max_new) requests
        # share one compiled loop; generation writes at absolute column
        # ``pos`` (starting at the exact S), overwriting the prompt-bucket
        # padding first, and the loop still stops at the exact traced
        # max_steps.  Masked-off unrolled sub-steps land in the spare last
        # column; the returned slice stops at S + tokens-produced, so
        # neither padding nor spare is ever seen.
        nb = self._bucket(max_new_tokens, self._config.max_out_tokens)
        buf = jnp.concatenate(
            [padded.astype(tokens.dtype),
             jnp.zeros((run_b, nb + 1), tokens.dtype)], axis=1)
        rng = rng if rng is not None else self._rng
        settings = (eos_token_id if eos_token_id is not None else -1,
                    bool(do_sample), float(temperature), int(top_k),
                    float(top_p))
        loop = self._gen_loop(settings)
        loop_params = self._dparams if self._dparams is not None else self._params
        buf, cache, pos, step, rng = loop(
            loop_params, cache, buf, logits, jnp.asarray(S, jnp.int32),
            jnp.asarray(max_new_tokens, jnp.int32),
            jnp.asarray(max_len, jnp.int32),
            jnp.asarray(B, jnp.int32), rng)
        self._rng = rng
        self._cache = cache
        n_done = int(step)  # single host sync for the whole generation
        return buf[:B, : S + n_done]


    def __call__(self, tokens):
        """Plain forward (logits) — reference ``engine(inputs)`` parity.
        int8 weights are dequantized inside the jit (transient per-leaf;
        the training-forward path expects dense arrays)."""
        if self._forward_fn is None:
            if self._int8_weights:
                from deepspeed_tpu.models.quant import dequantize_tree

                self._forward_fn = jax.jit(
                    lambda p, t: self.module.apply(
                        dequantize_tree(p, self.dtype), t))
            else:
                self._forward_fn = jax.jit(self.module.apply)
        return self._forward_fn(self._params, jnp.asarray(tokens))

    @property
    def config(self):
        return self._config
