"""Inference engine (reference: ``deepspeed/inference/engine.py``, SURVEY.md §3.5).

``init_inference(model, config)`` → engine with ``generate``.  The reference's
machinery maps onto TPU as:

- kernel injection (``replace_with_kernel_inject``) → the fused decode path
  is the only path (models/decoding.py); the flag is accepted for parity.
- AutoTP sharding → the model's logical tp specs applied over a ``tp`` mesh
  (the same column/row classification auto_tp.py derives by name analysis).
- KV-cache workspace (``max_out_tokens``, inference_context.h arena) →
  preallocated [L, B, Hkv, Smax, Dh] cache pytree, donated through the jitted
  decode step so XLA updates it in place.
- per-token fused decode loop → one compiled prefill program + one compiled
  decode program reused for every token (static shapes, no retracing).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm.mesh import build_mesh, get_global_mesh, set_global_mesh
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.models.decoding import (forward_with_cache, init_kv_cache,
                                           sample_token)
from deepspeed_tpu.runtime.zero.partition import params_pspecs, shardings_from_pspecs
from deepspeed_tpu.utils.logging import log_dist


class InferenceEngine:
    def __init__(self, model, config: DeepSpeedInferenceConfig, params: Any = None,
                 mesh=None):
        self.module = model                      # reference attr name
        self._config = config
        tp = config.tensor_parallel.tp_size if config.tensor_parallel else 1
        if mesh is None:
            mesh = get_global_mesh(create_default=False)
        if mesh is None or (tp > 1 and mesh.shape.get("tp", 1) != tp):
            mesh = build_mesh(tp=tp)
            set_global_mesh(mesh)
        self.mesh = mesh
        self.dtype = jnp.bfloat16 if config.dtype in ("bfloat16", "bf16") else (
            jnp.float16 if config.dtype in ("float16", "fp16", "half") else jnp.float32)
        self._params = None
        self._cache = None
        self._decode_fn = None
        self._prefill_fns = {}
        self._rng = jax.random.PRNGKey(config.seed)
        self._forward_fn = None
        if params is not None:
            self.set_params(params)
        elif getattr(config, "checkpoint", None):
            self.load_checkpoint(config.checkpoint)

    # ------------------------------------------------------------------
    def set_params(self, params: Any) -> None:
        """Shard params over the mesh per the model's logical tp specs
        (AutoTP equivalent) and cast to the serving dtype."""
        logical = (self.module.logical_pspecs()
                   if hasattr(self.module, "logical_pspecs") else None)
        specs = params_pspecs(params, self.mesh, shard=False, logical_specs=logical)
        shardings = shardings_from_pspecs(specs, self.mesh)
        cast = jax.tree.map(
            lambda a: a.astype(self.dtype)
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else jnp.asarray(a),
            params)
        self._params = jax.device_put(cast, shardings)
        n = sum(x.size for x in jax.tree.leaves(self._params))
        log_dist(f"inference engine ready: {n/1e6:.2f}M params, tp="
                 f"{self.mesh.shape.get('tp', 1)}, dtype {self.dtype.__name__}", ranks=[0])

    def load_checkpoint(self, path: str) -> None:
        from deepspeed_tpu.runtime.checkpoint_engine import MsgpackCheckpointEngine
        import os

        engine = MsgpackCheckpointEngine()
        f = path
        if os.path.isdir(path):
            latest = os.path.join(path, "latest")
            if os.path.exists(latest):
                with open(latest) as fh:
                    f = os.path.join(path, fh.read().strip(), "model_states.msgpack")
            else:
                f = os.path.join(path, "model_states.msgpack")
        self.set_params(engine.load(f))

    # ------------------------------------------------------------------
    def _ensure_compiled(self, batch: int, max_len: int):
        cfg = self.module.config
        if self._cache is None or self._cache["k"].shape[1] != batch or \
                self._cache["k"].shape[3] < max_len:
            self._cache = init_kv_cache(cfg, batch, max_len, dtype=self.dtype)
        if self._decode_fn is None:
            model = self.module

            @functools.partial(jax.jit, donate_argnums=(1,))
            def decode(params, cache, tokens, pos):
                logits, cache = forward_with_cache(model, params, tokens, cache, pos)
                return logits[:, -1], cache

            self._decode_fn = decode

    def _prefill(self, params, cache, tokens, pos):
        # one compiled program per prompt length (left-padded buckets would
        # collapse this further; lengths are usually few in serving)
        s = tokens.shape[1]
        if s not in self._prefill_fns:
            model = self.module

            @functools.partial(jax.jit, donate_argnums=(1,))
            def prefill(params, cache, tokens, pos):
                logits, cache = forward_with_cache(model, params, tokens, cache, pos)
                return logits[:, -1], cache

            self._prefill_fns[s] = prefill
        return self._prefill_fns[s](params, cache, tokens, pos)

    # ------------------------------------------------------------------
    def generate(self, input_ids, max_new_tokens: int = 128, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None, rng=None):
        """Autoregressive generation; returns [B, S+max_new_tokens] ids
        (right side may hold EOS padding once every row finished)."""
        if self._params is None:
            raise RuntimeError("no weights: pass params=, config.checkpoint, or set_params()")
        tokens = jnp.asarray(input_ids)
        if tokens.ndim == 1:
            tokens = tokens[None]
        B, S = tokens.shape
        max_len = min(self._config.max_out_tokens, S + max_new_tokens)
        if self._config.max_batch_size and B > self._config.max_batch_size:
            raise ValueError(
                f"batch {B} exceeds max_batch_size {self._config.max_batch_size}")
        if S + max(1, self._config.min_out_tokens) > self._config.max_out_tokens:
            raise ValueError(
                f"cache budget max_out_tokens={self._config.max_out_tokens} cannot "
                f"cover min_out_tokens={self._config.min_out_tokens} after a "
                f"{S}-token prompt")
        self._ensure_compiled(B, max_len)
        cache = self._cache
        self._cache = None  # donated below; invalidate the handle

        logits, cache = self._prefill(self._params, cache, tokens, 0)
        out = [tokens]
        finished = jnp.zeros((B,), bool)
        rng = rng if rng is not None else self._rng
        pos = S
        last = None
        for _ in range(max_new_tokens):
            rng, step_rng = jax.random.split(rng)
            nxt = sample_token(logits, step_rng, temperature=temperature,
                               top_k=top_k, top_p=top_p, do_sample=do_sample)
            if eos_token_id is not None:
                nxt = jnp.where(finished, eos_token_id, nxt)
                finished = finished | (nxt == eos_token_id)
            out.append(nxt[:, None])
            if pos >= max_len - 0 or (eos_token_id is not None and bool(finished.all())):
                break
            if pos >= cache["k"].shape[3]:
                break
            logits, cache = self._decode_fn(self._params, cache, nxt[:, None], pos)
            pos += 1
        self._rng = rng
        self._cache = cache
        return jnp.concatenate(out, axis=1)

    def __call__(self, tokens):
        """Plain forward (logits) — reference ``engine(inputs)`` parity."""
        if self._forward_fn is None:
            self._forward_fn = jax.jit(self.module.apply)
        return self._forward_fn(self._params, jnp.asarray(tokens))

    @property
    def config(self):
        return self._config
