"""Inference config (reference: ``deepspeed/inference/config.py``).

Key schema parity (SURVEY.md §2.1 "Inference engine", §3.5):
``dtype``, ``tensor_parallel.tp_size`` (also the legacy ``mp_size`` alias),
``max_out_tokens``, ``replace_with_kernel_inject``, ``checkpoint``,
``min_out_tokens``, ``max_tokens``.  ``replace_with_kernel_inject`` (and the
auto-on ``use_fused_decode`` extension) selects the Pallas kernel-injected
decode path (models/fused_decode.py): fused QKV weights + four fused kernels
per layer, the TPU form of the reference's injection containers.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class InferenceTPConfig(DeepSpeedConfigModel):
    tp_size: int = 1
    enabled: bool = True


class InferenceCheckpointConfig(DeepSpeedConfigModel):
    checkpoint_dir: Optional[str] = None
    save_mp_checkpoint_path: Optional[str] = None


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    # "int8" serves int8 weights (per-output-channel scales, dequant fused
    # into the matmuls; activations stay bf16) — reference
    # ``init_inference(dtype=torch.int8)`` parity.
    dtype: str = "bfloat16"
    # TPU extension: int8 KV cache (per-position/head scales) — halves the
    # cache footprint and its decode read bandwidth.
    quantize_kv_cache: bool = False
    tensor_parallel: Optional[InferenceTPConfig] = None
    max_out_tokens: int = 1024
    min_out_tokens: int = 1              # enforced: generate() raises if the
                                         # cache budget cannot cover it
    max_batch_size: int = 0              # 0 = unlimited; else generate() raises
    replace_with_kernel_inject: bool = False
    # TPU extensions for the fused decode path (models/fused_decode.py):
    # use_fused_decode None = auto (on when the model/config supports it);
    # decode_unroll = tokens generated per while_loop iteration (amortizes
    # per-iteration loop overhead; EOS/max-token tails are masked exactly).
    use_fused_decode: Optional[bool] = None
    decode_unroll: int = 4
    checkpoint: Optional[Any] = None
    enable_cuda_graph: bool = False      # accepted for parity; XLA always "graphs"
    seed: int = 0
    # Continuous-batching serving knobs (serving/engine.py — the
    # MII / DeepSpeed-FastGen dynamic-batching role):
    # num_slots = KV-cache slot pool size (max concurrently-decoding
    # requests; the compiled batch); prefill_chunk = max prompt tokens
    # prefilled per scheduler iteration per slot (bounds the decode stall
    # a long prompt causes); decode_block_tokens = decode steps per
    # compiled block per host sync (0 = follow decode_unroll);
    # max_prefill_chunks = prefill chunks advanced per iteration across
    # slots (decode-latency vs admission-latency trade).
    num_slots: int = 8
    prefill_chunk: int = 64
    decode_block_tokens: int = 0
    max_prefill_chunks: int = 2
    # Paged KV cache (serving/paged_kv.py — the vLLM/PagedAttention-style
    # block allocator): slots draw fixed-size token pages from ONE shared
    # pool instead of reserving max_out_tokens each, so HBM tracks the
    # tokens actually live and the slot count is no longer bounded by the
    # worst-case request.  kv_page_tokens = page granularity (0 = auto:
    # the flash-decode block, capped at the per-slot budget);
    # kv_pool_tokens = total pool capacity in tokens (0 = num_slots *
    # per-slot budget — same HBM as the fixed layout; set it LOWER to
    # oversubscribe slots against a fixed HBM budget, backed by LIFO
    # preempt-and-requeue when the pool runs dry).
    paged_kv_cache: bool = True
    kv_page_tokens: int = 0
    kv_pool_tokens: int = 0
    # Copy-on-write prefix caching (serving/prefix_cache.py — the
    # vLLM/SGLang radix-cache idiom): finished requests' full prompt
    # pages stay in a page-granular trie; a new request whose prompt
    # shares a cached prefix adopts those pages read-only (refcounted)
    # and prefill starts at the match frontier, with one device-side
    # page copy when the boundary page is only partially matched
    # (copy-on-write).  Greedy outputs are token-identical with the
    # cache on or off.  Paged engines only (ignored on the fixed-slot
    # layout).
    prefix_caching: bool = True
    # KV host tier (serving/host_tier.py — the ZeRO-Infinity move applied
    # to serving): > 0 bounds an LRU host-RAM store of that many pages;
    # prefix-cache eviction victims DEMOTE into it (device->host copy)
    # instead of dropping their KV, and a later admission that matches a
    # demoted chunk PROMOTES it back (host->device, byte-identical — greedy
    # outputs cannot change), so the effective prefix cache is host-RAM
    # sized and a preempt-resume re-adopts instead of re-prefilling.
    # 0 (default) = off: eviction drops, the PR 9 semantics.  Paged +
    # prefix_caching only.
    kv_host_tier_pages: int = 0
    # Overload protection (serving/scheduler.py, docs/RESILIENCE.md
    # "Serving fleet"): max_queue_depth bounds the admission queue — a
    # submit past the watermark sheds (QueueFull -> HTTP 429 with
    # Retry-After = shed_retry_after_s) instead of growing latency
    # without bound (0 = unbounded, the legacy behavior).
    # request_deadline_s is the DEFAULT per-request service deadline
    # applied at submit when the caller gives none (0 = none): a request
    # still queued past its deadline is cancelled with finish reason
    # "deadline" rather than burning a slot on an answer nobody is
    # waiting for.
    max_queue_depth: int = 0
    shed_retry_after_s: float = 1.0
    request_deadline_s: float = 0.0
    # Goodput ledger + SLO burn rules (monitor/goodput.py,
    # docs/OBSERVABILITY.md "Goodput ledger").  ``goodput`` mirrors the
    # training GoodputConfig as a plain dict ({enabled, path,
    # min_tick_interval_s}); ``slo`` maps rule name -> threshold
    # (goodput_ratio MIN, ttft_p99_s / shed_ratio MAX).  Setting either
    # enables the ledger for the serving engine; DSTPU_RUNLEDGER enables
    # it regardless (the supervisor channel).
    goodput: Optional[Dict[str, Any]] = None
    slo: Optional[Dict[str, float]] = None

    def __init__(self, **kwargs):
        # legacy alias: mp_size -> tensor_parallel.tp_size
        mp = kwargs.pop("mp_size", None)
        tp = kwargs.pop("tensor_parallel", None)
        if isinstance(tp, dict):
            tp = InferenceTPConfig(**tp)
        if tp is None:
            tp = InferenceTPConfig(tp_size=mp or 1)
        super().__init__(tensor_parallel=tp, **kwargs)
