"""Module injection / AutoTP (reference: ``deepspeed/module_inject/``).

The reference walks a torch module tree, classifies Linears as column- or
row-parallel by name analysis (``auto_tp.py``), and swaps fused kernels in
(``replace_module.py``; SURVEY.md §2.1, §3.5).  In the TPU framework that
classification is the model's ``logical_pspecs()`` (Megatron column/row specs
over the ``tp`` mesh axis) and "kernel injection" is the default compiled
path — so these entry points shard params instead of rewriting modules.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from deepspeed_tpu.comm.mesh import build_mesh, get_global_mesh, set_global_mesh
from deepspeed_tpu.runtime.zero.partition import params_pspecs, shardings_from_pspecs


def tp_model_init(model, tp_size: int = 1, dtype=None, params: Any = None, mesh=None):
    """Training-time tensor parallelism (reference ``tp_model_init``,
    used by HF for ``tensor_parallel.autotp_size``): returns (model, sharded
    params) with the model's logical tp layout applied over a tp mesh."""
    if mesh is None:
        mesh = get_global_mesh(create_default=False)
        if mesh is None or mesh.shape.get("tp", 1) != tp_size:
            mesh = build_mesh(tp=tp_size)
            set_global_mesh(mesh)
    if params is None:
        return model, None
    if hasattr(model, "logical_pspecs"):
        logical = model.logical_pspecs()
    else:
        # arbitrary param tree: classify column/row by name analysis
        # (reference auto_tp.py role)
        from deepspeed_tpu.module_inject.auto_tp import autotp_pspecs

        logical = autotp_pspecs(params)
    specs = params_pspecs(params, mesh, shard=False, logical_specs=logical)
    sharded = jax.device_put(params, shardings_from_pspecs(specs, mesh))
    if dtype is not None:
        import jax.numpy as jnp

        sharded = jax.tree.map(
            lambda a: a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a,
            sharded)
    return model, sharded


def replace_module(model=None, **kwargs):
    """Reference parity shim: kernel swapping is the compiled default on TPU;
    returns the model unchanged.  Warns when kernel-injection kwargs are
    passed so silently ignored intent is visible."""
    ignored = {k: v for k, v in kwargs.items()
               if k in ("replace_with_kernel_inject", "injection_policy",
                        "checkpoint") and v}
    if ignored:
        from deepspeed_tpu.utils.logging import logger

        logger.warning("replace_module: %s ignored (fused kernels are the "
                       "default compiled path on TPU)", sorted(ignored))
    return model


from deepspeed_tpu.module_inject.containers import (  # noqa: E402,F401
    causal_lm_from_hf, config_from_hf, hf_to_params, is_hf_checkpoint,
    load_hf_state_dict)
