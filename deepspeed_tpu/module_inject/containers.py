"""HF checkpoint import: published GPT-2 / Llama / Mixtral / OPT / Qwen2 /
GPT-NeoX(Pythia) / BLOOM / GPT-J weights -> the built-in models' param trees.

Reference: ``deepspeed/module_inject/containers/`` (SURVEY.md §2.1 row 34) —
the containers' real job is mapping public HuggingFace state dicts into the
runtime's layout.  Here that means: read safetensors / torch .bin shards,
rename + transpose into the CausalLM tree (stacked [L, ...] layer weights,
input-major linear layout), and derive the ModelConfig from config.json.

Conventions handled:
- HF ``nn.Linear`` stores [out, in] -> transposed to our [in, out].
- GPT-2 ``Conv1D`` stores [in, out] -> copied as-is; fused c_attn split into
  wq/wk/wv; biases mapped (our models carry biases when ``use_bias``).
- Llama/Mixtral rotary uses the half-split pairing — identical to our RoPE
  kernel, so q/k import without permutation.
- GPT-J rotary is INTERLEAVED; its q/k output columns are permuted at import
  so the half-split kernel computes identical rotations (the q.k dot is
  invariant to a permutation applied to both sides).  Its single shared
  ln_1 is copied into both norm slots of the parallel-residual block.
- BLOOM: fused per-head-interleaved QKV (like NeoX), ALiBi positions, and
  the word_embeddings_layernorm (``embed_norm``).
- Mixtral experts w1/w3/w2 -> w_gate/w_up/w_down stacked on a leading [E].
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger


def load_hf_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Read a HF checkpoint dir (safetensors preferred, torch .bin fallback)
    into {name: np.ndarray}."""
    sd: Dict[str, np.ndarray] = {}
    st_files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if st_files:
        from safetensors.numpy import load_file

        for f in st_files:
            sd.update(load_file(os.path.join(path, f)))
        return sd
    bin_files = sorted(f for f in os.listdir(path)
                       if f.endswith(".bin") and "pytorch_model" in f)
    if bin_files:
        import torch

        for f in bin_files:
            part = torch.load(os.path.join(path, f), map_location="cpu",
                              weights_only=True)
            sd.update({k: v.float().numpy() if v.dtype == torch.bfloat16
                       else v.numpy() for k, v in part.items()})
        return sd
    raise FileNotFoundError(f"no safetensors/.bin weights in {path}")


def _strip_prefix(sd: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    for prefix in ("transformer.", "model.", "gpt_neox."):
        if any(k.startswith(prefix) for k in sd):
            out = {}
            for k, v in sd.items():
                out[k[len(prefix):] if k.startswith(prefix) else k] = v
            return out
    return sd


def detect_arch(sd: Dict[str, np.ndarray]) -> str:
    keys = set(sd)
    if any("block_sparse_moe" in k for k in keys):
        return "mixtral"
    if any("word_embeddings_layernorm" in k for k in keys):
        return "bloom"
    if any("wte.weight" in k for k in keys):
        # gpt-j has separate q/k/v projections; gpt2 a fused Conv1D c_attn
        if any(".attn.q_proj." in k for k in keys):
            return "gptj"
        return "gpt2"
    if any("decoder.embed_positions" in k for k in keys):
        return "opt"
    if any("embed_in.weight" in k for k in keys):
        return "gpt_neox"
    if any("embed_tokens.weight" in k for k in keys):
        # qwen2 is llama-shaped with q/k/v biases
        if any(k.endswith("q_proj.bias") for k in keys):
            return "qwen2"
        return "llama"
    raise ValueError(f"unrecognized HF architecture (keys: {sorted(keys)[:8]}...)")


def config_from_hf(path: str):
    """ModelConfig from a HF config.json."""
    from deepspeed_tpu.models.config import ModelConfig

    with open(os.path.join(path, "config.json")) as fh:
        hf = json.load(fh)
    mt = hf.get("model_type", "")
    if mt == "gpt2":
        return ModelConfig(
            vocab_size=hf["vocab_size"], hidden_size=hf["n_embd"],
            intermediate_size=4 * hf["n_embd"], num_layers=hf["n_layer"],
            num_heads=hf["n_head"], max_seq_len=hf.get("n_positions", 1024),
            norm="layernorm", norm_eps=hf.get("layer_norm_epsilon", 1e-5),
            activation="gelu", glu=False, position="learned",
            tie_embeddings=True, use_bias=True)
    if mt in ("llama", "mistral", "qwen2"):
        return ModelConfig(
            vocab_size=hf["vocab_size"], hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf.get("num_key_value_heads"),
            max_seq_len=hf.get("max_position_embeddings", 4096),
            norm="rmsnorm", norm_eps=hf.get("rms_norm_eps", 1e-5),
            activation="silu", glu=True, position="rope",
            rope_theta=hf.get("rope_theta", 10000.0),
            qkv_bias=(mt == "qwen2"),
            tie_embeddings=hf.get("tie_word_embeddings", False))
    if mt == "gpt_neox":
        if not hf.get("attention_bias", True):
            raise ValueError(
                "gpt_neox with attention_bias=false is not supported: the "
                "model's use_bias covers attention AND mlp biases together "
                "(NeoX keeps mlp biases regardless)")
        # HF "gelu" is the exact erf form; the tanh approximations map to
        # this model zoo's default "gelu"
        act_map = {"gelu": "gelu_exact", "gelu_new": "gelu",
                   "gelu_fast": "gelu", "gelu_pytorch_tanh": "gelu"}
        act = hf.get("hidden_act", "gelu")
        if act not in act_map:
            raise ValueError(f"gpt_neox hidden_act {act!r} is not supported "
                             f"(supported: {sorted(act_map)})")
        return ModelConfig(
            vocab_size=hf["vocab_size"], hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            max_seq_len=hf.get("max_position_embeddings", 2048),
            norm="layernorm", norm_eps=hf.get("layer_norm_eps", 1e-5),
            activation=act_map[act], glu=False, position="rope",
            # transformers deprecated rotary_emb_base for rope_theta
            rope_theta=hf.get("rotary_emb_base",
                              hf.get("rope_theta", 10000.0)),
            rotary_pct=hf.get("rotary_pct", 1.0),
            parallel_residual=hf.get("use_parallel_residual", True),
            use_bias=True,
            tie_embeddings=hf.get("tie_word_embeddings", False))
    if mt == "bloom":
        D = hf["hidden_size" if "hidden_size" in hf else "n_embed"]
        return ModelConfig(
            vocab_size=hf["vocab_size"], hidden_size=D,
            intermediate_size=4 * D,
            num_layers=hf["n_layer"], num_heads=hf["n_head"],
            max_seq_len=hf.get("seq_length", 2048),
            norm="layernorm", norm_eps=hf.get("layer_norm_epsilon", 1e-5),
            # HF BloomGelu is the tanh approximation
            activation="gelu", glu=False, position="alibi",
            use_bias=True, embed_norm=True,
            tie_embeddings=hf.get("tie_word_embeddings", True))
    if mt == "gptj":
        D = hf["n_embd"]
        Dh = D // hf["n_head"]
        return ModelConfig(
            vocab_size=hf["vocab_size"], hidden_size=D,
            intermediate_size=hf.get("n_inner") or 4 * D,
            num_layers=hf["n_layer"], num_heads=hf["n_head"],
            max_seq_len=hf.get("n_positions", 2048),
            norm="layernorm", norm_eps=hf.get("layer_norm_epsilon", 1e-5),
            activation="gelu", glu=False, position="rope",
            rotary_pct=(hf.get("rotary_dim") or Dh) / Dh,
            # gpt-j runs attention and MLP in parallel off ONE layernorm;
            # the import copies ln_1 into both norm slots (identical math)
            parallel_residual=True,
            use_bias=False, mlp_bias=True, lm_head_bias=True,
            tie_embeddings=hf.get("tie_word_embeddings", False))
    if mt == "opt":
        D = hf["hidden_size"]
        if hf.get("word_embed_proj_dim", D) != D:
            raise ValueError("OPT word_embed_proj_dim != hidden_size "
                             "(project_in/out) is not supported")
        if not hf.get("do_layer_norm_before", True):
            raise ValueError("OPT with do_layer_norm_before=false (350m "
                             "post-LN variant) is not supported")
        return ModelConfig(
            vocab_size=hf["vocab_size"], hidden_size=D,
            intermediate_size=hf["ffn_dim"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            max_seq_len=hf.get("max_position_embeddings", 2048),
            norm="layernorm", activation="relu", glu=False,
            position="learned", use_bias=True,
            tie_embeddings=hf.get("tie_word_embeddings", True))
    if mt == "mixtral":
        return ModelConfig(
            vocab_size=hf["vocab_size"], hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf.get("num_key_value_heads"),
            max_seq_len=hf.get("max_position_embeddings", 4096),
            norm="rmsnorm", norm_eps=hf.get("rms_norm_eps", 1e-5),
            activation="silu", glu=True, position="rope",
            rope_theta=hf.get("rope_theta", 1e6),
            num_experts=hf["num_local_experts"],
            num_experts_per_tok=hf.get("num_experts_per_tok", 2),
            tie_embeddings=hf.get("tie_word_embeddings", False))
    raise ValueError(f"unsupported HF model_type {mt!r}")


def _stack(sd, fmt: str, L: int, transform=None) -> np.ndarray:
    parts = [sd[fmt.format(i)] for i in range(L)]
    if transform is not None:
        parts = [transform(p) for p in parts]
    return np.stack(parts)


def hf_to_params(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """Map a HF state dict onto the CausalLM param tree."""
    sd = _strip_prefix(sd)
    arch = detect_arch(sd)
    L, D = cfg.num_layers, cfg.hidden_size
    T = lambda w: np.ascontiguousarray(w.T)

    if arch == "gpt2":
        qkv = [sd[f"h.{i}.attn.c_attn.weight"] for i in range(L)]      # [D, 3D]
        qkv_b = [sd[f"h.{i}.attn.c_attn.bias"] for i in range(L)]      # [3D]
        attn = {
            "wq": np.stack([w[:, :D] for w in qkv]),
            "wk": np.stack([w[:, D:2 * D] for w in qkv]),
            "wv": np.stack([w[:, 2 * D:] for w in qkv]),
            "wo": _stack(sd, "h.{}.attn.c_proj.weight", L),
            "bq": np.stack([b[:D] for b in qkv_b]),
            "bk": np.stack([b[D:2 * D] for b in qkv_b]),
            "bv": np.stack([b[2 * D:] for b in qkv_b]),
            "bo": _stack(sd, "h.{}.attn.c_proj.bias", L),
        }
        mlp = {
            "w_up": _stack(sd, "h.{}.mlp.c_fc.weight", L),
            "b_up": _stack(sd, "h.{}.mlp.c_fc.bias", L),
            "w_down": _stack(sd, "h.{}.mlp.c_proj.weight", L),
            "b_down": _stack(sd, "h.{}.mlp.c_proj.bias", L),
        }
        params = {
            "embed": {"tok": sd["wte.weight"], "pos": sd["wpe.weight"]},
            "layers": {
                "attn_norm": {"scale": _stack(sd, "h.{}.ln_1.weight", L),
                              "bias": _stack(sd, "h.{}.ln_1.bias", L)},
                "mlp_norm": {"scale": _stack(sd, "h.{}.ln_2.weight", L),
                             "bias": _stack(sd, "h.{}.ln_2.bias", L)},
                "attn": attn, "mlp": mlp,
            },
            "final_norm": {"scale": sd["ln_f.weight"], "bias": sd["ln_f.bias"]},
        }
        return params

    if arch == "bloom":
        H, Dh = cfg.num_heads, cfg.head_dim

        def qkv_w(which):
            # fused [3D, D], per-head [q,k,v] interleave (same as neox)
            def split(i):
                w = sd[f"h.{i}.self_attention.query_key_value.weight"]
                part = w.reshape(H, 3, Dh, -1)[:, which]        # [H, Dh, D]
                return np.ascontiguousarray(part.reshape(H * Dh, -1).T)
            return np.stack([split(i) for i in range(L)])

        def qkv_b(which):
            def split(i):
                b = sd[f"h.{i}.self_attention.query_key_value.bias"]
                return b.reshape(H, 3, Dh)[:, which].reshape(H * Dh)
            return np.stack([split(i) for i in range(L)])

        attn = {
            "wq": qkv_w(0), "wk": qkv_w(1), "wv": qkv_w(2),
            "wo": _stack(sd, "h.{}.self_attention.dense.weight", L, T),
            "bq": qkv_b(0), "bk": qkv_b(1), "bv": qkv_b(2),
            "bo": _stack(sd, "h.{}.self_attention.dense.bias", L),
        }
        mlp = {
            "w_up": _stack(sd, "h.{}.mlp.dense_h_to_4h.weight", L, T),
            "b_up": _stack(sd, "h.{}.mlp.dense_h_to_4h.bias", L),
            "w_down": _stack(sd, "h.{}.mlp.dense_4h_to_h.weight", L, T),
            "b_down": _stack(sd, "h.{}.mlp.dense_4h_to_h.bias", L),
        }
        return {
            "embed": {"tok": sd["word_embeddings.weight"],
                      "norm": {"scale": sd["word_embeddings_layernorm.weight"],
                               "bias": sd["word_embeddings_layernorm.bias"]}},
            "layers": {
                "attn_norm": {
                    "scale": _stack(sd, "h.{}.input_layernorm.weight", L),
                    "bias": _stack(sd, "h.{}.input_layernorm.bias", L)},
                "mlp_norm": {
                    "scale": _stack(sd, "h.{}.post_attention_layernorm.weight", L),
                    "bias": _stack(sd, "h.{}.post_attention_layernorm.bias", L)},
                "attn": attn, "mlp": mlp,
            },
            "final_norm": {"scale": sd["ln_f.weight"],
                           "bias": sd["ln_f.bias"]},
        }

    if arch == "gptj":
        H, Dh = cfg.num_heads, cfg.head_dim
        from deepspeed_tpu.models.layers import rope_dim as _rd
        rd = _rd(cfg)
        # HF GPT-J rotates interleaved pairs (2i, 2i+1); our kernel rotates
        # half-split pairs (i, i+rd/2).  Permuting the q/k OUTPUT columns
        # within each head maps one convention onto the other exactly (the
        # q.k dot is invariant to a permutation applied to both sides).
        perm = np.arange(Dh)
        perm[:rd // 2] = np.arange(0, rd, 2)
        perm[rd // 2:rd] = np.arange(1, rd, 2)

        def rot_cols(w):
            # w: HF [out=H*Dh, in=D] -> ours [D, H*Dh] with permuted heads
            wt = w.T.reshape(-1, H, Dh)
            return np.ascontiguousarray(wt[:, :, perm].reshape(-1, H * Dh))

        attn = {
            "wq": _stack(sd, "h.{}.attn.q_proj.weight", L, rot_cols),
            "wk": _stack(sd, "h.{}.attn.k_proj.weight", L, rot_cols),
            "wv": _stack(sd, "h.{}.attn.v_proj.weight", L, T),
            "wo": _stack(sd, "h.{}.attn.out_proj.weight", L, T),
        }
        mlp = {
            "w_up": _stack(sd, "h.{}.mlp.fc_in.weight", L, T),
            "b_up": _stack(sd, "h.{}.mlp.fc_in.bias", L),
            "w_down": _stack(sd, "h.{}.mlp.fc_out.weight", L, T),
            "b_down": _stack(sd, "h.{}.mlp.fc_out.bias", L),
        }
        ln1_s = _stack(sd, "h.{}.ln_1.weight", L)
        ln1_b = _stack(sd, "h.{}.ln_1.bias", L)
        params = {
            "embed": {"tok": sd["wte.weight"]},
            "layers": {
                # one shared LayerNorm in the HF block: both slots get it
                "attn_norm": {"scale": ln1_s, "bias": ln1_b},
                "mlp_norm": {"scale": ln1_s.copy(), "bias": ln1_b.copy()},
                "attn": attn, "mlp": mlp,
            },
            "final_norm": {"scale": sd["ln_f.weight"],
                           "bias": sd["ln_f.bias"]},
            "lm_head": T(sd["lm_head.weight"]),
            "lm_head_bias": sd["lm_head.bias"],
        }
        return params

    if arch == "gpt_neox":
        H, Dh = cfg.num_heads, cfg.head_dim

        def qkv_w(which):
            # fused [3D, D], per-head [q,k,v] interleave -> our [D, H*Dh]
            def split(i):
                w = sd[f"layers.{i}.attention.query_key_value.weight"]
                part = w.reshape(H, 3, Dh, -1)[:, which]        # [H, Dh, D]
                return np.ascontiguousarray(part.reshape(H * Dh, -1).T)
            return np.stack([split(i) for i in range(L)])

        def qkv_b(which):
            def split(i):
                b = sd[f"layers.{i}.attention.query_key_value.bias"]
                return b.reshape(H, 3, Dh)[:, which].reshape(H * Dh)
            return np.stack([split(i) for i in range(L)])

        attn = {
            "wq": qkv_w(0), "wk": qkv_w(1), "wv": qkv_w(2),
            "wo": _stack(sd, "layers.{}.attention.dense.weight", L, T),
            "bq": qkv_b(0), "bk": qkv_b(1), "bv": qkv_b(2),
            "bo": _stack(sd, "layers.{}.attention.dense.bias", L),
        }
        mlp = {
            "w_up": _stack(sd, "layers.{}.mlp.dense_h_to_4h.weight", L, T),
            "b_up": _stack(sd, "layers.{}.mlp.dense_h_to_4h.bias", L),
            "w_down": _stack(sd, "layers.{}.mlp.dense_4h_to_h.weight", L, T),
            "b_down": _stack(sd, "layers.{}.mlp.dense_4h_to_h.bias", L),
        }
        params = {
            "embed": {"tok": sd["embed_in.weight"]},
            "layers": {
                "attn_norm": {
                    "scale": _stack(sd, "layers.{}.input_layernorm.weight", L),
                    "bias": _stack(sd, "layers.{}.input_layernorm.bias", L)},
                "mlp_norm": {
                    "scale": _stack(sd, "layers.{}.post_attention_layernorm.weight", L),
                    "bias": _stack(sd, "layers.{}.post_attention_layernorm.bias", L)},
                "attn": attn, "mlp": mlp,
            },
            "final_norm": {"scale": sd["final_layer_norm.weight"],
                           "bias": sd["final_layer_norm.bias"]},
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = T(sd["embed_out.weight"])
        return params

    if arch == "opt":
        attn = {
            "wq": _stack(sd, "decoder.layers.{}.self_attn.q_proj.weight", L, T),
            "wk": _stack(sd, "decoder.layers.{}.self_attn.k_proj.weight", L, T),
            "wv": _stack(sd, "decoder.layers.{}.self_attn.v_proj.weight", L, T),
            "wo": _stack(sd, "decoder.layers.{}.self_attn.out_proj.weight", L, T),
            "bq": _stack(sd, "decoder.layers.{}.self_attn.q_proj.bias", L),
            "bk": _stack(sd, "decoder.layers.{}.self_attn.k_proj.bias", L),
            "bv": _stack(sd, "decoder.layers.{}.self_attn.v_proj.bias", L),
            "bo": _stack(sd, "decoder.layers.{}.self_attn.out_proj.bias", L),
        }
        mlp = {
            "w_up": _stack(sd, "decoder.layers.{}.fc1.weight", L, T),
            "b_up": _stack(sd, "decoder.layers.{}.fc1.bias", L),
            "w_down": _stack(sd, "decoder.layers.{}.fc2.weight", L, T),
            "b_down": _stack(sd, "decoder.layers.{}.fc2.bias", L),
        }
        params = {
            "embed": {
                "tok": sd["decoder.embed_tokens.weight"],
                # OPT's learned positions carry a +2 fairseq padding offset;
                # with a full attention mask position ids are arange+2, so
                # rows [2:] are the effective table
                "pos": sd["decoder.embed_positions.weight"][2:],
            },
            "layers": {
                "attn_norm": {
                    "scale": _stack(sd, "decoder.layers.{}.self_attn_layer_norm.weight", L),
                    "bias": _stack(sd, "decoder.layers.{}.self_attn_layer_norm.bias", L)},
                "mlp_norm": {
                    "scale": _stack(sd, "decoder.layers.{}.final_layer_norm.weight", L),
                    "bias": _stack(sd, "decoder.layers.{}.final_layer_norm.bias", L)},
                "attn": attn, "mlp": mlp,
            },
            "final_norm": {"scale": sd["decoder.final_layer_norm.weight"],
                           "bias": sd["decoder.final_layer_norm.bias"]},
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = T(sd["lm_head.weight"])
        return params

    if arch in ("llama", "qwen2"):
        attn = {
            "wq": _stack(sd, "layers.{}.self_attn.q_proj.weight", L, T),
            "wk": _stack(sd, "layers.{}.self_attn.k_proj.weight", L, T),
            "wv": _stack(sd, "layers.{}.self_attn.v_proj.weight", L, T),
            "wo": _stack(sd, "layers.{}.self_attn.o_proj.weight", L, T),
        }
        if arch == "qwen2":
            attn.update(
                bq=_stack(sd, "layers.{}.self_attn.q_proj.bias", L),
                bk=_stack(sd, "layers.{}.self_attn.k_proj.bias", L),
                bv=_stack(sd, "layers.{}.self_attn.v_proj.bias", L))
        mlp = {
            "w_gate": _stack(sd, "layers.{}.mlp.gate_proj.weight", L, T),
            "w_up": _stack(sd, "layers.{}.mlp.up_proj.weight", L, T),
            "w_down": _stack(sd, "layers.{}.mlp.down_proj.weight", L, T),
        }
    else:  # mixtral
        E = cfg.num_experts
        attn = {
            "wq": _stack(sd, "layers.{}.self_attn.q_proj.weight", L, T),
            "wk": _stack(sd, "layers.{}.self_attn.k_proj.weight", L, T),
            "wv": _stack(sd, "layers.{}.self_attn.v_proj.weight", L, T),
            "wo": _stack(sd, "layers.{}.self_attn.o_proj.weight", L, T),
        }
        def experts(wname):
            return np.stack([
                np.stack([T(sd[f"layers.{i}.block_sparse_moe.experts.{e}.{wname}.weight"])
                          for e in range(E)]) for i in range(L)])
        mlp = {
            "gate_w": _stack(sd, "layers.{}.block_sparse_moe.gate.weight", L, T),
            "w_gate": experts("w1"),   # HF w1 = gate_proj
            "w_down": experts("w2"),   # HF w2 = down_proj
            "w_up": experts("w3"),     # HF w3 = up_proj
        }
    params = {
        "embed": {"tok": sd["embed_tokens.weight"]},
        "layers": {
            "attn_norm": {"scale": _stack(sd, "layers.{}.input_layernorm.weight", L)},
            "mlp_norm": {"scale": _stack(
                sd, "layers.{}.post_attention_layernorm.weight", L)},
            "attn": attn, "mlp": mlp,
        },
        "final_norm": {"scale": sd["norm.weight"]},
    }
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight")
        params["lm_head"] = (T(head) if head is not None
                             else T(sd["embed_tokens.weight"]))
    return params


def causal_lm_from_hf(path: str, mesh=None, dtype=None) -> Tuple[Any, Dict[str, Any]]:
    """One-call import: HF checkpoint dir -> (CausalLM, params tree)."""
    from deepspeed_tpu.models.transformer import CausalLM

    cfg = config_from_hf(path)
    sd = load_hf_state_dict(path)
    params = hf_to_params(sd, cfg)
    if dtype is not None:
        import ml_dtypes

        np_dtype = {"bfloat16": ml_dtypes.bfloat16}.get(str(dtype), dtype)
        params = {k: _tree_astype(v, np_dtype) for k, v in params.items()}
    n = sum(int(x.size) for x in _tree_leaves(params))
    logger.info("imported HF checkpoint %s: %s, %.2fM params", path,
                detect_arch(_strip_prefix(sd)), n / 1e6)
    return CausalLM(cfg, mesh=mesh), params


def is_hf_checkpoint(path: str) -> bool:
    """True only for genuine HF layouts (config.json + safetensors or
    pytorch_model*.bin) — the framework's own shard_p*.bin files must not
    match, or its checkpoints would become unloadable next to a config.json."""
    if not (os.path.isdir(path)
            and os.path.exists(os.path.join(path, "config.json"))):
        return False
    return any(f.endswith(".safetensors")
               or (f.endswith(".bin") and "pytorch_model" in f)
               for f in os.listdir(path))


def _tree_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def _tree_astype(tree, np_dtype):
    import jax

    return jax.tree_util.tree_map(
        lambda a: a.astype(np_dtype) if np.issubdtype(a.dtype, np.floating) else a,
        tree)
