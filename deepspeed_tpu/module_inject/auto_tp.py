"""Generic AutoTP: name-analysis tensor-parallel classification for
arbitrary param trees.

Reference: ``deepspeed/module_inject/auto_tp.py`` (SURVEY.md §2.1 row 34) —
the reference walks an unknown torch module, decides per Linear whether it is
column-parallel (split the output features; no comm) or row-parallel (split
the input features; all-reduce after) from the module's name, and leaves
anything it cannot classify unsharded.  Here the same decision is made over a
jax param pytree and expressed as ``PartitionSpec``s on a ``tp`` mesh axis —
the engine merges them with ZeRO's ``fsdp`` sharding exactly like the
built-in models' ``logical_pspecs()``.

Layout convention: 2D weights are input-major ``[in, out]`` (stacked layer
weights carry leading batch dims, e.g. ``[L, in, out]``), so

- column-parallel  -> split the LAST dim (out features),
- row-parallel     -> split the SECOND-TO-LAST dim (in features),
- embeddings       -> split the vocab dim (dim -2, Megatron-style),
- 1D tensors       -> split only when they are a column-split's bias
                      (their weight's out-features shard owns them),
- unrecognized     -> replicated, with a one-line log (the reference's
                      "don't split what you can't classify" rule).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.logging import logger

# Output-feature projections: QKV & up/gate MLP entries across the model
# families the reference's policies cover (HF naming) plus this framework's
# own names.  Splitting their OUT dim needs no collective in forward.
COLUMN_NAMES = frozenset({
    "wq", "wk", "wv", "q_proj", "k_proj", "v_proj", "query", "key", "value",
    "query_key_value", "c_attn", "qkv_proj", "in_proj",
    "w_up", "w_gate", "up_proj", "gate_proj", "c_fc", "fc1",
    "dense_h_to_4h", "w1", "w3", "wi", "wi_0", "wi_1", "linear_1",
})
# Input-feature projections: attention output & MLP down entries.  Splitting
# their IN dim makes each shard produce a partial sum -> all-reduce (the
# reference's LinearAllreduce).
ROW_NAMES = frozenset({
    "wo", "o_proj", "out_proj", "c_proj", "attn_out",
    "w_down", "down_proj", "fc2", "dense_4h_to_h", "w2", "dense",
    "wo_0", "linear_2",
})
# Vocab-dim-shardable embeddings / output heads ([V, D] or [D, V]).
EMBED_NAMES = frozenset({
    "tok", "wte", "embed_tokens", "word_embeddings", "embed_in", "wpe",
})
HEAD_NAMES = frozenset({"lm_head", "embed_out", "head"})
# Biases of column-split projections carry the split out-features.
COLUMN_BIAS = frozenset({
    "bq", "bk", "bv", "b_up", "b_gate",
})


def _leaf_name(path) -> str:
    """Last meaningful name component of a pytree path ('.weight'/'.bias'
    suffixes looked through, list indices skipped)."""
    names = []
    for k in path:
        if hasattr(k, "key"):
            names.append(str(k.key))
        elif hasattr(k, "name"):
            names.append(str(k.name))
    if not names:
        return ""
    last = names[-1]
    if last in ("weight", "bias", "kernel", "scale") and len(names) > 1:
        return names[-2] if last in ("weight", "kernel") else last
    return last


def classify(name: str, ndim: int, path_names: Optional[list] = None) -> str:
    """'column' | 'row' | 'embedding' | 'column_bias' | 'replicated' for one
    param.  ``name`` is the leaf's owning-module name (see ``_leaf_name``)."""
    base = name.lower()
    if ndim >= 2:
        if base in COLUMN_NAMES:
            return "column"
        if base in ROW_NAMES:
            return "row"
        if base in EMBED_NAMES:
            return "embedding"
        if base in HEAD_NAMES:
            return "column"   # [D, V] head: split vocab (out) dim
        return "replicated"
    if ndim == 1:
        if base in COLUMN_BIAS:
            return "column_bias"
        # HF-style '<proj>.bias': the module name decides
        if path_names and len(path_names) >= 2 and base == "bias":
            owner = path_names[-2].lower()
            if owner in COLUMN_NAMES:
                return "column_bias"
        return "replicated"
    return "replicated"


def autotp_pspecs(params: Any, axis: str = "tp") -> Any:
    """PartitionSpec tree for an arbitrary param pytree — the generic
    AutoTP classification (drop-in for a model's ``logical_pspecs()``).

    Unclassified >=2D leaves are replicated and reported once, mirroring the
    reference's behavior of leaving unknown Linears unsharded rather than
    guessing wrong."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = []
    unknown = []
    for path, leaf in flat:
        ndim = getattr(leaf, "ndim", len(getattr(leaf, "shape", ())))
        names = [str(k.key) for k in path if hasattr(k, "key")]
        name = _leaf_name(path)
        kind = classify(name, ndim, names)
        lead = (None,) * max(0, ndim - 2)
        if kind == "column":
            specs.append(P(*lead, None, axis))
        elif kind == "row":
            specs.append(P(*lead, axis, None))
        elif kind == "embedding":
            specs.append(P(*lead, axis, None))
        elif kind == "column_bias":
            specs.append(P(*((None,) * (ndim - 1)), axis))
        else:
            specs.append(P(*((None,) * ndim)))
            if ndim >= 2:
                unknown.append(".".join(names) or name)
    if unknown:
        logger.info("autotp: %d unclassified tensors left replicated "
                    "(e.g. %s)", len(unknown), unknown[:4])
    return jax.tree_util.tree_unflatten(treedef, specs)
