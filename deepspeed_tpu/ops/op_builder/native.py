"""Native (C++) op building: g++ → shared object → ctypes.

TPU-native analog of the reference's JIT path in ``op_builder/builder.py``
(SURVEY.md §2.1): where the reference shells out to nvcc via torch
cpp_extension, we compile host-side C++ (csrc/) with g++ once per source
change and bind via ctypes (no pybind11 in this image).  ``DS_BUILD_*``-style
forcing is honored through ``DS_TPU_REBUILD_OPS=1``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))
_BUILD_DIR = os.environ.get(
    "DS_TPU_BUILD_DIR", os.path.join(_REPO_ROOT, "build", "ops"))
_LOCK = threading.Lock()


class NativeOpBuilder:
    NAME: str = ""
    SOURCES: List[str] = []          # relative to repo root
    CXX_FLAGS = ["-O3", "-std=c++17", "-fPIC", "-shared", "-march=native",
                 "-funroll-loops"]
    LDFLAGS = ["-lpthread"]

    _cache: dict = {}

    def lib_path(self) -> str:
        return os.path.join(_BUILD_DIR, f"lib_ds_{self.NAME}.so")

    def _needs_build(self) -> bool:
        out = self.lib_path()
        if os.environ.get("DS_TPU_REBUILD_OPS"):
            return True
        if not os.path.exists(out):
            return True
        out_m = os.path.getmtime(out)
        return any(os.path.getmtime(os.path.join(_REPO_ROOT, s)) > out_m
                   for s in self.SOURCES)

    def build(self) -> str:
        with _LOCK:
            if not self._needs_build():
                return self.lib_path()
            os.makedirs(_BUILD_DIR, exist_ok=True)
            srcs = [os.path.join(_REPO_ROOT, s) for s in self.SOURCES]
            out = self.lib_path()
            cmd = ["g++", *self.CXX_FLAGS, *srcs, "-o", out + ".tmp", *self.LDFLAGS]
            logger.info("building native op %s: %s", self.NAME, " ".join(cmd))
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
            except subprocess.CalledProcessError as e:  # pragma: no cover
                raise RuntimeError(
                    f"native build of {self.NAME} failed:\n{e.stderr}") from e
            os.replace(out + ".tmp", out)
            return out

    def is_compatible(self) -> bool:
        try:
            self.load()
            return True
        except Exception as e:
            logger.warning("native op %s unavailable: %s", self.NAME, e)
            return False

    def load(self) -> ctypes.CDLL:
        key = self.NAME
        if key not in NativeOpBuilder._cache:
            NativeOpBuilder._cache[key] = ctypes.CDLL(self.build())
        return NativeOpBuilder._cache[key]


class CPUAdamBuilder(NativeOpBuilder):
    NAME = "cpu_adam"
    SOURCES = ["csrc/cpu_adam/cpu_adam.cpp"]

    def load(self) -> ctypes.CDLL:
        lib = super().load()
        i64, f, i, p = ctypes.c_int64, ctypes.c_float, ctypes.c_int, ctypes.c_void_p
        lib.ds_adam_step.argtypes = [i64, p, p, p, p, i64, f, f, f, f, f, i]
        lib.ds_adam_step.restype = None
        lib.ds_adam_step_bf16g.argtypes = [i64, p, p, p, p, p, i64, f, f, f, f, f, i]
        lib.ds_adam_step_bf16g.restype = None
        lib.ds_adagrad_step.argtypes = [i64, p, p, p, f, f, f]
        lib.ds_adagrad_step.restype = None
        lib.ds_lion_step.argtypes = [i64, p, p, p, f, f, f, f]
        lib.ds_lion_step.restype = None
        return lib


def available_ops():
    """(name, compatible, note) rows for every native builder — the data
    behind ``ds_report`` (reference: op compatibility matrix in
    env_report.py)."""
    rows = []
    for cls in (CPUAdamBuilder, AsyncIOBuilder):
        b = cls()
        built = os.path.exists(b.lib_path())
        try:
            ok = b.is_compatible()
            note = ("prebuilt" if built else "jit-built") if ok else "build failed"
        except Exception as exc:  # pragma: no cover
            ok, note = False, str(exc)
        rows.append((f"native.{cls.NAME}", ok, note))
    return rows


class AsyncIOBuilder(NativeOpBuilder):
    NAME = "aio"
    SOURCES = ["csrc/aio/ds_aio.cpp"]

    def load(self) -> ctypes.CDLL:
        lib = super().load()
        i64, i, p, cp = ctypes.c_int64, ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p
        lib.ds_aio_handle_new.argtypes = [i, i, i, i, i, i]
        lib.ds_aio_handle_new.restype = p
        lib.ds_aio_handle_free.argtypes = [p]
        lib.ds_aio_pread_async.argtypes = [p, cp, p, i64, i64]
        lib.ds_aio_pwrite_async.argtypes = [p, cp, p, i64, i64]
        lib.ds_aio_wait.argtypes = [p]
        lib.ds_aio_wait.restype = i64
        lib.ds_aio_read.argtypes = [p, cp, p, i64, i64]
        lib.ds_aio_read.restype = i64
        lib.ds_aio_write.argtypes = [p, cp, p, i64, i64]
        lib.ds_aio_write.restype = i64
        return lib
