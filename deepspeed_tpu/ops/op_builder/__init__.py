"""Op-builder registry.

TPU-native analog of the reference's ``op_builder/`` (SURVEY.md §2.1 "Op
builder system").  On GPU the builders JIT-compile CUDA; here most "ops" are
Pallas kernels that need no build step, so a builder reports availability and
returns the op module.  Native host-side ops (cpu_adam C++, async AIO) do have
a real build step via a Makefile-driven ``load()`` — see
``deepspeed_tpu/ops/op_builder/native.py``.
"""

from __future__ import annotations

import importlib
from typing import Dict, Optional, Type

_REGISTRY: Dict[str, str] = {
    # op name -> module providing it
    "fused_adam": "deepspeed_tpu.ops.adam.fused_adam",
    "cpu_adam": "deepspeed_tpu.ops.adam.cpu_adam",
    "cpu_adagrad": "deepspeed_tpu.ops.adagrad.cpu_adagrad",
    "cpu_lion": "deepspeed_tpu.ops.lion.cpu_lion",
    "fused_lamb": "deepspeed_tpu.ops.lamb.fused_lamb",
    "fused_lion": "deepspeed_tpu.ops.lion.fused_lion",
    "transformer": "deepspeed_tpu.ops.transformer.transformer",
    "transformer_inference": "deepspeed_tpu.ops.transformer.inference",
    "quantizer": "deepspeed_tpu.ops.quantizer",
    "async_io": "deepspeed_tpu.ops.aio",
    "sparse_attn": "deepspeed_tpu.ops.sparse_attention",
    "random_ltd": "deepspeed_tpu.ops.random_ltd",
}


class OpBuilder:
    """Build/availability probe for one op (reference: ``OpBuilder.load()``)."""

    def __init__(self, name: str, module: str):
        self.name = name
        self.module = module

    def is_compatible(self) -> bool:
        try:
            importlib.import_module(self.module)
            return True
        except Exception:
            return False

    def load(self):
        return importlib.import_module(self.module)

    def builder_name(self) -> str:
        return self.name


def get_op_builder(op_name: str) -> Optional[Type]:
    if op_name not in _REGISTRY:
        return None
    module = _REGISTRY[op_name]

    def factory():
        return OpBuilder(op_name, module)

    return factory


ALL_OPS = dict(_REGISTRY)
