"""Fused transformer layer wrappers.

Reference: ``deepspeed/ops/transformer/`` — ``DeepSpeedTransformerLayer`` +
``DeepSpeedTransformerConfig`` (the fused BERT-style training layer backed by
csrc/transformer kernels; SURVEY.md §2.1 "Ops: transformer kernels").
"""

from deepspeed_tpu.ops.transformer.transformer import (  # noqa: F401
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
