"""DeepSpeedTransformerLayer: the fused encoder layer, TPU-native.

Reference: ``deepspeed/ops/transformer/transformer.py`` (+
``csrc/transformer/*`` kernels, SURVEY.md §2.2 "Transformer training
kernels"): a BERT-style post-LN (or pre-LN) encoder block where the CUDA
version fuses LayerNorm, bias+GeLU, bias+dropout+residual, and strided-batch
GEMM attention.  Here the same block is built from the Pallas kernel set
(flash attention, fused LayerNorm) with XLA fusing the epilogues — the
config surface matches the reference so user code ports directly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas import flash_attention
from deepspeed_tpu.ops.pallas.layer_norm import layer_norm


@dataclass
class DeepSpeedTransformerConfig:
    """Reference config surface (unsupported CUDA-specific knobs accepted
    and ignored where XLA owns the behavior)."""

    batch_size: int = 1
    hidden_size: int = 768
    intermediate_size: int = 3072
    heads: int = 12
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = 1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    seed: int = 0
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False     # memory trick; remat covers it
    gelu_checkpoint: bool = False          # ditto
    stochastic_mode: bool = False          # CUDA fast-path; XLA is deterministic
    return_tuple: bool = False
    training: bool = True


class DeepSpeedTransformerLayer:
    """Functional fused encoder layer: ``init(rng) -> params``;
    ``apply(params, x, attention_mask=None, rng=None) -> y``."""

    def __init__(self, config: DeepSpeedTransformerConfig,
                 initial_weights: Optional[Dict[str, Any]] = None,
                 initial_biases: Optional[Dict[str, Any]] = None):
        self.config = config
        self._initial = (initial_weights, initial_biases)

    def init(self, rng) -> Dict[str, Any]:
        c = self.config
        D, F = c.hidden_size, c.intermediate_size
        k = iter(jax.random.split(rng, 8))
        s = c.initializer_range
        norm_p = lambda: {"scale": jnp.ones((D,), jnp.float32),
                          "bias": jnp.zeros((D,), jnp.float32)}
        return {
            "attn": {"wqkv": jax.random.normal(next(k), (D, 3 * D)) * s,
                     "bqkv": jnp.zeros((3 * D,)),
                     "wo": jax.random.normal(next(k), (D, D)) * s,
                     "bo": jnp.zeros((D,))},
            "attn_norm": norm_p(),
            "mlp": {"w1": jax.random.normal(next(k), (D, F)) * s,
                    "b1": jnp.zeros((F,)),
                    "w2": jax.random.normal(next(k), (F, D)) * s,
                    "b2": jnp.zeros((D,))},
            "mlp_norm": norm_p(),
        }

    def apply(self, params, x, attention_mask=None, rng=None):
        c = self.config
        B, S, D = x.shape
        H = c.heads
        Dh = D // H
        dtype = jnp.float16 if c.fp16 else x.dtype
        x = x.astype(dtype)

        def ln(t, p):
            flat = t.reshape(-1, D)
            return layer_norm(flat, p["scale"], p["bias"],
                              eps=c.layer_norm_eps).reshape(t.shape)

        if (c.training and rng is None
                and (c.attn_dropout_ratio > 0 or c.hidden_dropout_ratio > 0)):
            raise ValueError(
                "DeepSpeedTransformerLayer: dropout is configured "
                f"(attn={c.attn_dropout_ratio}, hidden={c.hidden_dropout_ratio}) "
                "but no rng was passed to apply(); pass rng= or zero the "
                "ratios — silently training without dropout would diverge "
                "from the reference layer")

        def drop(t, key, rate):
            if not c.training or rate <= 0.0 or key is None:
                return t
            keep = jax.random.bernoulli(key, 1.0 - rate, t.shape)
            return jnp.where(keep, t / (1.0 - rate), jnp.zeros((), t.dtype))

        k_attn = k_probs = k_mlp = None
        if rng is not None:
            k_attn, k_probs, k_mlp = jax.random.split(rng, 3)

        h = ln(x, params["attn_norm"]) if c.pre_layer_norm else x
        qkv = h @ params["attn"]["wqkv"].astype(dtype) + params["attn"]["bqkv"].astype(dtype)
        q, kk, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        use_probs_drop = (c.training and c.attn_dropout_ratio > 0
                          and k_probs is not None)
        if attention_mask is not None or use_probs_drop:
            # dense path: additive-bias attention (BERT-style pad masking)
            # and/or attention-probability dropout (the flash kernel has no
            # dropout hook; the reference CUDA layer drops probs here too)
            from deepspeed_tpu.ops.pallas import mha_reference

            bias = None
            if attention_mask is not None:
                m = jnp.asarray(attention_mask)
                bias = (jnp.where(m[:, None, None, :] > 0, 0.0, -1e30)
                        if m.ndim == 2 else m)
            pt = ((lambda p: drop(p, k_probs, c.attn_dropout_ratio))
                  if use_probs_drop else None)
            o = mha_reference(to_heads(q), to_heads(kk), to_heads(v),
                              causal=False, bias=bias, probs_transform=pt,
                              pv_dtype=dtype)  # MXU-rate probs@V
        else:
            o = flash_attention(to_heads(q), to_heads(kk), to_heads(v),
                                causal=False)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, D)
        o = o @ params["attn"]["wo"].astype(dtype) + params["attn"]["bo"].astype(dtype)
        o = drop(o, k_attn, c.hidden_dropout_ratio)
        x = x + o
        if not c.pre_layer_norm:
            x = ln(x, params["attn_norm"])

        h = ln(x, params["mlp_norm"]) if c.pre_layer_norm else x
        h = jax.nn.gelu(h @ params["mlp"]["w1"].astype(dtype)
                        + params["mlp"]["b1"].astype(dtype), approximate=True)
        h = h @ params["mlp"]["w2"].astype(dtype) + params["mlp"]["b2"].astype(dtype)
        h = drop(h, k_mlp, c.hidden_dropout_ratio)
        x = x + h
        if not c.pre_layer_norm:
            x = ln(x, params["mlp_norm"])
        return (x,) if c.return_tuple else x

    def __call__(self, params, x, **kw):
        return self.apply(params, x, **kw)
