"""AdamW with 8-bit blockwise-quantized optimizer states.

Role parity: the reference family of memory-reduced optimizer states
(``(R) csrc/quantization/*`` block quantization + DeepSpeed's quantized
optimizer-state configs); algorithmically this is the 8-bit Adam of
Dettmers et al. (bitsandbytes), re-built on the shared block quantizer
(ops/pallas/quantizer.py).  Purpose on TPU: optimizer states are the largest
persistent HBM tenant after the fp32 masters (8 bytes/param for fp32 m+v);
int8 blockwise states cut that to ~2 bytes/param, which is what lets a
>1B-param model train on one 16GB chip (BENCH r4 rung).

Design notes:
- ``m`` is quantized linearly (signed absmax int8 per block).
- ``v`` is quantized in **sqrt space** (stores ``sqrt(v)``): v spans many
  decades within a tensor; sqrt halves the dynamic range in log terms, so a
  127-level linear code loses far less.  Dequant squares it back.
- The update math runs in fp32 per block: dequant -> moment update ->
  bias-corrected AdamW direction -> requant.  XLA fuses dequant/requant into
  the elementwise chain, so the step stays bandwidth-bound on the int8
  reads/writes — the memory win is also a ~3x optimizer-step bandwidth win
  over fp32 states.
- Tensors smaller than ``min_quant_size`` keep fp32 moments (norms, biases:
  quantizing them saves nothing and costs precision — same escape hatch as
  bitsandbytes' ``min_8bit_size``).
- **Stochastic rounding** (``stochastic_rounding="auto"``): when params are
  bf16 there is no fp32 master, and deterministic round-to-nearest would
  drop any update smaller than ~2^-8 of the param — training stalls.  The
  update is computed in fp32 per block and rounded to bf16 *stochastically*
  (unbiased: E[round(x)] = x), the established recipe for master-free bf16
  training on TPUs.  fp32 params skip SR (the sum is already exact).
- The transformation returns **new params, not deltas** (``
  updates_are_new_params``): returning deltas would force a full fp32
  update tree (bf16 deltas under-round, fp32 deltas cost O(model) HBM);
  per-leaf new-params keeps every transient leaf-sized.  The engine checks
  the flag; ``optax.apply_updates`` must not be used with this optimizer.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax


class Adam8bitState(NamedTuple):
    count: jnp.ndarray
    m_q: Any        # int8 [nb, block] per leaf (or fp32 [n] for small leaves)
    m_scale: Any    # fp32 [nb] per leaf (or () placeholder)
    v_q: Any        # int8 [nb, block], sqrt-space (or fp32 [n])
    v_scale: Any


class NewParamsTransformation(NamedTuple):
    """optax-shaped transformation whose ``update`` returns the NEW params
    (the engine branches on ``updates_are_new_params``)."""

    init: Callable
    update: Callable
    updates_are_new_params: bool = True


def stochastic_round_bf16(x32: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """Unbiased fp32 -> bf16 rounding: add uniform noise below the truncated
    mantissa bits, then truncate.  Works in sign-magnitude space (the integer
    add only grows the magnitude bits; carries into the exponent produce the
    correctly-rounded next binade)."""
    bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    noise = jax.random.bits(key, x32.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(rounded, jnp.float32).astype(jnp.bfloat16)


def _block_quant(x2d: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[nb, B] fp32 -> (int8 [nb, B], fp32 [nb]) via the shared quantizer
    (already block-aligned, so pad is always 0)."""
    from deepspeed_tpu.ops.pallas.quantizer import quantize

    q, scale, _pad = quantize(x2d, bits=8, block=x2d.shape[-1], impl="xla")
    return q, scale


def _block_dequant(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    from deepspeed_tpu.ops.pallas.quantizer import dequantize

    return dequantize(q, scale, 0, q.shape, dtype=jnp.float32)


def adam8bit(learning_rate: Union[float, Callable] = 1e-3, b1: float = 0.9,
             b2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
             block: int = 512, min_quant_size: int = 4096,
             stochastic_rounding: Union[bool, str] = "auto",
             sr_seed: int = 0x5EED) -> NewParamsTransformation:
    """AdamW with int8 blockwise m/v.  ``update`` returns NEW params (see
    module docstring); weight decay is decoupled (AdamW-style).
    ``stochastic_rounding="auto"`` applies SR exactly to non-fp32 params."""

    # Per-leaf chunking: the fp32 temporaries of the update (dequantized
    # m/v, direction, new params) must never materialize for a whole big
    # leaf at once — a stacked-layers leaf of a >1B model is ~278M elements,
    # and ~6 fp32 temporaries of that size is ~7GB, which is what OOMs a
    # 16GB chip.  Big leaves are processed as a ``lax.map`` over chunks of
    # <= 2^25 elements; inputs stay in their storage dtype outside the
    # chunk body.
    chunk_target = 1 << 25

    def _quantized(p) -> bool:
        return int(np.prod(p.shape)) >= min_quant_size

    def _layout(p):
        n = int(np.prod(p.shape))
        split = max(1, -(-n // chunk_target))
        chunk = -(-(-(-n // split)) // block) * block  # ceil to block mult
        return n, split, chunk

    def init(params):
        def mk_q(p):
            if not _quantized(p):
                return jnp.zeros((int(np.prod(p.shape)),), jnp.float32)
            _, split, chunk = _layout(p)
            return jnp.zeros((split * chunk // block, block), jnp.int8)

        def mk_s(p):
            if not _quantized(p):
                return jnp.zeros((), jnp.float32)
            _, split, chunk = _layout(p)
            return jnp.ones((split * chunk // block,), jnp.float32)

        return Adam8bitState(
            count=jnp.zeros((), jnp.int32),
            m_q=jax.tree.map(mk_q, params), m_scale=jax.tree.map(mk_s, params),
            v_q=jax.tree.map(mk_q, params), v_scale=jax.tree.map(mk_s, params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("adam8bit requires params (for weight decay)")
        lr = learning_rate(state.count) if callable(learning_rate) else learning_rate
        count = state.count + 1
        c1 = 1.0 - b1 ** count.astype(jnp.float32)
        c2 = 1.0 - b2 ** count.astype(jnp.float32)

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_mq = treedef.flatten_up_to(state.m_q)
        flat_ms = treedef.flatten_up_to(state.m_scale)
        flat_vq = treedef.flatten_up_to(state.v_q)
        flat_vs = treedef.flatten_up_to(state.v_scale)

        base_key = jax.random.fold_in(jax.random.PRNGKey(sr_seed),
                                      state.count)

        new_p, n_mq, n_ms, n_vq, n_vs = [], [], [], [], []
        for i, (p, g, mq, ms, vq, vs) in enumerate(zip(
                flat_p, flat_g, flat_mq, flat_ms, flat_vq, flat_vs)):
            n = int(np.prod(p.shape))
            # SR only ever applies to bf16 params (it IS bf16 rounding);
            # True and "auto" are equivalent there, and fp32 params skip it
            # because their update sum is already exact.
            use_sr = (stochastic_rounding in (True, "auto")
                      and p.dtype == jnp.bfloat16)

            if _quantized(p):
                _, split, chunk = _layout(p)
                n_pad = split * chunk
                bpc = chunk // block              # blocks per chunk

                def pad_flat(x):  # keep storage dtype: no fp32 full copy
                    flat = x.reshape(-1)
                    return jnp.pad(flat, (0, n_pad - n)).reshape(split, chunk)

                g_c = pad_flat(g)
                p_c = pad_flat(p)
                keys = jax.random.split(jax.random.fold_in(base_key, i), split)

                def chunk_fn(xs):
                    gc, pc, mqc, msc, vqc, vsc, key = xs
                    g32 = gc.astype(jnp.float32).reshape(bpc, block)
                    m = _block_dequant(mqc, msc)
                    rv = _block_dequant(vqc, vsc)
                    v = rv * rv                   # sqrt-space storage
                    m = b1 * m + (1.0 - b1) * g32
                    v = b2 * v + (1.0 - b2) * g32 * g32
                    direction = (m / c1) / (jnp.sqrt(v / c2) + eps)
                    mq2, ms2 = _block_quant(m)
                    vq2, vs2 = _block_quant(jnp.sqrt(v))
                    p32 = pc.astype(jnp.float32)
                    new32 = (p32 - lr * (direction.reshape(-1)
                                         + weight_decay * p32))
                    if use_sr:
                        out = stochastic_round_bf16(new32, key)
                    else:
                        out = new32.astype(p.dtype)
                    return out, mq2, ms2, vq2, vs2

                xs = (g_c, p_c, mq.reshape(split, bpc, block),
                      ms.reshape(split, bpc), vq.reshape(split, bpc, block),
                      vs.reshape(split, bpc), keys)
                if split == 1:  # no loop: fuses flat, compiles faster
                    res = chunk_fn(jax.tree.map(lambda a: a[0], xs))
                    out, mq2, ms2, vq2, vs2 = jax.tree.map(
                        lambda a: a[None], res)
                else:
                    out, mq2, ms2, vq2, vs2 = jax.lax.map(chunk_fn, xs)
                new_p.append(out.reshape(-1)[:n].reshape(p.shape))
                n_mq.append(mq2.reshape(-1, block))
                n_ms.append(ms2.reshape(-1))
                n_vq.append(vq2.reshape(-1, block))
                n_vs.append(vs2.reshape(-1))
            else:
                g32 = g.astype(jnp.float32).reshape(-1)
                m = b1 * mq + (1.0 - b1) * g32
                v = b2 * (vq * vq) + (1.0 - b2) * g32 * g32
                direction = (m / c1) / (jnp.sqrt(v / c2) + eps)
                p32 = p.astype(jnp.float32)
                new32 = p32 - lr * (direction.reshape(p.shape)
                                    + weight_decay * p32)
                if use_sr:
                    new_p.append(stochastic_round_bf16(
                        new32, jax.random.fold_in(base_key, i)))
                else:
                    new_p.append(new32.astype(p.dtype))
                n_mq.append(m); n_ms.append(jnp.zeros((), jnp.float32))
                n_vq.append(jnp.sqrt(v)); n_vs.append(jnp.zeros((), jnp.float32))

        unflat = treedef.unflatten
        return (unflat(new_p), Adam8bitState(
            count=count, m_q=unflat(n_mq), m_scale=unflat(n_ms),
            v_q=unflat(n_vq), v_scale=unflat(n_vs)))

    return NewParamsTransformation(init, update)
