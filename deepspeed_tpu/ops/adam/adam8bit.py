"""AdamW with 8-bit blockwise-quantized optimizer states.

Role parity: the reference family of memory-reduced optimizer states
(``(R) csrc/quantization/*`` block quantization + DeepSpeed's quantized
optimizer-state configs); algorithmically this is the 8-bit Adam of
Dettmers et al. (bitsandbytes), re-built on the shared block quantizer
(ops/pallas/quantizer.py).  Purpose on TPU: optimizer states are the largest
persistent HBM tenant after the fp32 masters (8 bytes/param for fp32 m+v);
int8 blockwise states cut that to ~2 bytes/param, which is what lets a
>1B-param model train on one 16GB chip (BENCH r4 rung).

Design notes:
- ``m`` is quantized linearly (signed absmax int8 per block).
- ``v`` is quantized in **sqrt space** (stores ``sqrt(v)``): v spans many
  decades within a tensor; sqrt halves the dynamic range in log terms, so a
  127-level linear code loses far less.  Dequant squares it back.
- The update math runs in fp32 per block: dequant -> moment update ->
  bias-corrected AdamW direction -> requant.  XLA fuses dequant/requant into
  the elementwise chain, so the step stays bandwidth-bound on the int8
  reads/writes — the memory win is also a ~3x optimizer-step bandwidth win
  over fp32 states.
- Tensors smaller than ``min_quant_size`` keep fp32 moments (norms, biases:
  quantizing them saves nothing and costs precision — same escape hatch as
  bitsandbytes' ``min_8bit_size``).
- **Stochastic rounding** (``stochastic_rounding="auto"``): when params are
  bf16 there is no fp32 master, and deterministic round-to-nearest would
  drop any update smaller than ~2^-8 of the param — training stalls.  The
  update is computed in fp32 per block and rounded to bf16 *stochastically*
  (unbiased: E[round(x)] = x), the established recipe for master-free bf16
  training on TPUs.  fp32 params skip SR (the sum is already exact).
- The transformation returns **new params, not deltas** (``
  updates_are_new_params``): returning deltas would force a full fp32
  update tree (bf16 deltas under-round, fp32 deltas cost O(model) HBM);
  per-leaf new-params keeps every transient leaf-sized.  The engine checks
  the flag; ``optax.apply_updates`` must not be used with this optimizer.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax


class Adam8bitState(NamedTuple):
    count: jnp.ndarray
    m_q: Any        # int8 [nb, block] per leaf (or fp32 [n] for small leaves);
    #                 nb is padded to the kernel row tile (ROW_MULT)
    m_scale: Any    # fp32 [nb, 1] per leaf (or () placeholder)
    v_q: Any        # int8 [nb, block], sqrt-space (or fp32 [n])
    v_scale: Any


class NewParamsTransformation(NamedTuple):
    """optax-shaped transformation whose ``update`` returns the NEW params
    (the engine branches on ``updates_are_new_params``)."""

    init: Callable
    update: Callable
    updates_are_new_params: bool = True


def stochastic_round_bf16(x32: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """Unbiased fp32 -> bf16 rounding: add uniform noise below the truncated
    mantissa bits, then truncate.  Works in sign-magnitude space (the integer
    add only grows the magnitude bits; carries into the exponent produce the
    correctly-rounded next binade)."""
    bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    noise = jax.random.bits(key, x32.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(rounded, jnp.float32).astype(jnp.bfloat16)


def adam8bit(learning_rate: Union[float, Callable] = 1e-3, b1: float = 0.9,
             b2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
             block: int = 512, min_quant_size: int = 4096,
             stochastic_rounding: Union[bool, str] = "auto",
             sr_seed: int = 0x5EED) -> NewParamsTransformation:
    """AdamW with int8 blockwise m/v.  ``update`` returns NEW params (see
    module docstring); weight decay is decoupled (AdamW-style).
    ``stochastic_rounding="auto"`` applies SR exactly to non-fp32 params."""

    # The update itself runs as ONE fused Pallas pass per leaf
    # (ops/pallas/fused_adam8bit.py): dequant -> moment update -> requant ->
    # stochastic round in VMEM tiles, so no whole-leaf fp32 temporary ever
    # materializes (a stacked-layers leaf of a >1B model is ~278M elements;
    # ~6 fp32 temporaries of that is ~7GB — an instant OOM on a 16GB chip).
    from deepspeed_tpu.ops.pallas.fused_adam8bit import ROW_MULT

    def _quantized(p) -> bool:
        return int(np.prod(p.shape)) >= min_quant_size

    def _nb(p) -> int:
        n = int(np.prod(p.shape))
        nb = -(-n // block)
        return -(-nb // ROW_MULT) * ROW_MULT  # kernel row-tile alignment

    def init(params):
        def mk_q(p):
            if not _quantized(p):
                return jnp.zeros((int(np.prod(p.shape)),), jnp.float32)
            return jnp.zeros((_nb(p), block), jnp.int8)

        def mk_s(p):
            if not _quantized(p):
                return jnp.zeros((), jnp.float32)
            return jnp.ones((_nb(p), 1), jnp.float32)

        return Adam8bitState(
            count=jnp.zeros((), jnp.int32),
            m_q=jax.tree.map(mk_q, params), m_scale=jax.tree.map(mk_s, params),
            v_q=jax.tree.map(mk_q, params), v_scale=jax.tree.map(mk_s, params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("adam8bit requires params (for weight decay)")
        from deepspeed_tpu.ops.pallas.fused_adam8bit import fused_adam8bit_update

        lr = learning_rate(state.count) if callable(learning_rate) else learning_rate
        count = state.count + 1
        countf = count.astype(jnp.float32)
        c1 = 1.0 - b1 ** countf          # small-leaf form: direction m / c1
        c2 = 1.0 - b2 ** countf
        c1k = 1.0 / c1                   # kernel form: m * c1k
        c2k = 1.0 / c2

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_mq = treedef.flatten_up_to(state.m_q)
        flat_ms = treedef.flatten_up_to(state.m_scale)
        flat_vq = treedef.flatten_up_to(state.v_q)
        flat_vs = treedef.flatten_up_to(state.v_scale)

        base_key = jax.random.fold_in(jax.random.PRNGKey(sr_seed),
                                      state.count)

        new_p, n_mq, n_ms, n_vq, n_vs = [], [], [], [], []
        for i, (p, g, mq, ms, vq, vs) in enumerate(zip(
                flat_p, flat_g, flat_mq, flat_ms, flat_vq, flat_vs)):
            n = int(np.prod(p.shape))
            # SR only ever applies to bf16 params (it IS bf16 rounding);
            # True and "auto" are equivalent there, and fp32 params skip it
            # because their update sum is already exact.
            use_sr = (stochastic_rounding in (True, "auto")
                      and p.dtype == jnp.bfloat16)

            if _quantized(p):
                nb = _nb(p)
                n_pad = nb * block

                def pad2d(x):  # keep storage dtype: no fp32 full copy
                    flat = x.reshape(-1)
                    return jnp.pad(flat, (0, n_pad - n)).reshape(nb, block)

                seed = count * jnp.int32(1000003) + jnp.int32(i * 7919)
                out, mq2, ms2, vq2, vs2 = fused_adam8bit_update(
                    pad2d(p), pad2d(g), mq, ms, vq, vs, c1k, c2k, lr, seed,
                    b1=b1, b2=b2, eps=eps, wd=weight_decay, sr=use_sr)
                new_p.append(out.reshape(-1)[:n].reshape(p.shape))
                n_mq.append(mq2); n_ms.append(ms2)
                n_vq.append(vq2); n_vs.append(vs2)
            else:
                g32 = g.astype(jnp.float32).reshape(-1)
                m = b1 * mq + (1.0 - b1) * g32
                v = b2 * (vq * vq) + (1.0 - b2) * g32 * g32
                direction = (m / c1) / (jnp.sqrt(v / c2) + eps)
                p32 = p.astype(jnp.float32)
                new32 = p32 - lr * (direction.reshape(p.shape)
                                    + weight_decay * p32)
                if use_sr:
                    new_p.append(stochastic_round_bf16(
                        new32, jax.random.fold_in(base_key, i)))
                else:
                    new_p.append(new32.astype(p.dtype))
                n_mq.append(m); n_ms.append(jnp.zeros((), jnp.float32))
                n_vq.append(jnp.sqrt(v)); n_vs.append(jnp.zeros((), jnp.float32))

        unflat = treedef.unflatten
        return (unflat(new_p), Adam8bitState(
            count=count, m_q=unflat(n_mq), m_scale=unflat(n_ms),
            v_q=unflat(n_vq), v_scale=unflat(n_vs)))

    return NewParamsTransformation(init, update)
