"""FusedAdam as an optax transformation backed by the Pallas kernel.

Reference parity: ``deepspeed/ops/adam/fused_adam.py`` (SURVEY.md §2.1 "Ops:
Adam family") — same knobs (``adam_w_mode``, betas, eps, weight_decay); the
multi-tensor CUDA launch is replaced by per-leaf Pallas kernels that XLA
compiles into one fused program (see ops/pallas/fused_adam.py).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.ops.pallas.fused_adam import fused_adam_update


class FusedAdamState(NamedTuple):
    count: jnp.ndarray
    m: Any
    v: Any


def fused_adam(learning_rate: Union[float, Callable] = 1e-3, b1: float = 0.9,
               b2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
               adam_w_mode: bool = True, impl: Optional[str] = None) -> optax.GradientTransformation:
    """optax transformation whose update IS the new params delta.

    Note: unlike composed optax chains, the fused kernel computes new params
    directly; the returned "updates" are ``new_params - params`` so it stays a
    drop-in GradientTransformation.
    """

    def init(params):
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedAdamState(count=jnp.zeros((), jnp.int32), m=zeros,
                              v=jax.tree.map(jnp.copy, zeros))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        # Schedules are evaluated at the 0-based pre-increment count, matching
        # optax.scale_by_schedule, so "torch_adam": true stays a drop-in swap.
        lr = learning_rate(state.count) if callable(learning_rate) else learning_rate
        count = state.count + 1

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.m)
        flat_v = treedef.flatten_up_to(state.v)
        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            pn, mn, vn = fused_adam_update(p, g, m, v, count, lr=lr, beta1=b1, beta2=b2,
                                           eps=eps, weight_decay=weight_decay,
                                           adam_w_mode=adam_w_mode, impl=impl)
            new_p.append(pn)
            new_m.append(mn)
            new_v.append(vn)
        updates = jax.tree_util.tree_unflatten(
            treedef, [pn - p for pn, p in zip(new_p, flat_p)])
        return updates, FusedAdamState(count=count,
                                       m=jax.tree_util.tree_unflatten(treedef, new_m),
                                       v=jax.tree_util.tree_unflatten(treedef, new_v))

    return optax.GradientTransformation(init, update)


class FusedAdam:
    """Class-style constructor for reference API parity
    (``FusedAdam(params, lr=..., adam_w_mode=True)``)."""

    def __new__(cls, params=None, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                eps=1e-8, adam_w_mode=True, weight_decay=0.0, amsgrad=False,
                set_grad_none=True):
        if amsgrad:
            raise ValueError("FusedAdam does not support amsgrad (reference parity)")
        return fused_adam(learning_rate=lr, b1=betas[0], b2=betas[1], eps=eps,
                          weight_decay=weight_decay, adam_w_mode=adam_w_mode)
